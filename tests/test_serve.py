"""Inference serving subsystem (mxnet_tpu/serve/): bucketed micro-batch
engine, backpressure HTTP frontend, hot-swap registry, and the Predictor
satellites (dtype-honoring set_input, param-sharing reshape).

Acceptance (ISSUE 3): a warmed engine under 32 concurrent clients does
ZERO XLA compiles (telemetry compile counter flat), achieves mean batch
size > 1, and returns per-request outputs bitwise-identical to a
single-request Predictor.forward.
"""
import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry as tm
from mxnet_tpu.base import MXNetError
from mxnet_tpu.serve import (DeadlineExceededError, EngineClosedError,
                             InferenceEngine, ModelRegistry, QueueFullError,
                             ServeConfig, pad_axis0, pick_bucket,
                             power_of_two_buckets, serve_http, unpad_axis0)
from mxnet_tpu.serving import Predictor

FEATURE = 4
CLASSES = 3


def _model(tmp_path, scale=1.0, seed=0):
    """(symbol_json, param_bytes, w, b) for softmax(FC(data))."""
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=CLASSES, name="fc")
    sym = mx.sym.softmax(fc, name="prob")
    rng = np.random.RandomState(seed)
    w = (rng.randn(CLASSES, FEATURE) * scale).astype(np.float32)
    b = rng.randn(CLASSES).astype(np.float32)
    path = str(tmp_path / ("model_%s_%d.params" % (scale, seed)))
    mx.nd.save(path, {"arg:fc_weight": mx.nd.array(w),
                      "arg:fc_bias": mx.nd.array(b)})
    with open(path, "rb") as f:
        blob = f.read()
    return sym.tojson(), blob, w, b


def _fwd(pred, x):
    """One forward through a bound Predictor's executor."""
    outs = pred._exe.forward(is_train=False, data=x)
    return outs[0].asnumpy()


def _post(url, payload, timeout=30):
    req = urllib.request.Request(
        url + "/predict", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "{}"), dict(e.headers)


# ---------------------------------------------------------------------------
# batching primitives
# ---------------------------------------------------------------------------

def test_bucket_helpers():
    assert power_of_two_buckets(8) == (1, 2, 4, 8)
    assert power_of_two_buckets(1) == (1,)
    assert power_of_two_buckets(6) == (1, 2, 4, 6)
    assert pick_bucket(3, (1, 2, 4, 8)) == 4
    assert pick_bucket(4, (1, 2, 4, 8)) == 4
    assert pick_bucket(1, (1, 2, 4, 8)) == 1
    with pytest.raises(MXNetError):
        pick_bucket(9, (1, 2, 4, 8))


def test_bucket_spec_hardening():
    """Satellite: explicit bucket specs must be strictly increasing
    positive sizes — unsorted/duplicate/non-positive specs raise an
    MXNetError NAMING the spec instead of being silently normalized."""
    from mxnet_tpu.serve import parse_buckets, validate_buckets
    assert parse_buckets("1,4,16", 8) == (1, 4, 16)
    assert parse_buckets(" 1, 2 ,4 ", 8) == (1, 2, 4)
    assert parse_buckets("", 8) == (1, 2, 4, 8)
    for bad in ("16,4,8", "1,2,2,4", "0,1,2", "-1,2", "1,zap,4", ","):
        with pytest.raises(MXNetError) as ei:
            parse_buckets(bad, 8)
        assert repr(bad) in str(ei.value)   # names the offending spec
    # the same contract guards programmatic ladders (ServeConfig lists)
    with pytest.raises(MXNetError):
        validate_buckets([8, 2])
    with pytest.raises(MXNetError):
        validate_buckets([2, 2])
    with pytest.raises(MXNetError):
        validate_buckets([])
    with pytest.raises(MXNetError):
        ServeConfig(buckets=[4, 1])
    # pick_bucket beyond the ladder: explicit error naming the ladder
    with pytest.raises(MXNetError) as ei:
        pick_bucket(9, (1, 2, 4, 8))
    assert "(1, 2, 4, 8)" in str(ei.value)


def test_pad_unpad():
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    p = pad_axis0(x, 8)
    assert p.shape == (8, 4)
    assert np.array_equal(p[:3], x)
    assert not p[3:].any()
    assert np.array_equal(unpad_axis0(p, 3), x)
    assert pad_axis0(x, 3) is x
    with pytest.raises(MXNetError):
        pad_axis0(x, 2)


def test_padded_forward_bitwise_identical(tmp_path):
    """Satellite: real rows of a bucket-padded forward are BITWISE
    identical to an unpadded forward of the same rows."""
    sym_json, blob, _w, _b = _model(tmp_path)
    pred5 = Predictor(sym_json, blob, input_shapes={"data": (5, FEATURE)})
    pred8 = pred5.reshape({"data": (8, FEATURE)})
    x = np.random.RandomState(7).randn(5, FEATURE).astype(np.float32)
    out5 = _fwd(pred5, x)
    out8 = _fwd(pred8, pad_axis0(x, 8))
    assert unpad_axis0(out8, 5).tobytes() == out5.tobytes()


# ---------------------------------------------------------------------------
# Predictor satellites
# ---------------------------------------------------------------------------

def test_reshape_shares_device_param_buffers(tmp_path):
    """Satellite: reshape must not re-upload params host->device — the
    new bind aliases the SAME device-resident buffers."""
    sym_json, blob, w, _b = _model(tmp_path)
    pred = Predictor(sym_json, blob, input_shapes={"data": (1, FEATURE)})
    pred4 = pred.reshape({"data": (4, FEATURE)})
    for name in ("fc_weight", "fc_bias"):
        assert pred4._exe.arg_dict[name] is pred._exe.arg_dict[name]
        assert pred4._exe.arg_dict[name]._data is \
            pred._exe.arg_dict[name]._data
    # inputs are NOT shared (different shape, per-bind buffers)
    assert pred4._exe.arg_dict["data"] is not pred._exe.arg_dict["data"]
    # and the shared-param executor still computes correctly
    x = np.random.RandomState(3).randn(4, FEATURE).astype(np.float32)
    out = _fwd(pred4, x)
    logits = x @ w.T + _b_of(pred)
    e = np.exp(logits - logits.max(axis=1, keepdims=True))
    np.testing.assert_allclose(out, e / e.sum(axis=1, keepdims=True),
                               rtol=1e-5, atol=1e-6)


def _b_of(pred):
    return pred._exe.arg_dict["fc_bias"].asnumpy()


def test_set_input_honors_bound_dtype(tmp_path):
    """Satellite: set_input reads bytes in the bound array's dtype (not
    hard-coded <f4) and validates the byte length."""
    sym_json, blob, _w, _b = _model(tmp_path)
    rng = np.random.RandomState(11)
    x16 = rng.randn(2, FEATURE).astype(np.float16)

    p16 = Predictor(sym_json, blob, input_shapes={"data": (2, FEATURE)},
                    input_types={"data": np.float16})
    assert p16._exe.arg_dict["data"].dtype == np.float16
    p16.set_input("data", x16.tobytes())          # 16 bytes of fp16
    assert np.array_equal(p16._exe.arg_dict["data"].asnumpy(), x16)
    p16.forward()
    out16 = p16.get_output(0)

    # same values through the default f4 predictor: results agree to
    # fp16 precision (so the fp16 bytes really were interpreted as fp16)
    p32 = Predictor(sym_json, blob, input_shapes={"data": (2, FEATURE)})
    p32.set_input("data", x16.astype("<f4").tobytes())
    p32.forward()
    out32 = p32.get_output(0)
    np.testing.assert_allclose(np.frombuffer(out16, "<f4"),
                               np.frombuffer(out32, "<f4"),
                               rtol=2e-2, atol=2e-3)

    # byte-length validation names the mismatch
    with pytest.raises(MXNetError, match="bytes"):
        p32.set_input("data", x16.tobytes())      # fp16 bytes into an f4 bind
    with pytest.raises(MXNetError, match="bytes"):
        p16.set_input("data", x16.astype("<f4").tobytes())


def test_set_input_int_roundtrip(tmp_path):
    sym_json, blob, _w, _b = _model(tmp_path)
    p = Predictor(sym_json, blob, input_shapes={"data": (2, FEATURE)},
                  input_types={"data": np.int32})
    xi = np.arange(2 * FEATURE, dtype="<i4").reshape(2, FEATURE)
    p.set_input("data", xi.tobytes())
    assert np.array_equal(p._exe.arg_dict["data"].asnumpy(), xi)


# ---------------------------------------------------------------------------
# engine: the ISSUE acceptance criterion
# ---------------------------------------------------------------------------

def test_engine_32_clients_zero_compiles_batched_bitwise(tmp_path):
    """32 concurrent clients through a warmed engine: compile counter
    flat, mean batch size > 1, outputs bitwise-identical to
    single-request Predictor.forward."""
    sym_json, blob, _w, _b = _model(tmp_path)
    pred = Predictor(sym_json, blob, input_shapes={"data": (1, FEATURE)})
    cfg = ServeConfig(max_batch=8, queue_depth=128, batch_wait_ms=25,
                      default_timeout_ms=30000, workers=1)
    eng = InferenceEngine(pred, cfg).start().warmup()
    assert eng.ready

    # per-request row counts cycle 1..4; precompute the single-request
    # reference outputs (their own compiles land BEFORE the snapshot)
    refs = {r: pred.reshape({"data": (r, FEATURE)}) for r in (1, 2, 3, 4)}
    cases, expected = {}, {}
    for i in range(32):
        rng = np.random.RandomState(1000 + i)
        for j in range(2):
            r = (i + j) % 4 + 1
            x = rng.randn(r, FEATURE).astype(np.float32)
            cases[(i, j)] = x
            expected[(i, j)] = _fwd(refs[r], x)

    batches0 = tm.counter("serving/batches_total").value
    rows_h = tm.histogram("serving/batch_rows")._default()
    rows0, nbatch0 = rows_h.sum, rows_h.count
    compiles0 = tm.snapshot()["backend_compile_total"]

    results, errors = {}, []
    barrier = threading.Barrier(32)

    def client(i):
        try:
            barrier.wait()
            for j in range(2):
                results[(i, j)] = eng.predict({"data": cases[(i, j)]})[0]
        except Exception as e:           # pragma: no cover - diagnostic
            errors.append((i, e))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(32)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    eng.close(drain=True)

    assert not errors, errors
    # 1) zero XLA compiles after warmup
    assert tm.snapshot()["backend_compile_total"] == compiles0
    # 2) requests actually coalesced: mean batch size > 1
    nbatch = rows_h.count - nbatch0
    assert tm.counter("serving/batches_total").value > batches0
    assert nbatch >= 1
    mean_rows = (rows_h.sum - rows0) / nbatch
    assert mean_rows > 1.0, "no coalescing happened (mean=%s)" % mean_rows
    # 3) bitwise identity vs single-request forwards
    assert set(results) == set(expected)
    for key in expected:
        assert results[key].tobytes() == expected[key].tobytes(), key


def test_engine_feed_validation(tmp_path):
    sym_json, blob, _w, _b = _model(tmp_path)
    pred = Predictor(sym_json, blob, input_shapes={"data": (1, FEATURE)})
    eng = InferenceEngine(pred, ServeConfig(max_batch=4, batch_wait_ms=0))
    with pytest.raises(MXNetError, match="feature shape"):
        eng.submit({"data": np.zeros((1, FEATURE + 1), np.float32)})
    with pytest.raises(MXNetError, match="max_batch"):
        eng.submit({"data": np.zeros((5, FEATURE), np.float32)})
    with pytest.raises(MXNetError, match="missing"):
        eng.submit({"wrong": np.zeros((1, FEATURE), np.float32)})
    # a bare row without the batch axis is accepted as rows=1
    req = eng.submit(np.zeros((FEATURE,), np.float32))
    assert req.rows == 1


def test_engine_admission_control_and_drain(tmp_path):
    """Full queue rejects immediately; drain flushes everything queued;
    post-drain submits are refused."""
    sym_json, blob, _w, _b = _model(tmp_path)
    pred = Predictor(sym_json, blob, input_shapes={"data": (1, FEATURE)})
    cfg = ServeConfig(max_batch=2, queue_depth=3, batch_wait_ms=0,
                      default_timeout_ms=0)
    eng = InferenceEngine(pred, cfg)     # workers NOT started yet
    rejected0 = tm.counter("serving/rejected_total").value
    reqs = [eng.submit({"data": np.full((1, FEATURE), i, np.float32)})
            for i in range(3)]
    with pytest.raises(QueueFullError):
        eng.submit({"data": np.zeros((1, FEATURE), np.float32)})
    assert tm.counter("serving/rejected_total").value == rejected0 + 1
    assert tm.gauge("serving/queue_depth").value == 3

    eng.start()
    eng.close(drain=True)                # graceful: flush, then stop
    for i, req in enumerate(reqs):
        out = req.result()               # all three answered
        assert out[0].shape == (1, CLASSES)
    assert tm.gauge("serving/queue_depth").value == 0
    with pytest.raises(EngineClosedError):
        eng.submit({"data": np.zeros((1, FEATURE), np.float32)})


def test_engine_deadline_expiry(tmp_path):
    sym_json, blob, _w, _b = _model(tmp_path)
    pred = Predictor(sym_json, blob, input_shapes={"data": (1, FEATURE)})
    eng = InferenceEngine(pred, ServeConfig(max_batch=2, batch_wait_ms=0))
    timeouts0 = tm.counter("serving/timeouts_total").value
    # no workers: the request can only expire
    req = eng.submit({"data": np.zeros((1, FEATURE), np.float32)},
                     timeout_ms=80)
    with pytest.raises(DeadlineExceededError):
        req.result()
    assert tm.counter("serving/timeouts_total").value == timeouts0 + 1
    # a worker starting later fails the expired request, not compute it
    eng.start()
    eng.close(drain=True)
    assert isinstance(req.error, DeadlineExceededError) or req.error is None


# ---------------------------------------------------------------------------
# HTTP frontend
# ---------------------------------------------------------------------------

def test_http_concurrent_no_lost_or_duplicated(tmp_path):
    """8 threads x 4 requests with unique payloads: every response is
    200 and carries ITS request's output (bitwise vs the single-request
    reference) — no losses, no cross-request mixups."""
    sym_json, blob, _w, _b = _model(tmp_path)
    pred = Predictor(sym_json, blob, input_shapes={"data": (1, FEATURE)})
    cfg = ServeConfig(max_batch=8, queue_depth=64, batch_wait_ms=10,
                      default_timeout_ms=30000)
    eng = InferenceEngine(pred, cfg).start().warmup()
    ref1 = pred.reshape({"data": (1, FEATURE)})
    cases = {}
    for i in range(8):
        rng = np.random.RandomState(500 + i)
        for j in range(4):
            cases[(i, j)] = rng.randn(1, FEATURE).astype(np.float32)
    expected = {k: _fwd(ref1, v) for k, v in cases.items()}

    srv = serve_http(eng, port=0)
    statuses, outputs, errors = {}, {}, []

    def client(i):
        try:
            for j in range(4):
                code, body, _h = _post(
                    srv.url, {"inputs": {"data": cases[(i, j)].tolist()}})
                statuses[(i, j)] = code
                if code == 200:
                    outputs[(i, j)] = np.asarray(body["outputs"][0],
                                                 np.float32)
        except Exception as e:           # pragma: no cover - diagnostic
            errors.append(e)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    srv.close()
    eng.close()

    assert not errors, errors
    assert set(statuses) == set(cases)
    assert all(c == 200 for c in statuses.values()), statuses
    for key in cases:                    # float32 survives JSON exactly
        assert outputs[key].tobytes() == expected[key].tobytes(), key


def test_http_healthz_gate(tmp_path):
    """/healthz is 503 until BOTH warmup compiled every bucket and
    workers are live — a warmed engine nobody started must not attract
    load-balancer traffic."""
    sym_json, blob, _w, _b = _model(tmp_path)
    pred = Predictor(sym_json, blob, input_shapes={"data": (1, FEATURE)})
    cfg = ServeConfig(max_batch=2, queue_depth=2, batch_wait_ms=0,
                      default_timeout_ms=0)
    eng = InferenceEngine(pred, cfg)
    srv = serve_http(eng, port=0)

    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(srv.url + "/healthz", timeout=5)
    assert ei.value.code == 503          # neither warmed nor started
    eng.warmup()
    assert not eng.ready                 # warmed but no workers
    eng.start()
    r = urllib.request.urlopen(srv.url + "/healthz", timeout=5)
    assert r.status == 200 and r.read() == b"ok\n"

    # /metrics serves the shared registry
    body = urllib.request.urlopen(srv.url + "/metrics", timeout=5).read()
    assert b"mxnet_serving_queue_depth" in body
    eng.close()
    assert not eng.ready                 # closed -> unhealthy again
    srv.close()


def test_http_backpressure_and_deadline(tmp_path):
    sym_json, blob, _w, _b = _model(tmp_path)
    pred = Predictor(sym_json, blob, input_shapes={"data": (1, FEATURE)})
    cfg = ServeConfig(max_batch=2, queue_depth=2, batch_wait_ms=0,
                      default_timeout_ms=0)
    eng = InferenceEngine(pred, cfg)     # workers never started: queued
    srv = serve_http(eng, port=0)        # requests model saturation
    x = [[0.0] * FEATURE]

    # backpressure: fill the queue, then 503
    eng.submit({"data": np.zeros((1, FEATURE), np.float32)})
    eng.submit({"data": np.zeros((1, FEATURE), np.float32)})
    code, payload, headers = _post(srv.url, x)
    assert code == 503
    assert "error" in payload
    assert headers.get("Retry-After") == "1"

    # malformed input: 400, not a hung connection
    code, payload, _h = _post(srv.url, {"inputs": {"bogus": x}})
    assert code == 400
    # ragged arrays and non-numeric timeouts are client errors too
    code, _p, _h = _post(srv.url, {"inputs": {"data": [[1.0], [1.0, 2.0]]}})
    assert code == 400
    code, _p, _h = _post(srv.url, {"inputs": {"data": x},
                                   "timeout_ms": "fast"})
    assert code == 400

    # deadline: queued behind a stopped worker -> 504 within ~timeout
    eng.close(drain=False)               # flush the fillers
    eng._accepting = True                # reopen admission, still no worker
    code, payload, _h = _post(
        srv.url, {"inputs": {"data": x}, "timeout_ms": 120})
    assert code == 504
    srv.close()
    eng.close(drain=False)


# ---------------------------------------------------------------------------
# hot swap
# ---------------------------------------------------------------------------

def test_registry_hot_swap_zero_dropped(tmp_path):
    """Weights rotate under live traffic: every request succeeds and
    returns exactly the old or the new model's output."""
    sym_json, blob_a, w_a, b_a = _model(tmp_path, scale=1.0)
    _json_b, blob_b, w_b, b_b = _model(tmp_path, scale=-2.0, seed=1)
    cfg = ServeConfig(max_batch=4, queue_depth=64, batch_wait_ms=1,
                      default_timeout_ms=30000)
    reg = ModelRegistry(sym_json, blob_a, {"data": (1, FEATURE)},
                        config=cfg)
    reg.warmup()
    x = np.random.RandomState(9).randn(1, FEATURE).astype(np.float32)
    out_a = reg.predict({"data": x})[0]

    swaps0 = tm.counter("serving/swaps_total").value
    stop = threading.Event()
    seen, errors = [], []

    def traffic():
        while not stop.is_set():
            try:
                seen.append(reg.predict({"data": x})[0])
            except Exception as e:       # pragma: no cover - diagnostic
                errors.append(e)

    threads = [threading.Thread(target=traffic) for _ in range(4)]
    for t in threads:
        t.start()
    old_engine = reg.engine()
    reg.swap(blob_b)
    stop.set()
    for t in threads:
        t.join()

    out_b = reg.predict({"data": x})[0]
    assert not errors, errors
    assert seen, "no traffic flowed during the swap"
    assert not np.array_equal(out_a, out_b)
    a_bytes, b_bytes = out_a.tobytes(), out_b.tobytes()
    for out in seen:                     # old weights or new, never junk
        assert out.tobytes() in (a_bytes, b_bytes)
    assert tm.counter("serving/swaps_total").value == swaps0 + 1
    assert reg.engine() is not old_engine
    assert not old_engine._workers      # old engine drained + joined
    assert reg.ready
    reg.close()


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------

def test_serve_config_env_tier(monkeypatch):
    monkeypatch.setenv("MXNET_SERVE_MAX_BATCH", "4")
    monkeypatch.setenv("MXNET_SERVE_QUEUE_DEPTH", "7")
    monkeypatch.setenv("MXNET_SERVE_BATCH_WAIT_MS", "9")
    monkeypatch.setenv("MXNET_SERVE_DEADLINE_MS", "1234")
    monkeypatch.setenv("MXNET_SERVE_WORKERS", "3")
    cfg = ServeConfig()
    assert cfg.buckets == (1, 2, 4)
    assert cfg.max_batch == 4
    assert cfg.queue_depth == 7
    assert abs(cfg.batch_wait - 0.009) < 1e-9
    assert abs(cfg.default_timeout - 1.234) < 1e-9
    assert cfg.workers == 3
    monkeypatch.setenv("MXNET_SERVE_BUCKETS", "1,3,6")
    cfg = ServeConfig()
    assert cfg.buckets == (1, 3, 6)
    assert cfg.max_batch == 6            # ladder caps request size
    # constructor overrides beat the env tier
    cfg = ServeConfig(max_batch=16, queue_depth=2)
    assert cfg.buckets == (1, 3, 6)      # env spec still wins buckets
    cfg = ServeConfig(max_batch=16, buckets="", queue_depth=2)
    assert cfg.buckets == (1, 2, 4, 8, 16)
    assert cfg.queue_depth == 2


def test_snapshot_carries_serving_fields(tmp_path):
    sym_json, blob, _w, _b = _model(tmp_path)
    pred = Predictor(sym_json, blob, input_shapes={"data": (1, FEATURE)})
    eng = InferenceEngine(pred, ServeConfig(max_batch=2, batch_wait_ms=0,
                                            default_timeout_ms=0)).start()
    eng.predict({"data": np.zeros((1, FEATURE), np.float32)})
    eng.close()
    snap = tm.snapshot()
    for key in ("serve_requests", "serve_rejected", "serve_timeouts",
                "serve_batches", "serve_swaps"):
        assert key in snap
    assert snap["serve_requests"] >= 1
    assert snap["serve_batches"] >= 1
    assert "serve_mean_batch_rows" in snap
    assert "serve_mean_padding_waste" in snap
