"""jax version compatibility for the parallelism stack.

One definition of ``shard_map`` for every module in this package:
jax >= 0.5 exposes it as public API with varying-manual-axes (vma)
replication tracking; jax 0.4.x has it under ``jax.experimental`` with
the older ``check_rep`` checker, which lacks rules for ``pallas_call``
and the ring collectives used here — so on 0.4.x the wrapper maps any
``check_vma`` argument away and disables ``check_rep``.
"""
from __future__ import annotations

__all__ = ["shard_map"]

try:                                  # jax >= 0.5: public API
    from jax import shard_map
except ImportError:                   # jax 0.4.x: experimental namespace
    import functools as _ft
    from jax.experimental.shard_map import shard_map as _shard_map_04

    @_ft.wraps(_shard_map_04)
    def shard_map(*args, **kwargs):
        kwargs.pop("check_vma", None)
        kwargs.setdefault("check_rep", False)
        return _shard_map_04(*args, **kwargs)
