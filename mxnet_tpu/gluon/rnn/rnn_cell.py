"""Gluon recurrent cells.

Reference: python/mxnet/gluon/rnn/rnn_cell.py (RecurrentCell, RNNCell,
LSTMCell, GRUCell, SequentialRNNCell, DropoutCell, ZoneoutCell,
ResidualCell, BidirectionalCell).

TPU note: ``unroll`` builds an explicitly unrolled graph (fine under jit
for short T); the fused ``rnn_layer`` classes use the scan-based RNN op
for long sequences.
"""
from __future__ import annotations

from ..block import Block, HybridBlock
from ..parameter import Parameter
from ..nn.basic_layers import _init

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "HybridSequentialRNNCell",
           "DropoutCell", "ModifierCell", "ZoneoutCell", "ResidualCell",
           "BidirectionalCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _format_sequence(length, inputs, layout, merge):
    """Normalize sequence input to (list-or-tensor, time_axis, batch)
    (reference: rnn_cell.py _format_sequence)."""
    from ... import ndarray as nd
    from ...ndarray.ndarray import NDArray
    assert layout in ("NTC", "TNC")
    axis = layout.find("T")
    batch_axis = layout.find("N")
    if isinstance(inputs, NDArray):
        batch_size = inputs.shape[batch_axis]
        if merge is False:
            if length is None:
                length = inputs.shape[axis]
            inputs = [x.squeeze(axis=axis) for x in
                      nd.SliceChannel(inputs, num_outputs=length, axis=axis,
                                      squeeze_axis=False)]
    else:
        assert length is None or len(inputs) == length
        batch_size = inputs[0].shape[0]   # per-step arrays are (N, C)
        if merge is True:
            inputs = _stack(inputs, axis)
    return inputs, axis, batch_size


def _stack(arrays, axis):
    from ... import ndarray as nd
    return nd.stack(*arrays, axis=axis)


def _mask_sequence_variable_length(F, data, length, valid_length, time_axis,
                                   merge):
    assert valid_length is not None
    if not isinstance(data, (list, tuple)):
        data = [data[i] if False else d for i, d in enumerate(data)]
    outputs = F.SequenceMask(_stack(data, time_axis), valid_length,
                             use_sequence_length=True, axis=time_axis)
    if not merge:
        outputs = [x.squeeze(axis=time_axis) for x in
                   F.SliceChannel(outputs, num_outputs=len(data),
                                  axis=time_axis, squeeze_axis=False)]
    return outputs


class RecurrentCell(Block):
    """Base class for recurrent cells (reference: rnn_cell.py:85)."""

    def __init__(self, prefix=None, params=None):
        super(RecurrentCell, self).__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial states (reference: rnn_cell.py begin_state)."""
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called"
        from ... import ndarray as nd
        if func is None:
            func = nd.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            info = dict(info)
            shape = info.pop("shape")
            info.pop("__layout__", None)
            states.append(func(shape=shape, **{**kwargs, **info})
                          if "dtype" in info else func(shape=shape, **kwargs))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll the cell over ``length`` timesteps
        (reference: rnn_cell.py unroll)."""
        from ... import ndarray as F
        self.reset()
        inputs, axis, batch_size = _format_sequence(length, inputs, layout,
                                                    False)
        begin_state = begin_state if begin_state is not None else \
            self.begin_state(batch_size=batch_size)
        states = begin_state
        outputs = []
        all_states = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
            if valid_length is not None:
                all_states.append(states)
        if valid_length is not None:
            states = [_stack(ele_list, 0) for ele_list in zip(*all_states)]
            states = [F.SequenceLast(s, valid_length,
                                     use_sequence_length=True, axis=0)
                      for s in states]
            outputs = _mask_sequence_variable_length(
                F, outputs, length, valid_length, axis, True)
            if merge_outputs is False:
                outputs = [x.squeeze(axis=axis) for x in
                           F.SliceChannel(outputs, num_outputs=length,
                                          axis=axis, squeeze_axis=False)]
        elif merge_outputs:
            outputs = _stack(outputs, axis)
        return outputs, states

    def _forward_cell(self, inputs, states):
        raise NotImplementedError

    def forward(self, inputs, states):
        return self._forward_cell(inputs, states)

    def __call__(self, inputs, states):
        self._counter += 1
        for hook in self._forward_pre_hooks:
            hook(self, (inputs, states))
        out = self._forward_cell(inputs, states)
        for hook in self._forward_hooks:
            hook(self, (inputs, states), out)
        return out


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    """Recurrent cell with hybrid_forward(F, x, states, **params)
    (reference: rnn_cell.py HybridRecurrentCell)."""

    def __init__(self, prefix=None, params=None):
        super(HybridRecurrentCell, self).__init__(prefix=prefix,
                                                  params=params)

    def _forward_cell(self, inputs, states):
        from ... import ndarray as F
        from ..parameter import DeferredInitializationError
        try:
            params = {n: p.data() for n, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._finish_deferred((inputs, states))
            params = {n: p.data() for n, p in self._reg_params.items()}
        return self.hybrid_forward(F, inputs, states, **params)

    def _finish_deferred(self, args):
        inputs, _states = args
        self.infer_shape(inputs)
        for p in self.collect_params().values():
            if p._deferred_init is not None:
                p._finish_deferred_init()

    def hybrid_forward(self, F, x, states, **params):
        raise NotImplementedError


class RNNCell(HybridRecurrentCell):
    """Elman RNN cell: h' = act(W x + b_i + R h + b_h)
    (reference: rnn_cell.py RNNCell)."""

    def __init__(self, hidden_size, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super(RNNCell, self).__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(hidden_size, input_size),
            init=_init(i2h_weight_initializer), allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(hidden_size, hidden_size),
            init=_init(h2h_weight_initializer), allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(hidden_size,),
            init=_init(i2h_bias_initializer), allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(hidden_size,),
            init=_init(h2h_bias_initializer), allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def infer_shape(self, x):
        self.i2h_weight._set_shape_from((self._hidden_size, x.shape[-1]))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = F.Activation(i2h + h2h, act_type=self._activation)
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """LSTM cell (reference: rnn_cell.py LSTMCell; gate order i,f,c,o)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super(LSTMCell, self).__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=_init(i2h_weight_initializer), allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, hidden_size),
            init=_init(h2h_weight_initializer), allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,),
            init=_init(i2h_bias_initializer), allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,),
            init=_init(h2h_bias_initializer), allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def infer_shape(self, x):
        self.i2h_weight._set_shape_from((4 * self._hidden_size, x.shape[-1]))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slices = F.SliceChannel(gates, num_outputs=4, axis=-1)
        in_gate = F.sigmoid(slices[0])
        forget_gate = F.sigmoid(slices[1])
        in_transform = F.tanh(slices[2])
        out_gate = F.sigmoid(slices[3])
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * F.tanh(next_c)
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """GRU cell (reference: rnn_cell.py GRUCell; gate order r,z,n —
    cuDNN convention)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super(GRUCell, self).__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(3 * hidden_size, input_size),
            init=_init(i2h_weight_initializer), allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(3 * hidden_size, hidden_size),
            init=_init(h2h_weight_initializer), allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(3 * hidden_size,),
            init=_init(i2h_bias_initializer), allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(3 * hidden_size,),
            init=_init(h2h_bias_initializer), allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def infer_shape(self, x):
        self.i2h_weight._set_shape_from((3 * self._hidden_size, x.shape[-1]))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h_n = F.SliceChannel(i2h, num_outputs=3, axis=-1)
        h2h_r, h2h_z, h2h_n = F.SliceChannel(h2h, num_outputs=3, axis=-1)
        reset_gate = F.sigmoid(i2h_r + h2h_r)
        update_gate = F.sigmoid(i2h_z + h2h_z)
        next_h_tmp = F.tanh(i2h_n + reset_gate * h2h_n)
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stack cells sequentially (reference: rnn_cell.py
    SequentialRNNCell)."""

    def __init__(self, prefix=None, params=None):
        super(SequentialRNNCell, self).__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def _forward_cell(self, inputs, states):
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            cell_states = states[p:p + n]
            p += n
            inputs, cell_states = cell(inputs, cell_states)
            next_states.extend(cell_states)
        return inputs, next_states

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)


class HybridSequentialRNNCell(SequentialRNNCell):
    pass


class DropoutCell(HybridRecurrentCell):
    """Dropout on cell outputs (reference: rnn_cell.py DropoutCell)."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super(DropoutCell, self).__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ModifierCell(HybridRecurrentCell):
    """Base for cells wrapping another cell
    (reference: rnn_cell.py ModifierCell)."""

    def __init__(self, base_cell):
        assert not base_cell._modified, \
            "Cell %s is already modified." % base_cell.name
        base_cell._modified = True
        super(ModifierCell, self).__init__(prefix=base_cell.prefix + self._alias(),
                                           params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (reference: rnn_cell.py ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support zoneout. Apply ZoneoutCell " \
            "to the cells underneath instead."
        super(ZoneoutCell, self).__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super(ZoneoutCell, self).reset()
        self._prev_output = None

    def _forward_cell(self, inputs, states):
        from ... import ndarray as F
        cell = self.base_cell
        next_output, next_states = cell(inputs, states)
        p_outputs, p_states = self.zoneout_outputs, self.zoneout_states

        def mask(p, like):
            return F.Dropout(F.ones_like(like), p=p)

        prev_output = self._prev_output
        if prev_output is None:
            prev_output = F.zeros_like(next_output)
        output = F.where(mask(p_outputs, next_output), next_output,
                         prev_output) if p_outputs != 0.0 else next_output
        new_states = [F.where(mask(p_states, new_s), new_s, old_s)
                      for new_s, old_s in zip(next_states, states)] \
            if p_states != 0.0 else next_states
        self._prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    """Adds residual connection (reference: rnn_cell.py ResidualCell)."""

    def _alias(self):
        return "residual"

    def _forward_cell(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states


class BidirectionalCell(HybridRecurrentCell):
    """Run two cells over the sequence in both directions
    (reference: rnn_cell.py BidirectionalCell). Only usable via unroll."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super(BidirectionalCell, self).__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def _alias(self):
        return "bi"

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def _forward_cell(self, inputs, states):
        raise NotImplementedError(
            "BidirectionalCell cannot be stepped. Please use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as F
        self.reset()
        inputs, axis, batch_size = _format_sequence(length, inputs, layout,
                                                    False)
        reversed_inputs = list(reversed(inputs))
        begin_state = begin_state if begin_state is not None else \
            self.begin_state(batch_size=batch_size)
        states = begin_state
        l_cell, r_cell = self._children.values()
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=states[:len(l_cell.state_info())],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=reversed_inputs,
            begin_state=states[len(l_cell.state_info()):],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        if valid_length is not None:
            r_outputs = _mask_sequence_variable_length(
                F, list(reversed(r_outputs)), length, valid_length, axis,
                False)
        else:
            r_outputs = list(reversed(r_outputs))
        outputs = [F.Concat(l_o, r_o, dim=1)
                   for l_o, r_o in zip(l_outputs, r_outputs)]
        if merge_outputs:
            outputs = _stack(outputs, axis)
        states = l_states + r_states
        return outputs, states
