"""Learning-rate schedulers.

Reference: python/mxnet/lr_scheduler.py (Factor/MultiFactor/Poly/Cosine,
281 LoC). Schedulers are host-side scalar functions of num_update — they
never enter the compiled graph, so changing the lr does not retrigger XLA
compilation (lr is passed to the fused update ops as a traced scalar).
"""
from __future__ import annotations

import math
import logging

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler",
           "PolyScheduler", "CosineScheduler"]


class LRScheduler(object):
    """Base scheduler: maps num_update -> learning rate."""

    def __init__(self, base_lr=0.01, warmup_steps=0, warmup_begin_lr=0.0,
                 warmup_mode="linear"):
        self.base_lr = base_lr
        if warmup_steps < 0:
            raise ValueError("warmup_steps must be >= 0")
        self.warmup_steps = warmup_steps
        self.warmup_begin_lr = warmup_begin_lr
        self.warmup_final_lr = base_lr
        if warmup_mode not in ("linear", "constant"):
            raise ValueError("warmup_mode must be 'linear' or 'constant'")
        self.warmup_mode = warmup_mode

    def get_warmup_lr(self, num_update):
        assert num_update < self.warmup_steps
        if self.warmup_mode == "linear":
            inc = (self.warmup_final_lr - self.warmup_begin_lr) \
                * num_update / self.warmup_steps
            return self.warmup_begin_lr + inc
        return self.warmup_begin_lr

    def __call__(self, num_update):
        raise NotImplementedError()


class FactorScheduler(LRScheduler):
    """lr decays by ``factor`` once per ``step`` updates, floored at
    ``stop_factor_lr``. Computed in closed form from num_update — there is
    no incremental state to corrupt on checkpoint resume."""

    def __init__(self, step, factor=1.0, stop_factor_lr=1e-8, base_lr=0.01,
                 warmup_steps=0, warmup_begin_lr=0.0, warmup_mode="linear"):
        super().__init__(base_lr, warmup_steps, warmup_begin_lr, warmup_mode)
        if step < 1:
            raise ValueError("step must be a positive update count")
        if factor > 1.0:
            raise ValueError("a decay factor > 1 would grow the lr")
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr
        self._decays_logged = 0

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        decays = max(0, (num_update - 1) // self.step)
        lr = self.base_lr * (self.factor ** decays)
        floored = lr < self.stop_factor_lr
        if floored:
            lr = self.stop_factor_lr
        if decays != self._decays_logged:
            self._decays_logged = decays
            logging.info("lr schedule: update %d -> lr %.5e%s", num_update,
                         lr, " (floor reached; holding)" if floored else "")
        return lr


class MultiFactorScheduler(LRScheduler):
    """lr decays by ``factor`` after each milestone in ``step`` (an
    increasing list of update counts). Closed-form: the lr at update t is
    base_lr * factor^(milestones passed)."""

    def __init__(self, step, factor=1.0, base_lr=0.01, warmup_steps=0,
                 warmup_begin_lr=0.0, warmup_mode="linear"):
        super().__init__(base_lr, warmup_steps, warmup_begin_lr, warmup_mode)
        if not isinstance(step, list) or not step:
            raise ValueError("step must be a non-empty list of milestones")
        if any(s < 1 for s in step):
            raise ValueError("milestones must be positive update counts")
        if any(b <= a for a, b in zip(step, step[1:])):
            raise ValueError("milestones must be strictly increasing")
        if factor > 1.0:
            raise ValueError("a decay factor > 1 would grow the lr")
        self.step = step
        self.factor = factor
        self._decays_logged = 0

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        passed = sum(1 for s in self.step if num_update > s)
        lr = self.base_lr * (self.factor ** passed)
        if passed != self._decays_logged:
            self._decays_logged = passed
            logging.info("lr schedule: update %d -> lr %.5e", num_update, lr)
        return lr


class _AnnealingScheduler(LRScheduler):
    """Shared shape for Poly/Cosine: anneal from base_lr down to final_lr
    over the post-warmup window, hold final_lr afterwards. Pure function
    of num_update — no mutable lr state, so resume-from-checkpoint at any
    update count reproduces the schedule exactly."""

    def __init__(self, max_update, base_lr, final_lr, warmup_steps,
                 warmup_begin_lr, warmup_mode):
        super().__init__(base_lr, warmup_steps, warmup_begin_lr, warmup_mode)
        if int(max_update) != max_update or max_update < 1:
            raise ValueError("max_update must be a positive integer")
        if max_update <= warmup_steps:
            raise ValueError("max_update must exceed warmup_steps")
        self.max_update = int(max_update)
        self.final_lr = final_lr
        self.max_steps = self.max_update - self.warmup_steps

    def _progress(self, num_update):
        """Fraction of the annealing window consumed, clamped to [0, 1]."""
        t = (num_update - self.warmup_steps) / self.max_steps
        return min(max(t, 0.0), 1.0)

    def _anneal(self, t):
        raise NotImplementedError()

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        span = self.base_lr - self.final_lr
        return self.final_lr + span * self._anneal(self._progress(num_update))


class PolyScheduler(_AnnealingScheduler):
    """Polynomial decay: lr(t) follows (1 - t)^pwr over max_update steps,
    then holds at final_lr."""

    def __init__(self, max_update, base_lr=0.01, pwr=2, final_lr=0,
                 warmup_steps=0, warmup_begin_lr=0.0, warmup_mode="linear"):
        super().__init__(max_update, base_lr, final_lr, warmup_steps,
                         warmup_begin_lr, warmup_mode)
        self.power = pwr

    def _anneal(self, t):
        return (1.0 - t) ** self.power


class CosineScheduler(_AnnealingScheduler):
    """Cosine (half-period) decay over max_update steps, then holds at
    final_lr."""

    def __init__(self, max_update, base_lr=0.01, final_lr=0,
                 warmup_steps=0, warmup_begin_lr=0.0, warmup_mode="linear"):
        super().__init__(max_update, base_lr, final_lr, warmup_steps,
                         warmup_begin_lr, warmup_mode)

    def _anneal(self, t):
        return 0.5 * (1.0 + math.cos(math.pi * t))
