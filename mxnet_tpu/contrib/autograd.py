"""contrib autograd: the pre-1.0 experimental surface (reference:
python/mxnet/contrib/autograd.py), expressed over the first-class
``mxnet_tpu.autograd``. Kept so code written against the old names
(train_section, mark_variables-with-gradients, grad_and_loss) runs."""
from __future__ import annotations

import functools

from .. import autograd as _ag
from ..base import MXNetError
from ..ndarray.ndarray import NDArray

__all__ = ["set_is_training", "train_section", "test_section",
           "mark_variables", "backward", "compute_gradient",
           "grad_and_loss", "grad"]


def set_is_training(is_train):
    """Reference: contrib/autograd.py set_is_training. Returns the
    previous state."""
    prev = _ag.set_recording(bool(is_train))
    _ag.set_training(bool(is_train))
    return prev


def train_section():
    """``with train_section():`` == autograd.record()."""
    return _ag.record()


def test_section():
    """``with test_section():`` == autograd.pause()."""
    return _ag.pause()


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers (the old API passes them explicitly)."""
    if isinstance(variables, NDArray):
        variables, gradients = [variables], [gradients]
    _ag.mark_variables(list(variables), list(gradients),
                       grad_reqs=grad_reqs)


def backward(outputs, out_grads=None, retain_graph=False):
    if isinstance(outputs, NDArray):
        outputs = [outputs]
    _ag.backward(list(outputs), head_grads=out_grads,
                 retain_graph=retain_graph)


def compute_gradient(outputs):
    """Reference: contrib/autograd.py compute_gradient — backward, then
    collect the marked variables' gradients (the new-API entry point
    returns them directly)."""
    backward(outputs)
    return None


def grad_and_loss(func, argnum=None):
    """Decorate ``func`` to return (gradients, loss) w.r.t. its inputs
    (reference: contrib/autograd.py grad_and_loss)."""

    @functools.wraps(func)
    def wrapped(*args):
        variables = list(args)
        if argnum is not None:
            sel = [argnum] if isinstance(argnum, int) else list(argnum)
            variables = [args[i] for i in sel]
        for x in variables:
            if not isinstance(x, NDArray):
                raise MXNetError("arguments must be NDArrays")
        grads = [x.zeros_like() for x in variables]
        mark_variables(variables, grads)
        with train_section():
            out = func(*args)
        backward([out] if isinstance(out, NDArray) else out)
        return grads, out

    return wrapped


def grad(func, argnum=None):
    """Decorate ``func`` to return gradients only (reference:
    contrib/autograd.py grad)."""
    fn = grad_and_loss(func, argnum)

    @functools.wraps(func)
    def wrapped(*args):
        return fn(*args)[0]

    return wrapped
