"""Data-parallel executor groups (reference:
python/mxnet/executor_manager.py — the pre-Module training plumbing:
slice the batch over devices, run one executor per device, walk the
per-device gradient lists).

The TPU-first Module trains DP through ONE jitted program on a device
mesh (module/module.py); this module keeps the reference's
executor-group surface for code written against it: explicit
per-device executors, host-side batch slicing, per-parameter lists of
per-device gradients.
"""
from __future__ import annotations

import logging

from .base import MXNetError
from .io import DataDesc

__all__ = ["DataParallelExecutorGroup", "DataParallelExecutorManager"]


def _split_input_slice(batch_size, work_load_list):
    """Batch -> per-device slices proportional to work_load_list
    (reference: executor_manager.py _split_input_slice)."""
    total = sum(work_load_list)
    slices = []
    start = 0
    for i, w in enumerate(work_load_list):
        if i + 1 == len(work_load_list):
            stop = batch_size
        else:
            stop = min(batch_size, start + int(round(batch_size * w
                                                     / total)))
        if stop <= start:
            raise MXNetError(
                "too many devices for batch size %d" % batch_size)
        slices.append(slice(start, stop))
        start = stop
    return slices


class DataParallelExecutorGroup(object):
    """One executor per device over sliced batch shapes
    (reference: executor_manager.py DataParallelExecutorGroup)."""

    def __init__(self, sym, arg_names, param_names, ctx, slices,
                 train_data, shared_group=None):
        self.sym = sym
        self.data_names = [x[0] for x in train_data.provide_data]
        self.label_names = [x[0] for x in train_data.provide_label] \
            if train_data.provide_label else []
        self.aux_names = sym.list_auxiliary_states()
        self.param_names = [n for n in arg_names if n in set(param_names)]
        self.slices = slices
        self.train_execs = []
        for i, ctxi in enumerate(ctx):
            n = slices[i].stop - slices[i].start
            shapes = {}
            types = {}
            for x in (list(train_data.provide_data)
                      + list(train_data.provide_label or [])):
                shapes[x[0]] = (n,) + tuple(x[1][1:])
                if isinstance(x, DataDesc):
                    types[x.name] = x.dtype
            reqs = {a: ("write" if a in self.param_names else "null")
                    for a in arg_names}
            exe = sym.simple_bind(ctxi, grad_req=reqs, type_dict=types,
                                  **shapes)
            if shared_group is not None:
                # parameter sharing with an existing group (bucketing)
                src = shared_group.train_execs[i]
                for name in self.param_names:
                    exe.arg_dict[name][:] = src.arg_dict[name]
                for name in self.aux_names:
                    exe.aux_dict[name][:] = src.aux_dict[name]
            self.train_execs.append(exe)

        self.param_arrays = [[e.arg_dict[n] for e in self.train_execs]
                             for n in self.param_names]
        self.grad_arrays = [[e.grad_dict[n] for e in self.train_execs]
                            for n in self.param_names]
        self.aux_arrays = [[e.aux_dict[n] for e in self.train_execs]
                           for n in self.aux_names]

    def load_data_batch(self, data_batch):
        """Slice the host batch into each executor's input arrays."""
        for name, arr in zip(self.data_names, data_batch.data):
            for sl, exe in zip(self.slices, self.train_execs):
                exe.arg_dict[name][:] = arr[sl]
        if self.label_names and data_batch.label:
            for name, arr in zip(self.label_names, data_batch.label):
                for sl, exe in zip(self.slices, self.train_execs):
                    exe.arg_dict[name][:] = arr[sl]

    def forward(self, is_train=False):
        for exe in self.train_execs:
            exe.forward(is_train=is_train)

    def backward(self):
        for exe in self.train_execs:
            exe.backward()

    def update_metric(self, metric, labels, pre_sliced=False):
        for i, (exe, sl) in enumerate(zip(self.train_execs, self.slices)):
            part = labels[i] if pre_sliced else [lbl[sl] for lbl in labels]
            metric.update(part, exe.outputs)


class DataParallelExecutorManager(object):
    """Slices batches over devices and delegates to the (possibly
    bucketed) executor group (reference: executor_manager.py
    DataParallelExecutorManager)."""

    def __init__(self, symbol, ctx, train_data, arg_names=None,
                 param_names=None, aux_names=None, work_load_list=None,
                 logger=None, sym_gen=None):
        self.logger = logger or logging
        # train_data may be a DataIter or anything with provide_data
        # (the reference accepts "DataIter or DataBatch")
        batch_size = getattr(train_data, "batch_size", None) or \
            train_data.provide_data[0][1][0]
        if work_load_list is None:
            work_load_list = [1] * len(ctx)
        if len(work_load_list) != len(ctx):
            raise MXNetError("work_load_list must match ctx length")
        self.slices = _split_input_slice(batch_size, work_load_list)
        self.ctx = ctx
        self.arg_names = arg_names or symbol.list_arguments()
        if param_names is None:
            inputs = {x[0] for x in train_data.provide_data} | \
                {x[0] for x in (train_data.provide_label or [])}
            param_names = [n for n in self.arg_names if n not in inputs]
        self.param_names = param_names
        self.sym_gen = sym_gen
        self.execgrp = DataParallelExecutorGroup(
            symbol, self.arg_names, self.param_names, ctx, self.slices,
            train_data)
        self.execgrp_bucket = {}
        self.curr_execgrp = self.execgrp

    # param/grad/aux lists always refer to the group that actually ran
    # (the reference shares parameter STORAGE across bucket groups; JAX
    # arrays are immutable, so here updates are applied to the current
    # group and synchronized into the next group on bucket switch)
    @property
    def param_arrays(self):
        return self.curr_execgrp.param_arrays

    @property
    def grad_arrays(self):
        return self.curr_execgrp.grad_arrays

    @property
    def aux_arrays(self):
        return self.curr_execgrp.aux_arrays

    def set_params(self, arg_params, aux_params):
        for grp in [self.execgrp] + list(self.execgrp_bucket.values()):
            for exe in grp.train_execs:
                exe.copy_params_from(arg_params, aux_params,
                                     allow_extra_params=True)

    def _sync_groups(self, src, dst):
        """Carry the freshest parameters from the last-trained group
        into the group about to run."""
        if src is dst:
            return
        for s_exe, d_exe in zip(src.train_execs, dst.train_execs):
            for name in dst.param_names:
                d_exe.arg_dict[name][:] = s_exe.arg_dict[name]
            for name in dst.aux_names:
                d_exe.aux_dict[name][:] = s_exe.aux_dict[name]

    def load_data_batch(self, data_batch):
        if self.sym_gen is not None:
            key = data_batch.bucket_key
            if key not in self.execgrp_bucket:
                sym = self.sym_gen(key)
                self.execgrp_bucket[key] = DataParallelExecutorGroup(
                    sym, self.arg_names, self.param_names, self.ctx,
                    self.slices, data_batch,
                    shared_group=self.curr_execgrp)
            nxt = self.execgrp_bucket[key]
            self._sync_groups(self.curr_execgrp, nxt)
            self.curr_execgrp = nxt
        else:
            self.curr_execgrp = self.execgrp
        self.curr_execgrp.load_data_batch(data_batch)

    def forward(self, is_train=False):
        self.curr_execgrp.forward(is_train=is_train)

    def backward(self):
        self.curr_execgrp.backward()

    def update_metric(self, metric, labels, pre_sliced=False):
        self.curr_execgrp.update_metric(metric, labels, pre_sliced)
