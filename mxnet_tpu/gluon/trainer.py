"""Gluon Trainer.

Reference: python/mxnet/gluon/trainer.py (kvstore setup :158-211,
step :254, _update :347).

Applies an Optimizer to a set of Parameters after ``autograd.backward``,
optionally synchronizing gradients through a KVStore (allreduce over the
device mesh / processes for ``device`` / ``dist_tpu_sync`` types).
"""
from __future__ import annotations

from ..base import MXNetError
from .. import optimizer as opt_mod
from ..kvstore import KVStore, create as kv_create
from .parameter import ParameterDict, Parameter

__all__ = ["Trainer"]


class Trainer(object):
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                "got %s." % type(params))
        self._params = []
        self._param2idx = {}
        for i, p in enumerate(params):
            if not isinstance(p, Parameter):
                raise ValueError("got %s instead of Parameter" % type(p))
            self._params.append(p)
            self._param2idx[p.name] = i
        self._compression_params = compression_params
        self._contains_sparse = any(p.stype != "default" for p in self._params)
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_type = kvstore
        self._kvstore = None
        self._update_on_kvstore = update_on_kvstore
        self._kv_initialized = False

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, opt_mod.Optimizer):
            if optimizer_params and list(optimizer_params) != ["rescale_grad"]:
                raise ValueError(
                    "optimizer_params must be None if optimizer is an "
                    "instance of Optimizer instead of str")
            self._optimizer = optimizer
        else:
            self._optimizer = opt_mod.create(optimizer, **optimizer_params)
        self._optimizer.param_dict = param_dict
        self._states = [self._optimizer.create_state_multi_precision(
            i, p.data()) if p._data is not None else None
            for i, p in enumerate(self._params)]

    def _init_kvstore(self):
        if self._kvstore_type:
            kv = self._kvstore_type
            self._kvstore = kv if isinstance(kv, KVStore) else kv_create(kv)
            if self._compression_params:
                self._kvstore.set_gradient_compression(
                    self._compression_params)
            if self._update_on_kvstore is None:
                self._update_on_kvstore = False
            for i, p in enumerate(self._params):
                if p.grad_req != "null":
                    self._kvstore.init(i, p.data())
            if self._update_on_kvstore:
                self._kvstore.set_optimizer(self._optimizer)
        else:
            self._kvstore = None
            self._update_on_kvstore = False
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    @property
    def optimizer(self):
        return self._optimizer

    def _ensure_states(self):
        for i, p in enumerate(self._params):
            if self._states[i] is None and p._data is not None:
                self._states[i] = \
                    self._optimizer.create_state_multi_precision(i, p.data())

    def allreduce_grads(self):
        """Reduce gradients over devices/workers without updating
        (reference: trainer.py allreduce_grads)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        for i, p in enumerate(self._params):
            if p.grad_req != "null":
                self._kvstore.push(i, p.grad(), priority=-i)
                self._kvstore.pull(i, p.grad(), priority=-i,
                                   ignore_sparse=False)

    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce + optimizer update
        (reference: trainer.py:254 step)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._ensure_states()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def update(self, batch_size, ignore_stale_grad=False):
        """Optimizer update only — caller did allreduce_grads
        (reference: trainer.py update)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._ensure_states()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        items = []
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            if p._data is None:
                if not ignore_stale_grad:
                    raise MXNetError(
                        "Parameter %s has not been initialized" % p.name)
                continue
            items.append((i, p.data(), p.grad(), self._states[i]))
        # Fused path: every parameter's update in ONE donated XLA program
        # (a single Python→XLA dispatch) instead of one kernel dispatch
        # per parameter. fused_apply declines (→ per-param fallback) for
        # sparse grads, multi-precision, optimizers without a pure rule,
        # dist_* kvstores, or MXNET_FUSED_STEP=0.
        if items and self._fused_update_ok() \
                and opt_mod.fused_apply(self._optimizer, items):
            return
        for i, weight, grad, state in items:
            self._optimizer.update_multi_precision(i, weight, grad, state)

    def _fused_update_ok(self):
        from ..model import fused_step_supported
        return fused_step_supported(self._optimizer, self._kvstore,
                                    self._update_on_kvstore,
                                    self._compression_params)

    def save_states(self, fname):
        """Reference: trainer.py save_states."""
        import pickle
        with open(fname, "wb") as f:
            states = []
            for s in self._states:
                states.append(_state_to_numpy(s))
            pickle.dump({"optimizer": self._optimizer.__class__.__name__,
                         "num_update": self._optimizer.num_update,
                         "states": states}, f)

    def load_states(self, fname):
        import pickle
        from ..ndarray.ndarray import array
        with open(fname, "rb") as f:
            blob = pickle.load(f)
        self._ensure_states()
        self._optimizer.num_update = blob.get("num_update", 0)
        self._states = [_state_from_numpy(s) for s in blob["states"]]


def _state_to_numpy(s):
    from ..ndarray.ndarray import NDArray
    if s is None:
        return None
    if isinstance(s, NDArray):
        return s.asnumpy()
    if isinstance(s, (list, tuple)):
        return [_state_to_numpy(x) for x in s]
    return s


def _state_from_numpy(s):
    import numpy as np
    from ..ndarray.ndarray import array
    if s is None:
        return None
    if isinstance(s, np.ndarray):
        return array(s, dtype=s.dtype)
    if isinstance(s, list):
        return [_state_from_numpy(x) for x in s]
    return s
