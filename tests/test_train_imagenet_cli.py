"""North-star CLI smoke tests (reference:
example/image-classification/train_imagenet.py + common/fit.py).
"""
import os
import subprocess
import sys

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = os.path.join(ROOT, "examples", "train_imagenet.py")


def _run(args, timeout=240):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags +
                            " --xla_force_host_platform_device_count=8").strip()
    return subprocess.run([sys.executable, CLI] + args, capture_output=True,
                          text=True, timeout=timeout, env=env, cwd=ROOT)


def test_cli_mlp_synthetic():
    r = _run(["--network", "mlp", "--benchmark", "1", "--image-shape", "784",
              "--num-classes", "10", "--num-examples", "256",
              "--batch-size", "64", "--num-epochs", "1",
              "--kv-store", "local"])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "Train-accuracy" in r.stderr or "Train-accuracy" in r.stdout


def test_cli_resnet_dp_multi_device(tmp_path):
    """ResNet-20 over a 4-device dp context list with checkpointing —
    the north-star config shape at smoke scale."""
    prefix = str(tmp_path / "ck" / "resnet")
    r = _run(["--network", "resnet", "--num-layers", "20",
              "--image-shape", "3,32,32", "--benchmark", "1",
              "--num-classes", "10", "--num-examples", "128",
              "--batch-size", "32", "--num-epochs", "1",
              "--tpus", "0,1,2,3", "--kv-store", "device",
              "--model-prefix", prefix])
    assert r.returncode == 0, r.stderr[-2000:]
    assert os.path.exists(prefix + "-symbol.json")
    assert os.path.exists(prefix + "-0001.params")


def test_cli_rec_data_training(tmp_path):
    """End-to-end: im2rec-style .rec pack → ImageRecordIter → fit."""
    cv2 = pytest.importorskip("cv2")
    from mxnet_tpu import recordio
    rec_path = str(tmp_path / "train.rec")
    idx_path = str(tmp_path / "train.idx")
    rec = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    rng = np.random.RandomState(0)
    for i in range(64):
        img = rng.randint(0, 255, (40, 40, 3), dtype=np.uint8)
        ok, buf = cv2.imencode(".jpg", img)
        assert ok
        header = recordio.IRHeader(0, float(i % 10), i, 0)
        rec.write_idx(i, recordio.pack(header, buf.tobytes()))
    rec.close()
    r = _run(["--network", "mlp", "--image-shape", "3,32,32",
              "--num-classes", "10", "--num-examples", "64",
              "--batch-size", "16", "--num-epochs", "1",
              "--kv-store", "local", "--data-train", rec_path,
              "--random-mirror", "1", "--random-crop", "1"])
    assert r.returncode == 0, r.stderr[-2000:]


def test_image_iter_num_parts(tmp_path):
    """Distributed sharding: parts are disjoint and cover the dataset
    (reference: iter_image_recordio_2.cc num_parts/part_index)."""
    cv2 = pytest.importorskip("cv2")
    from mxnet_tpu import recordio
    from mxnet_tpu.image import ImageIter
    rec_path = str(tmp_path / "d.rec")
    idx_path = str(tmp_path / "d.idx")
    rec = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    rng = np.random.RandomState(0)
    for i in range(30):
        img = rng.randint(0, 255, (32, 32, 3), dtype=np.uint8)
        _, buf = cv2.imencode(".jpg", img)
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i), i, 0), buf.tobytes()))
    rec.close()
    seen = []
    for part in range(3):
        it = ImageIter(batch_size=5, data_shape=(3, 32, 32),
                       path_imgrec=rec_path, num_parts=3, part_index=part)
        labels = []
        try:
            while True:
                b = it.next()
                labels.extend(int(x) for x in b.label[0].asnumpy())
        except StopIteration:
            pass
        assert len(labels) == 10
        seen.extend(labels)
    assert sorted(seen) == list(range(30))


def test_cli_dist_tpu_sync_two_workers(tmp_path):
    """The literal BASELINE config shape: tools/launch.py -n 2 local +
    train_imagenet.py --kv-store dist_tpu_sync with num_parts data
    sharding (reference: example/image-classification/train_imagenet.py
    + tools/launch.py). Both ranks see DISJOINT data halves; sync
    aggregation through the PS must leave both ranks with identical
    final parameters."""
    cv2 = pytest.importorskip("cv2")
    from mxnet_tpu import recordio
    rec_path = str(tmp_path / "train.rec")
    idx_path = str(tmp_path / "train.idx")
    rec = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    rng = np.random.RandomState(0)
    for i in range(64):
        img = rng.randint(0, 255, (36, 36, 3), dtype=np.uint8)
        ok, buf = cv2.imencode(".jpg", img)
        assert ok
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i % 10), i, 0), buf.tobytes()))
    rec.close()

    prefix = str(tmp_path / "ck" / "model")
    launch = os.path.join(ROOT, "tools", "launch.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("MXNET_TPU_PS_URI", None)
    r = subprocess.run(
        [sys.executable, launch, "-n", "2", "--launcher", "local",
         "--sync-mode", "sync", "--",
         sys.executable, CLI, "--network", "mlp",
         "--image-shape", "3,32,32", "--num-classes", "10",
         "--num-examples", "64", "--batch-size", "16",
         "--num-epochs", "1", "--kv-store", "dist_tpu_sync",
         "--data-train", rec_path, "--model-prefix", prefix],
        capture_output=True, text=True, timeout=900, env=env, cwd=ROOT)
    assert r.returncode == 0, (r.stdout[-1000:], r.stderr[-2000:])

    from mxnet_tpu import model as mxmodel
    _, args0, _ = mxmodel.load_checkpoint(prefix, 1)
    _, args1, _ = mxmodel.load_checkpoint(prefix + "-1", 1)
    assert set(args0) == set(args1)
    for name in args0:
        np.testing.assert_allclose(
            args0[name].asnumpy(), args1[name].asnumpy(), rtol=1e-5,
            atol=1e-6, err_msg="rank divergence in %s" % name)
