"""Model hot-swap: atomic engine replacement with zero dropped requests.

A serving deployment updates weights (a new checkpoint from the training
fleet) without a restart: :meth:`ModelRegistry.swap` builds a NEW
:class:`InferenceEngine` from the new params blob, warms every bucket
(compiles finish before the swap — traffic never eats one), atomically
replaces the active engine, and gracefully drains the old one. Requests
already queued on the old engine flush through the old weights; requests
arriving after the swap run the new ones; nothing is dropped. The
rollout is observable via ``serving/swaps_total`` and the standard
engine metrics.
"""
from __future__ import annotations

import threading

from .. import telemetry as _tm
from ..base import MXNetError
from .engine import EngineClosedError, InferenceEngine, ServeConfig

__all__ = ["ModelRegistry"]


class ModelRegistry(object):
    """Owns the live engine for one model and swaps it atomically.

    Parameters mirror :class:`serving.Predictor`: the symbol stays fixed
    across swaps (weight updates, not architecture changes), the params
    blob is what rotates.
    """

    def __init__(self, symbol_json, param_bytes, input_shapes,
                 dev_type=1, dev_id=0, input_types=None, config=None):
        self._symbol_json = symbol_json
        self._input_shapes = dict(input_shapes)
        self._dev = (dev_type, dev_id)
        self._input_types = input_types
        self._cfg = config or ServeConfig()
        self._lock = threading.Lock()
        self._decode = None
        self._m_swaps = _tm.counter(
            "serving/swaps_total", "Model hot-swaps completed")
        self._engine = self._build(param_bytes)

    def _build(self, param_bytes):
        from ..serving import Predictor
        pred = Predictor(self._symbol_json, param_bytes,
                         dev_type=self._dev[0], dev_id=self._dev[1],
                         input_shapes=self._input_shapes,
                         input_types=self._input_types)
        return InferenceEngine(pred, self._cfg).start()

    # -- engine access -----------------------------------------------------
    def engine(self):
        """The CURRENT engine (atomic read; may be superseded by a
        concurrent swap — use :meth:`submit`/:meth:`predict`, which
        retry across swaps, unless you hold it only briefly)."""
        with self._lock:
            return self._engine

    @property
    def ready(self):
        return self.engine().ready

    def warmup(self):
        self.engine().warmup()
        return self

    def submit(self, feed, timeout_ms=None, ctx=None):
        """Engine submit that is safe across a concurrent swap: a
        request refused because ITS engine started draining re-routes
        to the replacement instead of surfacing a 503."""
        while True:
            eng = self.engine()
            try:
                return eng.submit(feed, timeout_ms, ctx=ctx)
            except EngineClosedError:
                if self.engine() is eng:     # closed for real, no swap
                    raise
                # else: swapped between the read and the submit; retry

    def predict(self, feed, timeout_ms=None):
        return self.submit(feed, timeout_ms).result()

    # -- decode attachment -------------------------------------------------
    def attach_decode(self, engine):
        """Attach a :class:`~mxnet_tpu.serve.decode.DecodeEngine`
        serving this model's autoregressive traffic. :meth:`swap` then
        DRAINS its decode sessions before the hot-swap (every in-flight
        generation finishes before the flip; pass ``decode_params`` to
        rotate the decode weights inside the same quiesced window), and
        :func:`serve_http` routes ``POST /generate`` to it."""
        self._decode = engine
        return engine

    def decode_engine(self):
        """The attached decode engine, or None."""
        return self._decode

    # -- lifecycle ---------------------------------------------------------
    def swap(self, param_bytes, drain_timeout=30.0, decode_params=None):
        """Hot-swap to a new params blob with zero dropped requests.

        Builds + warms the replacement engine while the old one keeps
        serving, DRAINS any attached decode engine's sessions BEFORE
        the flip (each in-flight generation finishes on the weights it
        started with; new ``/generate`` admissions 503 for the drain
        window), flips the active reference atomically, then drains the
        old engine (its queued requests complete on the old weights).

        ``decode_params``: the decode engine's new transformer weight
        pytree (its weights are a separate artifact from the predictor
        blob). When given, they rotate inside the quiesced window — the
        predictor flip and the decode weights move together, so no
        generation and no scoring batch ever mixes versions. When
        omitted, the decode engine keeps its current weights (the drain
        still quiesces decode across the flip); call
        ``DecodeEngine.swap_params`` separately if they rotate on their
        own cadence. Returns the new engine."""
        new = self._build(param_bytes)
        try:
            new.warmup()                  # compiles land BEFORE the flip
        except Exception:
            # failed rollout must not leak the replacement's workers or
            # its HBM weight copy; the old engine keeps serving
            new.close(drain=False)
            raise
        decode = self._decode
        if decode is not None:
            # decode sessions drain BEFORE the flip: generation state
            # (the KV cache) is weight-coupled in a way stateless
            # predict batches are not
            if not decode.pause(drain=True, timeout=drain_timeout):
                decode.resume()
                new.close(drain=False)
                raise MXNetError(
                    "decode sessions did not drain within %.1fs; "
                    "swap aborted, old weights still serving"
                    % drain_timeout)
            if decode_params is not None:
                # engine is idle (paused + drained): a plain rebind is
                # race-free, and programs take params as traced
                # arguments, so no recompiles either
                decode._params = decode_params
        try:
            with self._lock:
                old, self._engine = self._engine, new
        finally:
            if decode is not None:
                decode.resume()
        self._m_swaps.inc()
        old.close(drain=True, timeout=drain_timeout)
        return new

    def close(self, drain=True, timeout=30.0):
        if self._decode is not None:
            self._decode.close(drain=drain, timeout=timeout)
        self.engine().close(drain=drain, timeout=timeout)
