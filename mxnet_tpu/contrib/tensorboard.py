"""TensorBoard logging callback.

Reference: python/mxnet/contrib/tensorboard.py (LogMetricsCallback
writing eval metrics to an event file). The summary writer backend is
optional; without it we fall back to a plain JSONL event log that the
XLA-profiler TensorBoard plugin setup can ingest later.
"""
from __future__ import annotations

import json
import os
import time

__all__ = ["LogMetricsCallback"]


class LogMetricsCallback(object):
    """Log metrics each batch/epoch (reference: contrib/tensorboard.py).

    Uses tensorboardX / torch.utils.tensorboard when importable,
    otherwise appends JSONL records to ``logging_dir/metrics.jsonl``.
    """

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self._dir = logging_dir
        os.makedirs(logging_dir, exist_ok=True)
        self._writer = None
        for mod, cls in (("tensorboardX", "SummaryWriter"),
                         ("torch.utils.tensorboard", "SummaryWriter")):
            try:
                import importlib
                m = importlib.import_module(mod)
                self._writer = getattr(m, cls)(logging_dir)
                break
            except Exception:
                continue
        if self._writer is None:
            self._fallback = open(os.path.join(logging_dir,
                                               "metrics.jsonl"), "a")

    def __call__(self, param):
        if param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = "%s-%s" % (self.prefix, name)
            step = getattr(param, "nbatch", 0) + \
                getattr(param, "epoch", 0) * 1000000
            if self._writer is not None:
                self._writer.add_scalar(name, value, step)
            else:
                self._fallback.write(json.dumps(
                    {"ts": time.time(), "name": name, "value": float(value),
                     "step": step}) + "\n")
                self._fallback.flush()
