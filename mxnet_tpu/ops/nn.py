"""Neural-network operators.

Reference: src/operator/nn/ (fully_connected.cc, convolution.cc,
deconvolution.cc, pooling.cc, batch_norm.cc, layer_norm.cc, dropout.cc,
softmax.cc, activation.cc, lrn.cc, upsampling.cc), src/operator/rnn.cc,
src/operator/softmax_output.cc, src/operator/leaky_relu.cc.

TPU design notes:
* Convs/matmuls go through ``lax.conv_general_dilated`` / ``dot_general``
  so XLA tiles them onto the MXU; elementwise epilogues (bias, activation,
  BN scale/shift) fuse into the same kernel at compile time — this is the
  TPU equivalent of the reference's cuDNN fused paths.
* Everything is static-shape and functional. Stateful bits of the
  reference ops (BatchNorm moving stats, Dropout RNG) are externalized:
  BN returns (out, mean, var) and the layer owns running stats; random
  ops take an explicit PRNG key threaded by the runtime.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias
from ..base import MXNetError


# ---------------------------------------------------------------------------
# FullyConnected (reference: src/operator/nn/fully_connected.cc:239)
# ---------------------------------------------------------------------------

@register("FullyConnected", attr_defaults={"num_hidden": 0, "no_bias": False,
                                           "flatten": True})
def _fully_connected(data, weight, bias=None, num_hidden=0, no_bias=False,
                     flatten=True):
    if flatten and data.ndim > 2:
        data = data.reshape(data.shape[0], -1)
    out = lax.dot_general(data, weight,
                          (((data.ndim - 1,), (1,)), ((), ())))
    if not no_bias and bias is not None:
        out = out + bias
    return out


# ---------------------------------------------------------------------------
# Convolution / Deconvolution
# ---------------------------------------------------------------------------

_CONV_DIMS = {1: ("NCW", "OIW", "NCW"),
              2: ("NCHW", "OIHW", "NCHW"),
              3: ("NCDHW", "OIDHW", "NCDHW")}


def _tup(v, n, default):
    if v is None or v == ():
        return (default,) * n
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


@register("Convolution", attr_defaults={"kernel": (), "stride": (), "dilate": (),
                                        "pad": (), "num_filter": 0,
                                        "num_group": 1, "no_bias": False,
                                        "layout": None, "workspace": 1024,
                                        "cudnn_tune": None, "cudnn_off": False})
def _convolution(data, weight, bias=None, kernel=(), stride=(), dilate=(),
                 pad=(), num_filter=0, num_group=1, no_bias=False, layout=None,
                 **_ignored):
    """Reference: src/operator/nn/convolution.cc. NCHW in/out; XLA's layout
    assignment re-tiles internally for the MXU so no manual NHWC transpose
    is needed."""
    nd = len(kernel)
    if nd not in _CONV_DIMS:
        raise MXNetError("Convolution supports 1/2/3-d kernels")
    stride = _tup(stride, nd, 1)
    dilate = _tup(dilate, nd, 1)
    pad = _tup(pad, nd, 0)
    dn = lax.conv_dimension_numbers(data.shape, weight.shape, _CONV_DIMS[nd])
    out = lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=num_group)
    if not no_bias and bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


@register("Deconvolution", attr_defaults={"kernel": (), "stride": (), "dilate": (),
                                          "pad": (), "adj": (), "num_filter": 0,
                                          "num_group": 1, "no_bias": True,
                                          "layout": None, "target_shape": (),
                                          "workspace": 1024})
def _deconvolution(data, weight, bias=None, kernel=(), stride=(), dilate=(),
                   pad=(), adj=(), num_filter=0, num_group=1, no_bias=True,
                   layout=None, target_shape=(), **_ignored):
    """Transposed convolution (reference: src/operator/nn/deconvolution.cc).
    Implemented as input-dilated convolution with flipped kernels — the
    gradient-of-conv formulation XLA pattern-matches natively."""
    nd = len(kernel)
    stride = _tup(stride, nd, 1)
    dilate = _tup(dilate, nd, 1)
    pad = _tup(pad, nd, 0)
    adj = _tup(adj, nd, 0)
    # weight layout (in_channels, num_filter//num_group, *kernel)
    w = jnp.flip(weight, axis=tuple(range(2, 2 + nd)))

    def one_group(x, wg):
        dn = lax.conv_dimension_numbers(x.shape,
                                        (wg.shape[1], wg.shape[0]) + wg.shape[2:],
                                        _CONV_DIMS[nd])
        wt = jnp.swapaxes(wg, 0, 1)  # -> (num_filter/g, in/g, *k)
        padding = []
        for k, p, d, a in zip(kernel, pad, dilate, adj):
            keff = (k - 1) * d + 1
            padding.append((keff - 1 - p, keff - 1 - p + a))
        return lax.conv_general_dilated(
            x, wt, window_strides=(1,) * nd, padding=padding,
            lhs_dilation=stride, rhs_dilation=dilate, dimension_numbers=dn)

    if num_group == 1:
        out = one_group(data, w)
    else:
        xs = jnp.split(data, num_group, axis=1)
        ws = jnp.split(w, num_group, axis=0)
        out = jnp.concatenate([one_group(x, wg) for x, wg in zip(xs, ws)], axis=1)
    if not no_bias and bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


# ---------------------------------------------------------------------------
# Pooling (reference: src/operator/nn/pooling.cc)
# ---------------------------------------------------------------------------

@register("Pooling", attr_defaults={"kernel": (), "pool_type": "max",
                                    "global_pool": False, "stride": (),
                                    "pad": (), "pooling_convention": "valid",
                                    "count_include_pad": True, "cudnn_off": False})
def _pooling(data, kernel=(), pool_type="max", global_pool=False, stride=(),
             pad=(), pooling_convention="valid", count_include_pad=True,
             **_ignored):
    nd = data.ndim - 2
    if global_pool:
        axes = tuple(range(2, data.ndim))
        if pool_type == "max":
            return jnp.max(data, axis=axes, keepdims=True)
        if pool_type == "sum":
            return jnp.sum(data, axis=axes, keepdims=True)
        return jnp.mean(data, axis=axes, keepdims=True)
    kernel = tuple(kernel)
    stride = _tup(stride, nd, 1)
    pad = _tup(pad, nd, 0)
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    padding = [(0, 0), (0, 0)]
    for i, (k, s, p) in enumerate(zip(kernel, stride, pad)):
        lo = hi = p
        if pooling_convention == "full":
            # ceil output convention (reference pooling_convention=full):
            # pad the high side so the last partial window is included
            in_sz = data.shape[2 + i]
            out_sz = -(-(in_sz + 2 * p - k) // s) + 1  # ceil div
            needed = (out_sz - 1) * s + k - in_sz - p
            hi = max(p, needed)
        padding.append((lo, hi))
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
        return lax.reduce_window(data, init, lax.max, window, strides, padding)
    summed = lax.reduce_window(data, 0.0, lax.add, window, strides, padding)
    if pool_type == "sum":
        return summed
    if count_include_pad:
        denom = 1.0
        for k in kernel:
            denom *= k
        return summed / denom
    ones = jnp.ones(data.shape, dtype=data.dtype)
    counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, padding)
    return summed / counts


@register("_contrib_AdaptiveAvgPooling2D", attr_defaults={"output_size": ()})
def _adaptive_avg_pool(data, output_size=()):
    """Reference: src/operator/contrib/adaptive_avg_pooling.cc."""
    if not output_size:
        out_h = out_w = 1
    elif isinstance(output_size, int):
        out_h = out_w = output_size
    else:
        out_h, out_w = output_size
    n, c, h, w = data.shape
    if h % out_h == 0 and w % out_w == 0:
        x = data.reshape(n, c, out_h, h // out_h, out_w, w // out_w)
        return x.mean(axis=(3, 5))
    return jax.image.resize(data, (n, c, out_h, out_w), method="linear")


@register("UpSampling", attr_defaults={"scale": 1, "sample_type": "nearest",
                                       "num_filter": 0, "multi_input_mode": "concat",
                                       "workspace": 512})
def _upsampling(*args, scale=1, sample_type="nearest", **_ignored):
    data = args[0]
    if sample_type == "nearest":
        out = jnp.repeat(jnp.repeat(data, scale, axis=2), scale, axis=3)
    else:
        n, c, h, w = data.shape
        out = jax.image.resize(data, (n, c, h * scale, w * scale), method="linear")
    return out


@register("_contrib_BilinearResize2D", attr_defaults={"height": 0, "width": 0,
                                                      "scale_height": None,
                                                      "scale_width": None})
def _bilinear_resize(data, height=0, width=0, scale_height=None, scale_width=None):
    n, c, h, w = data.shape
    if scale_height is not None:
        height, width = int(h * scale_height), int(w * scale_width)
    return jax.image.resize(data, (n, c, height, width), method="linear")


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------

def _mean_var_outputs(attrs):
    return 3 if dict(attrs).get("output_mean_var", False) else 1


@register("BatchNorm", num_outputs=_mean_var_outputs,
          attr_defaults={"eps": 1e-3, "momentum": 0.9, "fix_gamma": True,
                         "use_global_stats": False, "output_mean_var": False,
                         "axis": 1, "cudnn_off": False, "train_mode": False})
def _batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
                momentum=0.9, fix_gamma=True, use_global_stats=False,
                output_mean_var=False, axis=1, train_mode=False, **_ignored):
    """Reference: src/operator/nn/batch_norm.cc. Returns (out, mean, var);
    the Gluon layer owns the moving-stat update (functional state)."""
    axis = axis % data.ndim
    red = tuple(i for i in range(data.ndim) if i != axis)
    bshape = tuple(data.shape[axis] if i == axis else 1 for i in range(data.ndim))
    if fix_gamma:
        gamma = jnp.ones_like(gamma)
    # mixed precision: stats/affine in fp32, output in the input dtype
    # (stats params stay fp32 under net.cast — reference fp16 BN policy)
    in_dtype = data.dtype
    x = data.astype(jnp.float32)
    if train_mode and not use_global_stats:
        mean = jnp.mean(x, axis=red)
        var = jnp.var(x, axis=red)
    else:
        mean, var = moving_mean, moving_var
    inv = lax.rsqrt(var.astype(jnp.float32) + eps).reshape(bshape)
    out = (x - mean.astype(jnp.float32).reshape(bshape)) * inv \
        * gamma.astype(jnp.float32).reshape(bshape) \
        + beta.astype(jnp.float32).reshape(bshape)
    out = out.astype(in_dtype)
    if output_mean_var:
        return out, mean, var
    return out


@register("SyncBatchNorm", num_outputs=_mean_var_outputs,
          attr_defaults={"eps": 1e-3, "momentum": 0.9, "fix_gamma": True,
                         "use_global_stats": False, "output_mean_var": False,
                         "axis": 1, "ndev": 1, "key": "", "axis_name": "",
                         "train_mode": False})
def _sync_batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
                     momentum=0.9, fix_gamma=True, use_global_stats=False,
                     output_mean_var=False, axis=1, ndev=1, key="",
                     axis_name="", train_mode=False, **_ignored):
    """Cross-device BatchNorm (reference:
    src/operator/contrib/sync_batch_norm-inl.h — the reference syncs
    per-GPU moments with a host-side barrier keyed by ``key``/``ndev``).

    TPU-native semantics: under the GSPMD paths (Module DP mesh /
    ShardedTrainer) the batch axis is one *logical* axis, so the plain
    batch moments below already reduce over every device — XLA inserts
    the cross-chip all-reduce; ``ndev``/``key`` are accepted for API
    parity and unused. Under an explicit ``shard_map``/``pmap`` with a
    mapped batch axis, pass ``axis_name`` and the moments are pmean'd
    across it."""
    axis = axis % data.ndim
    red = tuple(i for i in range(data.ndim) if i != axis)
    bshape = tuple(data.shape[axis] if i == axis else 1
                   for i in range(data.ndim))
    if fix_gamma:
        gamma = jnp.ones_like(gamma)
    in_dtype = data.dtype
    x = data.astype(jnp.float32)
    if train_mode and not use_global_stats:
        mean = jnp.mean(x, axis=red)
        meansq = jnp.mean(jnp.square(x), axis=red)
        if axis_name:
            mean = lax.pmean(mean, axis_name)
            meansq = lax.pmean(meansq, axis_name)
        var = meansq - jnp.square(mean)
    else:
        mean, var = moving_mean, moving_var
    out = (x - mean.astype(jnp.float32).reshape(bshape)) * lax.rsqrt(
        var.astype(jnp.float32).reshape(bshape) + eps) \
        * gamma.astype(jnp.float32).reshape(bshape) \
        + beta.astype(jnp.float32).reshape(bshape)
    out = out.astype(in_dtype)
    if output_mean_var:
        return out, mean, var
    return out


@register("LayerNorm", num_outputs=_mean_var_outputs,
          attr_defaults={"axis": -1, "eps": 1e-5, "output_mean_var": False})
def _layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    """Reference: src/operator/nn/layer_norm.cc."""
    axis = axis % data.ndim
    mean = jnp.mean(data, axis=axis, keepdims=True)
    var = jnp.var(data, axis=axis, keepdims=True)
    inv = lax.rsqrt(var + eps)
    bshape = tuple(data.shape[axis] if i == axis else 1 for i in range(data.ndim))
    out = (data - mean) * inv * gamma.reshape(bshape) + beta.reshape(bshape)
    if output_mean_var:
        return out, jnp.squeeze(mean, axis), jnp.squeeze(var, axis)
    return out


@register("InstanceNorm", attr_defaults={"eps": 1e-3})
def _instance_norm(data, gamma, beta, eps=1e-3):
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return (data - mean) * lax.rsqrt(var + eps) * gamma.reshape(bshape) \
        + beta.reshape(bshape)


@register("LRN", attr_defaults={"alpha": 1e-4, "beta": 0.75, "knorm": 2.0,
                                "nsize": 5})
def _lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    """Reference: src/operator/nn/lrn.cc — cross-channel local response norm."""
    sq = jnp.square(data)
    half = nsize // 2
    pad = [(0, 0), (half, half)] + [(0, 0)] * (data.ndim - 2)
    sq = jnp.pad(sq, pad)
    window = (1, nsize) + (1,) * (data.ndim - 2)
    ssum = lax.reduce_window(sq, 0.0, lax.add, window, (1,) * data.ndim,
                             [(0, 0)] * data.ndim)
    return data / jnp.power(knorm + alpha * ssum / nsize, beta)


# ---------------------------------------------------------------------------
# activations / softmax
# ---------------------------------------------------------------------------

@register("Activation", attr_defaults={"act_type": "relu"})
def _activation(data, act_type="relu"):
    if act_type == "relu":
        return jnp.maximum(data, 0)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jnp.logaddexp(data, 0.0)
    if act_type == "softsign":
        return jax.nn.soft_sign(data)
    raise MXNetError("unknown act_type %r" % act_type)


@register("LeakyReLU", attr_defaults={"act_type": "leaky", "slope": 0.25,
                                      "lower_bound": 0.125, "upper_bound": 0.334})
def _leaky_relu(data, gamma=None, act_type="leaky", slope=0.25, **_ignored):
    """Reference: src/operator/leaky_relu.cc (leaky/prelu/elu/selu/gelu)."""
    if act_type == "leaky":
        return jnp.where(data >= 0, data, slope * data)
    if act_type == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2)) if gamma.ndim == 1 else gamma
        return jnp.where(data >= 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        alpha, lam = 1.6732632423543772, 1.0507009873554805
        return lam * jnp.where(data >= 0, data, alpha * jnp.expm1(data))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    raise MXNetError("unknown LeakyReLU act_type %r" % act_type)


@register("softmax", attr_defaults={"axis": -1, "temperature": None,
                                    "dtype": None, "use_length": False})
def _softmax(data, axis=-1, temperature=None, **_ignored):
    if temperature:
        data = data / temperature
    return jax.nn.softmax(data, axis=axis)


@register("log_softmax", attr_defaults={"axis": -1, "temperature": None})
def _log_softmax(data, axis=-1, temperature=None, **_ignored):
    if temperature:
        data = data / temperature
    return jax.nn.log_softmax(data, axis=axis)


@register("softmin", attr_defaults={"axis": -1, "temperature": None})
def _softmin(data, axis=-1, temperature=None, **_ignored):
    if temperature:
        data = data / temperature
    return jax.nn.softmax(-data, axis=axis)


@register("softmax_cross_entropy")
def _softmax_cross_entropy(data, label):
    """Reference: src/operator/loss_binary_op.cc — scalar total CE loss."""
    logp = jax.nn.log_softmax(data, axis=-1)
    lab = label.astype(jnp.int32)
    picked = jnp.take_along_axis(logp, lab[:, None], axis=-1)
    return -jnp.sum(picked)


def _softmax_output_fwd(data, label, grad_scale=1.0, ignore_label=-1.0,
                        multi_output=False, use_ignore=False,
                        preserve_shape=False, normalization="null",
                        out_grad=False, smooth_alpha=0.0):
    if multi_output:
        out = jax.nn.softmax(data, axis=1)
    else:
        out = jax.nn.softmax(data, axis=-1)
    return out


import functools as _functools


@_functools.lru_cache(maxsize=None)
def _softmax_output_core(grad_scale, ignore_label, multi_output, use_ignore,
                         normalization, smooth_alpha):
    """Build a custom-vjp softmax-output closure for one static attr set.

    The reference's SoftmaxOutput combines loss + gradient: backward is
    (softmax - one_hot(label)) regardless of head grad
    (reference: src/operator/softmax_output-inl.h)."""
    axis_of = lambda out: 1 if multi_output else -1

    @jax.custom_vjp
    def core(data, label):
        return jax.nn.softmax(data, axis=1 if multi_output else -1)

    def fwd(data, label):
        out = core(data, label)
        return out, (out, label)

    def bwd(res, g):
        out, label = res
        axis = axis_of(out)
        depth = out.shape[axis]
        lab = label.astype(jnp.int32)
        oh = jax.nn.one_hot(lab, depth, axis=axis, dtype=out.dtype)
        if smooth_alpha:
            oh = oh * (1.0 - smooth_alpha) + smooth_alpha / (depth - 1) * (1.0 - oh)
        grad = out - oh
        if use_ignore:
            keep = (label != ignore_label).astype(out.dtype)
            keep = jnp.expand_dims(keep, axis) if keep.ndim < grad.ndim else keep
            grad = grad * keep
        scale = grad_scale
        if normalization == "batch":
            scale = scale / out.shape[0]
        if normalization == "valid" and use_ignore:
            valid = jnp.maximum(jnp.sum(label != ignore_label), 1)
            scale = scale / valid
        return (grad * scale, jnp.zeros_like(label))

    core.defvjp(fwd, bwd)
    return core


@register("SoftmaxOutput", attr_defaults={"grad_scale": 1.0, "ignore_label": -1.0,
                                          "multi_output": False, "use_ignore": False,
                                          "preserve_shape": False,
                                          "normalization": "null",
                                          "out_grad": False, "smooth_alpha": 0.0})
def _softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                    multi_output=False, use_ignore=False, preserve_shape=False,
                    normalization="null", out_grad=False, smooth_alpha=0.0):
    core = _softmax_output_core(float(grad_scale), float(ignore_label),
                                bool(multi_output), bool(use_ignore),
                                str(normalization), float(smooth_alpha))
    return core(data, label)

alias("Softmax", "SoftmaxOutput")


@register("LinearRegressionOutput", attr_defaults={"grad_scale": 1.0})
def _linear_regression_output(data, label, grad_scale=1.0):
    """Reference: src/operator/regression_output.cc."""
    @jax.custom_vjp
    def core(d, l):
        return d

    def fwd(d, l):
        return d, (d, l)

    def bwd(res, g):
        d, l = res
        return ((d - l.reshape(d.shape)) * grad_scale, jnp.zeros_like(l))

    core.defvjp(fwd, bwd)
    return core(data, label)


@register("LogisticRegressionOutput", attr_defaults={"grad_scale": 1.0})
def _logistic_regression_output(data, label, grad_scale=1.0):
    @jax.custom_vjp
    def core(d, l):
        return jax.nn.sigmoid(d)

    def fwd(d, l):
        return jax.nn.sigmoid(d), (jax.nn.sigmoid(d), l)

    def bwd(res, g):
        o, l = res
        return ((o - l.reshape(o.shape)) * grad_scale, jnp.zeros_like(l))

    core.defvjp(fwd, bwd)
    return core(data, label)


@register("MAERegressionOutput", attr_defaults={"grad_scale": 1.0})
def _mae_regression_output(data, label, grad_scale=1.0):
    @jax.custom_vjp
    def core(d, l):
        return d

    def fwd(d, l):
        return d, (d, l)

    def bwd(res, g):
        d, l = res
        return (jnp.sign(d - l.reshape(d.shape)) * grad_scale, jnp.zeros_like(l))

    core.defvjp(fwd, bwd)
    return core(data, label)


# ---------------------------------------------------------------------------
# Dropout (stateless; key threaded by runtime)
# ---------------------------------------------------------------------------

@register("Dropout", needs_rng=True,
          attr_defaults={"p": 0.5, "mode": "training", "axes": (),
                         "train_mode": False})
def _dropout(key, data, p=0.5, mode="training", axes=(), train_mode=False,
             **_ignored):
    """Reference: src/operator/nn/dropout.cc. The per-device RandGenerator
    resource becomes an explicit PRNG key input."""
    if not train_mode and mode != "always":
        return data
    if p <= 0.0:
        return data
    shape = data.shape
    if axes:
        shape = tuple(1 if i in axes else s for i, s in enumerate(shape))
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, shape).astype(data.dtype) / keep
    return data * mask


# ---------------------------------------------------------------------------
# Fused RNN (reference: src/operator/rnn.cc, rnn_impl.h; cuDNN-packed params)
# ---------------------------------------------------------------------------

def _rnn_num_outputs(attrs):
    a = dict(attrs)
    if not a.get("state_outputs", False):
        return 1
    return 3 if a.get("mode", "lstm") == "lstm" else 2


def _gates(mode):
    return {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]


def rnn_param_size(num_layers, input_size, state_size, bidirectional, mode,
                   projection_size=None):
    """Total flat parameter count, cuDNN layout (W, R, bW, bR per layer/dir;
    LSTMP adds a recurrent projection matrix P per layer/dir)."""
    g = _gates(mode)
    dirs = 2 if bidirectional else 1
    hout = projection_size if projection_size else state_size
    size = 0
    for layer in range(num_layers):
        isz = input_size if layer == 0 else hout * dirs
        size += dirs * g * state_size * (isz + hout + 2)
        if projection_size:
            size += dirs * projection_size * state_size
    return size


def _unpack_rnn_params(params, num_layers, input_size, state_size,
                       bidirectional, mode, projection_size=None):
    g = _gates(mode)
    dirs = 2 if bidirectional else 1
    hout = projection_size if projection_size else state_size
    offset = 0
    layers = []
    for layer in range(num_layers):
        isz = input_size if layer == 0 else hout * dirs
        per_dir = []
        for _ in range(dirs):
            W = params[offset: offset + g * state_size * isz].reshape(
                g * state_size, isz)
            offset += g * state_size * isz
            R = params[offset: offset + g * state_size * hout].reshape(
                g * state_size, hout)
            offset += g * state_size * hout
            bW = params[offset: offset + g * state_size]
            offset += g * state_size
            bR = params[offset: offset + g * state_size]
            offset += g * state_size
            if projection_size:
                P = params[offset: offset + projection_size * state_size].reshape(
                    projection_size, state_size)
                offset += projection_size * state_size
            else:
                P = None
            per_dir.append((W, R, bW, bR, P))
        layers.append(per_dir)
    return layers


def _cell_step(mode):
    def step(carry, x_t, W, R, bW, bR, P=None):
        if mode == "lstm":
            h, c = carry
            z = x_t @ W.T + h @ R.T + bW + bR
            i, f, g, o = jnp.split(z, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c = f * c + i * g
            h = o * jnp.tanh(c)
            if P is not None:  # LSTMP recurrent projection
                h = h @ P.T
            return (h, c), h
        if mode == "gru":
            (h,) = carry
            zx = x_t @ W.T + bW
            zh = h @ R.T + bR
            rx, zx_, nx = jnp.split(zx, 3, axis=-1)
            rh, zh_, nh = jnp.split(zh, 3, axis=-1)
            r = jax.nn.sigmoid(rx + rh)
            z = jax.nn.sigmoid(zx_ + zh_)
            n = jnp.tanh(nx + r * nh)
            h = (1 - z) * n + z * h
            return (h,), h
        (h,) = carry
        z = x_t @ W.T + h @ R.T + bW + bR
        h = jnp.tanh(z) if mode == "rnn_tanh" else jnp.maximum(z, 0)
        return (h,), h
    return step


@register("RNN", num_outputs=_rnn_num_outputs, needs_rng=True,
          attr_defaults={"state_size": 0, "num_layers": 1, "bidirectional": False,
                         "mode": "lstm", "p": 0.0, "state_outputs": False,
                         "projection_size": None, "train_mode": False})
def _rnn(key, data, params, state, *maybe_cell, state_size=0, num_layers=1,
         bidirectional=False, mode="lstm", p=0.0, state_outputs=False,
         projection_size=None, train_mode=False, **_ignored):
    """Fused multilayer RNN over time via lax.scan (sequence layout TNC,
    matching the reference's RNN op, src/operator/rnn.cc). Each timestep is
    a single MXU matmul per direction; the scan keeps compile time flat for
    long sequences. Inter-layer dropout ``p`` (cuDNN semantics: applied to
    the input of layers 1..L-1, training only) and LSTMP ``projection_size``
    are honored."""
    T, N, I = data.shape
    H = state_size
    dirs = 2 if bidirectional else 1
    if projection_size and mode != "lstm":
        raise MXNetError("projection_size is only supported for lstm")
    cell = maybe_cell[0] if (mode == "lstm" and maybe_cell) else None
    layers = _unpack_rnn_params(params, num_layers, I, H, bidirectional, mode,
                                projection_size)
    step = _cell_step(mode)

    x = data
    h_states, c_states = [], []
    for li, per_dir in enumerate(layers):
        if li > 0 and p > 0.0 and train_mode:
            key, sub = jax.random.split(key)
            keep = 1.0 - p
            mask = jax.random.bernoulli(sub, keep, x.shape).astype(x.dtype) / keep
            x = x * mask
        outs = []
        for di, (W, R, bW, bR, P) in enumerate(per_dir):
            h0 = state[li * dirs + di]
            carry = (h0, cell[li * dirs + di]) if mode == "lstm" else (h0,)
            xs = jnp.flip(x, axis=0) if di == 1 else x

            def scan_fn(c, x_t, W=W, R=R, bW=bW, bR=bR, P=P):
                return step(c, x_t, W, R, bW, bR, P)

            carry, ys = lax.scan(scan_fn, carry, xs)
            if di == 1:
                ys = jnp.flip(ys, axis=0)
            outs.append(ys)
            h_states.append(carry[0])
            if mode == "lstm":
                c_states.append(carry[1])
        x = outs[0] if dirs == 1 else jnp.concatenate(outs, axis=-1)
    out = x
    if not state_outputs:
        return out
    hN = jnp.stack(h_states, axis=0)
    if mode == "lstm":
        return out, hN, jnp.stack(c_states, axis=0)
    return out, hN
