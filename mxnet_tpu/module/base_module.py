"""BaseModule: the high-level train/predict interface.

Reference: python/mxnet/module/base_module.py (fit at :410, the first
judged milestone of SURVEY.md §7 stage 4).
"""
from __future__ import annotations

import logging
import os
import signal
import threading
import time

from .. import fault as _fault
from .. import goodput as _gp
from .. import health as _health
from .. import metric as _metric
from .. import io as _io
from .. import tracing as _tr
from ..base import MXNetError
from ..initializer import Uniform
from ..ndarray.ndarray import NDArray

__all__ = ["BaseModule"]


def _check_input_names(symbol, names, typename, throw):
    args = symbol.list_arguments()
    for name in names:
        if name in args:
            continue
        candidates = [a for a in args
                      if not a.endswith(("_weight", "_bias", "_gamma", "_beta"))]
        msg = ("\033[91mYou created Module with Module(..., %s_names=%s) but "
               "input with name '%s' is not found in symbol.list_arguments(). "
               "Did you mean one of:\n\t%s\033[0m"
               % (typename, str(names), name, "\n\t".join(candidates)))
        if throw:
            raise ValueError(msg)
        logging.warning(msg)


def _parse_data_desc(data_names, label_names, data_shapes, label_shapes):
    data_shapes = [x if isinstance(x, _io.DataDesc) else _io.DataDesc(*x)
                   for x in data_shapes]
    _check_names_match(data_names, data_shapes, "data", True)
    if label_shapes is not None:
        label_shapes = [x if isinstance(x, _io.DataDesc) else _io.DataDesc(*x)
                        for x in label_shapes]
        _check_names_match(label_names, label_shapes, "label", False)
    else:
        _check_names_match(label_names, [], "label", False)
    return data_shapes, label_shapes


def _check_names_match(data_names, data_shapes, name, throw):
    actual = [x[0] for x in data_shapes]
    if sorted(data_names) != sorted(actual):
        msg = "Data provided by %s_shapes don't match names specified by " \
              "%s_names (%s vs. %s)" % (name, name, str(data_shapes),
                                        str(data_names))
        if throw:
            raise ValueError(msg)
        logging.warning(msg)


def _as_list(obj):
    if isinstance(obj, (list, tuple)):
        return obj
    return [obj]


class BaseModule(object):
    """Base class for modules (reference: base_module.py:64)."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None
        self._total_exec_bytes = 0

    # -- high-level API ----------------------------------------------------
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0, sparse_row_id_fn=None):
        """Evaluate on ``eval_data`` (reference: base_module.py:210)."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)
        eval_metric.reset()
        actual_num_batch = 0
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            if isinstance(eval_batch, list):
                self.update_metric(eval_metric,
                                   [eb.label for eb in eval_batch],
                                   pre_sliced=True)
            else:
                self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                params = _BatchEndParam(epoch=epoch, nbatch=nbatch,
                                        eval_metric=eval_metric, locals=locals())
                for callback in _as_list(batch_end_callback):
                    callback(params)
            actual_num_batch += 1
        if score_end_callback:
            params = _BatchEndParam(epoch=epoch, nbatch=actual_num_batch,
                                    eval_metric=eval_metric, locals=locals())
            for callback in _as_list(score_end_callback):
                callback(params)
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad] for out in self.get_outputs()]
            yield outputs, nbatch, eval_batch

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        """Run prediction, collecting outputs
        (reference: base_module.py:321)."""
        import numpy as np
        from ..ndarray.ndarray import array
        assert self.binded and self.params_initialized
        if isinstance(eval_data, (NDArray, np.ndarray)):
            if isinstance(eval_data, np.ndarray):
                eval_data = array(eval_data)
            self.forward(_io.DataBatch([eval_data]))
            return self.get_outputs()[0]
        if not isinstance(eval_data, _io.DataIter):
            raise ValueError("eval_data must be of type NDArray or DataIter")
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad].copy()
                       for out in self.get_outputs()]
            output_list.append(outputs)
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                assert len(out) == num_outputs, \
                    "Cannot merge batches, as num of outputs is not the same " \
                    "in mini-batches. Maybe bucketing is used?"
            output_list2 = [
                array(np.concatenate(
                    [out[i].asnumpy() for out in output_list]))
                for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return output_list2[0]
            return output_list2
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=Uniform(0.01), arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None, checkpoint_prefix=None,
            checkpoint_period=1, save_optimizer_states=True, resume=False):
        """The full training loop (reference: base_module.py:410; loop body
        forward_backward/update at :528-529).

        Fault tolerance (beyond the reference): with
        ``checkpoint_prefix`` set, fit writes a crash-consistent
        checkpoint (params + optimizer state + manifest carrying the
        epoch/batch position and RNG state) every ``checkpoint_period``
        epochs, and a SIGTERM — the preemption notice on TPU VMs —
        takes a final mid-epoch checkpoint within the
        ``MXNET_CKPT_GRACE_S`` grace window before stopping. With
        ``resume=True`` fit restores the newest *valid* checkpoint
        under the prefix (torn/corrupt ones are skipped) and continues
        from the exact epoch + batch with the optimizer and RNG state
        of the interrupted run, so the post-resume trajectory is
        bitwise-identical to the uninterrupted one — provided the data
        iterator replays deterministically (no unseeded shuffling).
        """
        assert num_epoch is not None, "please specify number of epochs"

        if checkpoint_prefix is not None or resume:
            # a checkpointing (hence restartable) run wires the
            # persistent compile cache up front: the resumed process's
            # fused-step build — routed through programs.get_or_build —
            # loads from disk instead of recompiling, so
            # restore-to-first-step is dominated by the restore, not
            # XLA (the train_resume bench banks both walls)
            from .. import programs as _pg
            _pg.ensure_persistent_cache()

        resume_state = None
        skip_nbatch = 0
        io_seeked = False
        if resume:
            if checkpoint_prefix is None:
                raise MXNetError(
                    "fit(resume=True) needs checkpoint_prefix to know "
                    "where the checkpoints live")
            from ..checkpoint import load_latest_valid
            resume_state = load_latest_valid(checkpoint_prefix)
            if resume_state is not None:
                arg_params = resume_state.arg_params
                aux_params = resume_state.aux_params
                allow_missing = False
                begin_epoch = resume_state.epoch
                skip_nbatch = resume_state.nbatch
                # seek the data iterator via the manifest's shard cursor
                # when it supports it: O(1), nothing decoded on the way,
                # and the shuffle seed travels with the cursor so the
                # post-resume batch stream is bitwise-identical to the
                # uninterrupted run. Iterators without a cursor (or a
                # cursor from a different stream) fall back to replay.
                cur = resume_state.io_cursor
                if cur and hasattr(train_data, "restore_state"):
                    try:
                        train_data.restore_state(cur)
                        io_seeked = True
                    except MXNetError as e:
                        self.logger.warning(
                            "io cursor in %s-%04d does not fit this "
                            "iterator (%s); replaying the epoch instead",
                            checkpoint_prefix, resume_state.epoch, e)
                self.logger.info(
                    "resuming from checkpoint %s-%04d (epoch %d, "
                    "batch %d%s)", checkpoint_prefix, resume_state.epoch,
                    resume_state.epoch, resume_state.nbatch,
                    ", iterator seeked" if io_seeked else "")

        # -- elastic dist_tpu_sync (checkpoint-free rescale) ---------------
        # JOIN mode: a relaunched rank asks the running world for
        # admission BEFORE binding — the adopted plan brings the
        # runtime up against the new coordinator and positions the
        # (resharded) iterator at the agreed step; the kvstore init
        # broadcast below then pulls the survivors' parameters.
        from ..config import get as _cfg
        _elastic = None
        _el = None
        _el_root = str(_cfg("MXNET_ELASTIC_DIR") or "")
        if _el_root and int(_cfg("MXNET_ELASTIC_JOIN") or 0):
            from .. import elastic as _el
            _elastic, begin_epoch, skip_nbatch = _el.ElasticFit.join(
                train_data)
            io_seeked = True
            self.logger.info(
                "elastic: joined world=%d as rank %d, resuming at "
                "epoch %d batch %d", _elastic.agent.world,
                _elastic.agent.rank, begin_epoch, skip_nbatch)

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)

        _kv_obj = getattr(self, "_kvstore", None)
        if _el_root and _kv_obj is not None and \
                getattr(_kv_obj, "type", "") == "dist_tpu_sync" and \
                hasattr(self, "elastic_snapshot"):
            if _el is None:
                from .. import elastic as _el
            if _elastic is None:
                _elastic = _el.ElasticFit.for_world(self, train_data,
                                                    _kv_obj)
            _elastic.after_init(self, begin_epoch, skip_nbatch)
        elif _elastic is not None:
            raise MXNetError(
                "elastic join mode needs a dist_tpu_sync kvstore with "
                "a fused-step-capable module (got kvstore %r)"
                % getattr(_kv_obj, "type", kvstore))
        _rescale_errors = _el.rescale_errors() if _elastic is not None \
            else ()

        if resume_state is not None:
            # a module whose params were already live before this fit
            # (in-process re-fit after a caught interruption) must still
            # take the CHECKPOINT's params: init_params above ignores
            # its cache once params_initialized, set_params(force_init)
            # does not — params, optimizer state, and RNG must all come
            # from the same checkpoint or resume is silently mixed
            self.set_params(resume_state.arg_params,
                            resume_state.aux_params, force_init=True)
            if resume_state.states_fname and \
                    hasattr(self, "load_optimizer_states"):
                self.load_optimizer_states(resume_state.states_fname)
            if resume_state.rng is not None:
                from .. import random as _random
                _random.set_state(resume_state.rng)

        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)

        # SIGTERM = preemption notice: checkpoint within the grace
        # window, then stop. The watchdog hard-exits at grace end —
        # the platform reclaims the VM then regardless, and a wedged
        # save must not make the process outstay the notice.
        preempt = {"flag": False, "watchdog": None}
        prev_handler = None
        if checkpoint_prefix is not None and \
                threading.current_thread() is threading.main_thread():
            def _on_sigterm(signum, frame):
                if preempt["flag"]:
                    return
                preempt["flag"] = True
                from ..config import get as _cfg
                grace = float(_cfg("MXNET_CKPT_GRACE_S"))
                if grace > 0:
                    t = threading.Timer(grace, os._exit, args=(143,))
                    t.daemon = True
                    t.start()
                    preempt["watchdog"] = t
                self.logger.info("SIGTERM: checkpointing and stopping "
                                 "within the %.0fs grace window", grace)
            try:
                prev_handler = signal.signal(signal.SIGTERM, _on_sigterm)
            except ValueError:
                prev_handler = None

        # goodput ledger: attribute every wall-second of this fit to one
        # category (step compute / data wait / compile / checkpoint /
        # rescale / restart / straggler wait / idle) — pure host
        # arithmetic, zero device dispatches (goodput.py)
        _gp.session_begin()

        try:
            while True:
                try:
                    for epoch in range(begin_epoch, num_epoch):
                        tic = time.time()
                        eval_metric.reset()
                        nbatch = 0
                        data_iter = iter(train_data)
                        if skip_nbatch:
                            if io_seeked:
                                # the iterator is already at the cursor; only
                                # the batch numbering needs to line up
                                nbatch = skip_nbatch
                            else:
                                # mid-epoch resume without a seekable cursor:
                                # draw and discard the batches the interrupted
                                # run already trained on, so the iterator
                                # position and batch numbering line up with the
                                # uninterrupted run
                                for _ in range(skip_nbatch):
                                    try:
                                        next(data_iter)
                                    except StopIteration:
                                        break
                                    nbatch += 1
                            skip_nbatch = 0
                        io_seeked = False
                        end_of_batch = False
                        eval_name_vals = eval_metric.get_name_value()
                        try:
                            next_data_batch = next(data_iter)
                        except StopIteration:
                            end_of_batch = True
                        while not end_of_batch:
                            data_batch = next_data_batch
                            _fault.inject("engine.step")
                            if _elastic is not None:
                                # raises MembershipChange on a stale
                                # peer heartbeat or a pending joiner
                                _elastic.pre_step(epoch, nbatch)
                            _gp_tok = _gp.step_begin()
                            _gp_dw = 0.0
                            # per-step trace timeline: one root span per step
                            # (head-sampled), with the phase split a stall
                            # investigation needs — was the step waiting on
                            # data, on forward-backward, or on the optimizer?
                            with _tr.start_span("train.step",
                                                attrs={"epoch": epoch,
                                                       "nbatch": nbatch}):
                                if monitor is not None:
                                    monitor.tic()
                                try:
                                    with _tr.child_span("train.forward_backward"):
                                        self.forward_backward(data_batch)
                                    with _tr.child_span("train.update"):
                                        if _elastic is not None:
                                            # step watchdog: a peer dying
                                            # mid-collective can park this
                                            # call forever on TPU
                                            _elastic.run_update()
                                        else:
                                            self.update()
                                except _health.NumericsError:
                                    # policy checkpoint-and-raise: preserve the
                                    # tripped state under a FORENSIC prefix (the
                                    # nonfinite params are the blast-radius
                                    # evidence) without clobbering the recovery
                                    # chain load_latest_valid walks, then stop
                                    if (checkpoint_prefix is not None
                                            and _health.numerics_policy()
                                            == "checkpoint-and-raise"):
                                        self._save_fit_checkpoint(
                                            checkpoint_prefix + ".numerics",
                                            epoch, nbatch + 1,
                                            save_optimizer_states, train_data)
                                    raise
                                if isinstance(data_batch, list):
                                    self.update_metric(
                                        eval_metric,
                                        [db.label for db in data_batch],
                                        pre_sliced=True)
                                else:
                                    self.update_metric(eval_metric,
                                                       data_batch.label)
                                if _elastic is not None:
                                    # the metric sync above proved the
                                    # step's arrays are materialized:
                                    # vote it completed and refresh the
                                    # host param mirror survivors would
                                    # restore from
                                    _elastic.note_step(epoch, nbatch + 1)
                                fetched = None
                                with _tr.child_span("train.data_wait"):
                                    _gp_dw = time.perf_counter()
                                    try:
                                        fetched = next(data_iter)
                                    except StopIteration:
                                        end_of_batch = True
                                    _gp_dw = time.perf_counter() - _gp_dw
                                if fetched is not None:
                                    next_data_batch = fetched
                                    try:
                                        self.prepare(
                                            next_data_batch,
                                            sparse_row_id_fn=sparse_row_id_fn)
                                    except StopIteration:
                                        end_of_batch = True
                            _gp.step_end(_gp_tok, data_wait_s=_gp_dw)
                            if monitor is not None:
                                monitor.toc_print()
                            if end_of_batch:
                                eval_name_vals = eval_metric.get_name_value()
                            if batch_end_callback is not None:
                                params = _BatchEndParam(epoch=epoch, nbatch=nbatch,
                                                        eval_metric=eval_metric,
                                                        locals=locals())
                                for callback in _as_list(batch_end_callback):
                                    callback(params)
                            nbatch += 1
                            if preempt["flag"]:
                                if end_of_batch:
                                    self._save_fit_checkpoint(
                                        checkpoint_prefix, epoch + 1, 0,
                                        save_optimizer_states, train_data)
                                else:
                                    self._save_fit_checkpoint(
                                        checkpoint_prefix, epoch, nbatch,
                                        save_optimizer_states, train_data)
                                if preempt["watchdog"] is not None:
                                    preempt["watchdog"].cancel()
                                self.logger.info(
                                    "preemption checkpoint saved at epoch %d "
                                    "batch %d; stopping fit (resume=True picks "
                                    "up here)", epoch, nbatch)
                                return

                        # drain the deferred numerics sentinel of the epoch's
                        # final step (its verdict is read one step behind so
                        # the device pipeline never stalls)
                        try:
                            self._flush_numerics()
                        except _health.NumericsError:
                            if (checkpoint_prefix is not None
                                    and _health.numerics_policy()
                                    == "checkpoint-and-raise"):
                                self._save_fit_checkpoint(
                                    checkpoint_prefix + ".numerics", epoch,
                                    nbatch, save_optimizer_states, train_data)
                            raise

                        for name, val in eval_name_vals:
                            self.logger.info("Epoch[%d] Train-%s=%f", epoch, name,
                                             val)
                        toc = time.time()
                        self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                                         (toc - tic))

                        arg_p, aux_p = self.get_params()
                        self.set_params(arg_p, aux_p)
                        if epoch_end_callback is not None:
                            for callback in _as_list(epoch_end_callback):
                                callback(epoch, self.symbol, arg_p, aux_p)
                        if checkpoint_prefix is not None and \
                                (epoch + 1) % checkpoint_period == 0:
                            self._save_fit_checkpoint(checkpoint_prefix, epoch + 1,
                                                      0, save_optimizer_states,
                                                      train_data)

                        if eval_data is not None:
                            res = self.score(eval_data, validation_metric,
                                             score_end_callback=eval_end_callback,
                                             batch_end_callback=eval_batch_end_callback,
                                             epoch=epoch)
                            for name, val in res:
                                self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                                 name, val)
                        train_data.reset()
                except _rescale_errors as _mchange:
                    # a membership change (dead peer, wedged
                    # collective, pending joiner): run the rescale
                    # barrier, rebuild on the surviving mesh, and
                    # re-enter the loop at the agreed step
                    begin_epoch, skip_nbatch = _elastic.handle(_mchange)
                    io_seeked = True
                    continue
                break
        finally:
            _gp.session_end()
            if prev_handler is not None:
                signal.signal(signal.SIGTERM, prev_handler)
            if preempt["watchdog"] is not None:
                preempt["watchdog"].cancel()
            if _elastic is not None:
                _elastic.stop()
            # deterministic teardown of prefetch threads / decode
            # workers (close() is restartable, so handing the same
            # iterator to a second fit still works)
            for it in (train_data, eval_data):
                closer = getattr(it, "close", None)
                if callable(closer):
                    try:
                        closer()
                    except Exception:
                        self.logger.warning(
                            "data iterator close() failed", exc_info=True)

    def _save_fit_checkpoint(self, prefix, epoch, nbatch,
                             save_optimizer_states, train_data=None):
        """One crash-consistent fit checkpoint: params + optimizer state
        + manifest (epoch/batch position, RNG state, and — when the
        iterator supports it — the resumable shard cursor). Numbered by
        completed epochs; a mid-epoch save reuses the epoch number with
        ``nbatch`` > 0 and supersedes that epoch's boundary save."""
        io_cursor = None
        cursor_fn = getattr(train_data, "checkpoint_state", None)
        if callable(cursor_fn):
            try:
                io_cursor = cursor_fn(epoch, nbatch)
            except Exception:
                self.logger.warning(
                    "data iterator checkpoint_state() failed; checkpoint "
                    "carries no io cursor (resume will replay)",
                    exc_info=True)
        _gp_t0 = time.perf_counter()
        try:
            with _tr.start_span("train.checkpoint",
                                attrs={"epoch": epoch, "nbatch": nbatch}):
                saver = getattr(self, "save_checkpoint", None)
                if saver is not None:
                    saver(prefix, epoch, save_optimizer_states, nbatch=nbatch,
                          io_cursor=io_cursor)
                    return
                # modules without a save_checkpoint of their own
                # (Sequential, Python): params + manifest through the
                # model-level writer
                from ..model import save_checkpoint as _model_save
                arg_p, aux_p = self.get_params()
                states = None
                if save_optimizer_states and self.optimizer_initialized and \
                        hasattr(self, "save_optimizer_states"):
                    states = "%s-%04d.states" % (prefix, epoch)
                    self.save_optimizer_states(states)
                _model_save(prefix, epoch, self._symbol, arg_p, aux_p,
                            nbatch=nbatch, states_fname=states,
                            io_cursor=io_cursor)
        finally:
            _gp.note("checkpoint", time.perf_counter() - _gp_t0)

    # -- properties --------------------------------------------------------
    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        raise NotImplementedError()

    @property
    def output_names(self):
        raise NotImplementedError()

    @property
    def data_shapes(self):
        raise NotImplementedError()

    @property
    def label_shapes(self):
        raise NotImplementedError()

    @property
    def output_shapes(self):
        raise NotImplementedError()

    # -- parameters --------------------------------------------------------
    def get_params(self):
        raise NotImplementedError()

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        raise NotImplementedError()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def save_params(self, fname):
        arg_params, aux_params = self.get_params()
        save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
        save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
        from ..ndarray import save
        save(fname, save_dict)

    def load_params(self, fname):
        from ..ndarray import load
        save_dict = load(fname)
        arg_params = {}
        aux_params = {}
        for k, value in save_dict.items():
            arg_type, name = k.split(":", 1)
            if arg_type == "arg":
                arg_params[name] = value
            elif arg_type == "aux":
                aux_params[name] = value
            else:
                raise ValueError("Invalid param file " + fname)
        self.set_params(arg_params, aux_params)

    def get_states(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        assert not merge_multi_context
        return []

    def set_states(self, states=None, value=None):
        assert self.binded and self.params_initialized
        assert not states and not value

    def install_monitor(self, mon):
        raise NotImplementedError()

    # -- computation -------------------------------------------------------
    def prepare(self, data_batch, sparse_row_id_fn=None):
        """Prepare for processing a batch (row-sparse pull hook in the
        reference; no-op here)."""

    def _flush_numerics(self):
        """Drain the bound executor's deferred numerics sentinel (the
        per-step verdict is read one step behind); no-op for modules
        without a fused-step executor."""
        exe = getattr(self, "_exec", None)
        if exe is not None and hasattr(exe, "flush_numerics"):
            exe.flush_numerics()

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError()

    def backward(self, out_grads=None):
        raise NotImplementedError()

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError()

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError()

    def update(self):
        raise NotImplementedError()

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        raise NotImplementedError()

    # -- binding -----------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError()


class _BatchEndParam(object):
    """Callback parameter bundle (reference: model.py BatchEndParam)."""

    def __init__(self, epoch, nbatch, eval_metric, locals=None):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = locals
