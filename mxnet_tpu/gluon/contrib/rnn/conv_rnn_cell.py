"""Convolutional recurrent cells (reference:
gluon/contrib/rnn/conv_rnn_cell.py; Shi et al. 2015 ConvLSTM). The
input-to-hidden and hidden-to-hidden transforms are convolutions, so
states carry spatial structure: state shape = (batch, hidden_channels,
*spatial)."""
from __future__ import annotations

from ...rnn.rnn_cell import HybridRecurrentCell

__all__ = ["Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell"]


def _init(v):
    from ....initializer import create as _create
    return _create(v) if isinstance(v, str) else v


def _tup(x, n):
    if isinstance(x, int):
        return (x,) * n
    assert len(x) == n
    return tuple(x)


class _BaseConvRNNCell(HybridRecurrentCell):
    """Shared conv-recurrent plumbing (reference:
    conv_rnn_cell.py:37 _BaseConvRNNCell)."""

    def __init__(self, input_shape, hidden_channels, i2h_kernel,
                 h2h_kernel, i2h_pad, activation, n_gates, dims,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super(_BaseConvRNNCell, self).__init__(prefix=prefix,
                                               params=params)
        self._dims = dims
        self._input_shape = tuple(input_shape)   # (C_in, *spatial)
        self._hidden_channels = hidden_channels
        self._activation = activation
        self._n_gates = n_gates
        self._i2h_kernel = _tup(i2h_kernel, dims)
        self._h2h_kernel = _tup(h2h_kernel, dims)
        for k in self._h2h_kernel:
            assert k % 2 == 1, \
                "h2h kernel must be odd to preserve the state's " \
                "spatial shape (got %s)" % (self._h2h_kernel,)
        self._i2h_pad = _tup(i2h_pad, dims)
        self._h2h_pad = tuple(k // 2 for k in self._h2h_kernel)
        c_in = self._input_shape[0]
        out_ch = n_gates * hidden_channels
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(out_ch, c_in) + self._i2h_kernel,
            init=_init(i2h_weight_initializer), allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight",
            shape=(out_ch, hidden_channels) + self._h2h_kernel,
            init=_init(h2h_weight_initializer), allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(out_ch,),
            init=_init(i2h_bias_initializer), allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(out_ch,),
            init=_init(h2h_bias_initializer), allow_deferred_init=True)

    def _state_spatial(self):
        # i2h conv with stride 1: spatial' = spatial + 2*pad - k + 1
        return tuple(s + 2 * p - k + 1 for s, p, k in
                     zip(self._input_shape[1:], self._i2h_pad,
                         self._i2h_kernel))

    def state_info(self, batch_size=0):
        shape = (batch_size, self._hidden_channels) + self._state_spatial()
        return [{"shape": shape, "__layout__": "NC" + "DHW"[-self._dims:]}]

    def _conv_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                      i2h_bias, h2h_bias):
        out_ch = self._n_gates * self._hidden_channels
        i2h = F.Convolution(inputs, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel, pad=self._i2h_pad,
                            num_filter=out_ch)
        h2h = F.Convolution(states[0], h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel, pad=self._h2h_pad,
                            num_filter=out_ch)
        return i2h, h2h

    def infer_shape(self, x):
        pass                                     # shapes are explicit


class _ConvRNNCell(_BaseConvRNNCell):
    def __init__(self, input_shape, hidden_channels, i2h_kernel,
                 h2h_kernel, i2h_pad=0, activation="tanh", dims=2,
                 **kwargs):
        super(_ConvRNNCell, self).__init__(
            input_shape, hidden_channels, i2h_kernel, h2h_kernel,
            i2h_pad, activation, n_gates=1, dims=dims, **kwargs)

    def _alias(self):
        return "conv_rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        output = F.Activation(i2h + h2h, act_type=self._activation)
        return output, [output]


class _ConvLSTMCell(_BaseConvRNNCell):
    def __init__(self, input_shape, hidden_channels, i2h_kernel,
                 h2h_kernel, i2h_pad=0, activation="tanh", dims=2,
                 **kwargs):
        super(_ConvLSTMCell, self).__init__(
            input_shape, hidden_channels, i2h_kernel, h2h_kernel,
            i2h_pad, activation, n_gates=4, dims=dims, **kwargs)

    def _alias(self):
        return "conv_lstm"

    def state_info(self, batch_size=0):
        shape = (batch_size, self._hidden_channels) + self._state_spatial()
        layout = "NC" + "DHW"[-self._dims:]
        return [{"shape": shape, "__layout__": layout},
                {"shape": shape, "__layout__": layout}]

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        gates = i2h + h2h
        slices = F.SliceChannel(gates, num_outputs=4, axis=1)
        in_gate = F.sigmoid(slices[0])
        forget_gate = F.sigmoid(slices[1])
        in_transform = F.Activation(slices[2],
                                    act_type=self._activation)
        out_gate = F.sigmoid(slices[3])
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * F.Activation(next_c,
                                         act_type=self._activation)
        return next_h, [next_h, next_c]


class _ConvGRUCell(_BaseConvRNNCell):
    def __init__(self, input_shape, hidden_channels, i2h_kernel,
                 h2h_kernel, i2h_pad=0, activation="tanh", dims=2,
                 **kwargs):
        super(_ConvGRUCell, self).__init__(
            input_shape, hidden_channels, i2h_kernel, h2h_kernel,
            i2h_pad, activation, n_gates=3, dims=dims, **kwargs)

    def _alias(self):
        return "conv_gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        i2h_s = F.SliceChannel(i2h, num_outputs=3, axis=1)
        h2h_s = F.SliceChannel(h2h, num_outputs=3, axis=1)
        reset = F.sigmoid(i2h_s[0] + h2h_s[0])
        update = F.sigmoid(i2h_s[1] + h2h_s[1])
        new = F.Activation(i2h_s[2] + reset * h2h_s[2],
                           act_type=self._activation)
        next_h = update * states[0] + (1.0 - update) * new
        return next_h, [next_h]


def _make(base, dims, doc_kind):
    class _Cell(base):
        def __init__(self, input_shape, hidden_channels, i2h_kernel,
                     h2h_kernel, i2h_pad=0, activation="tanh", **kwargs):
            super(_Cell, self).__init__(input_shape, hidden_channels,
                                        i2h_kernel, h2h_kernel,
                                        i2h_pad=i2h_pad,
                                        activation=activation,
                                        dims=dims, **kwargs)
    _Cell.__name__ = "Conv%dD%sCell" % (dims, doc_kind)
    _Cell.__qualname__ = _Cell.__name__
    _Cell.__doc__ = ("%dD convolutional %s cell (reference: gluon/"
                     "contrib/rnn/conv_rnn_cell.py Conv%dD%sCell)."
                     % (dims, doc_kind, dims, doc_kind))
    return _Cell


Conv1DRNNCell = _make(_ConvRNNCell, 1, "RNN")
Conv2DRNNCell = _make(_ConvRNNCell, 2, "RNN")
Conv3DRNNCell = _make(_ConvRNNCell, 3, "RNN")
Conv1DLSTMCell = _make(_ConvLSTMCell, 1, "LSTM")
Conv2DLSTMCell = _make(_ConvLSTMCell, 2, "LSTM")
Conv3DLSTMCell = _make(_ConvLSTMCell, 3, "LSTM")
Conv1DGRUCell = _make(_ConvGRUCell, 1, "GRU")
Conv2DGRUCell = _make(_ConvGRUCell, 2, "GRU")
Conv3DGRUCell = _make(_ConvGRUCell, 3, "GRU")
