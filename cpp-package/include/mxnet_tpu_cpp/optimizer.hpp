// C++ optimizers over the in-place update operators.
// Capability analog of the reference's cpp-package/include/mxnet-cpp/
// optimizer.h: per-parameter state, Update(index, weight, grad); the
// math runs in the framework's fused update ops (ops/optimizer_ops.py)
// via MXImperativeInvoke, exactly like the reference routes through
// its registered optimizer kernels.
#ifndef MXNET_TPU_CPP_OPTIMIZER_HPP_
#define MXNET_TPU_CPP_OPTIMIZER_HPP_

#include <map>
#include <string>
#include <utility>

#include "mxnet_tpu_cpp/ndarray.hpp"

namespace mxnet_tpu_cpp {

class SGDOptimizer {
 public:
  explicit SGDOptimizer(float lr, float momentum = 0.0f, float wd = 0.0f)
      : lr_(lr), momentum_(momentum), wd_(wd) {}

  void Update(int index, NDArray* weight, const NDArray& grad) {
    AttrMapOf attrs = {{"lr", std::to_string(lr_)},
                       {"wd", std::to_string(wd_)}};
    if (momentum_ == 0.0f) {
      InvokeInPlace("sgd_update", {weight, &grad}, attrs);
      return;
    }
    attrs["momentum"] = std::to_string(momentum_);
    auto it = states_.find(index);
    if (it == states_.end()) {
      NDArray mom(weight->Shape());
      it = states_.emplace(index, std::move(mom)).first;
    }
    InvokeInPlace("sgd_mom_update", {weight, &grad, &it->second}, attrs);
  }

  void SetLR(float lr) { lr_ = lr; }

 private:
  using AttrMapOf = std::map<std::string, std::string>;
  float lr_, momentum_, wd_;
  std::map<int, NDArray> states_;
};

}  // namespace mxnet_tpu_cpp

#endif  // MXNET_TPU_CPP_OPTIMIZER_HPP_
