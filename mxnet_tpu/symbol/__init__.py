"""Symbol package: graph construction + generated op namespace
(reference: python/mxnet/symbol/__init__.py)."""
from .symbol import (Symbol, Variable, var, Group, load, load_json,
                     AUX_STATES, AttrScope)
from . import _internal

from . import register as _register
_register.populate(__name__, __package__ + "._internal")

# sub-namespaces over the generated ops (reference: symbol/{contrib,
# linalg,random}.py) — imported AFTER populate so they can bind ops
from . import contrib          # noqa: E402,F401
from . import linalg           # noqa: E402,F401
from . import random           # noqa: E402,F401


def zeros(shape, dtype="float32", name=None):
    from . import _zeros
    return _zeros(shape=tuple(shape) if not isinstance(shape, int) else (shape,),
                  dtype=dtype, name=name)


def ones(shape, dtype="float32", name=None):
    from . import _ones
    return _ones(shape=tuple(shape) if not isinstance(shape, int) else (shape,),
                 dtype=dtype, name=name)
