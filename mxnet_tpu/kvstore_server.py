"""Parameter-server process for the distributed KVStore (DCN path).

Reference: src/kvstore/kvstore_dist_server.h:155 (request handlers at
:331-337, sync aggregation + ApplyUpdates at :346) and
python/mxnet/kvstore_server.py:65-73 (worker-side bootstrap).

TPU-native split of responsibilities: *synchronous* data-parallel
gradient exchange rides XLA allreduce over ICI (see kvstore.py /
parallel.trainer) — no server round-trip. What still needs a host-side
parameter server is the DCN tier: asynchronous updates, sparse
embedding pulls, elastic membership, and cross-pod coordination. This
server provides that tier as a threaded TCP service speaking a
length-prefixed pickle protocol:

  INIT / PUSH / PULL / BARRIER / SET_OPTIMIZER / SET_COMPRESSION / STOP

Sync mode (``dist_tpu_sync``): pushes are aggregated per key; the
round completes when all workers contributed, then the server applies
the updater (or stores the summed gradient when no optimizer is
installed — the reference's DataHandleDefault behavior used by its
dist tests). Async mode (``dist_async``): every push updates
immediately — stragglers never block (kvstore.cc:55-57 semantics).

Self-healing (the ps-lite node-recovery analog, kvstore.h:353):

* **Failover** — with a snapshot path configured the server
  crash-consistently snapshots its state (store, barrier generation,
  per-rank RPC-dedup commit records, membership epochs, server-side
  optimizer state) through ``checkpoint.atomic_writer``. In sync mode
  every committed round snapshots BEFORE any worker is acked, so an
  acked update is never lost and an unacked one is resent and
  deduplicated — a restarted ``--restore`` server resumes bitwise.
  Async mode throttles snapshots to ``MXNET_KV_SNAPSHOT_S`` (the
  documented failover staleness window). Every response carries the
  server's **incarnation id**; clients detect a restart, re-register,
  and replay in-flight RPCs under their original sequence numbers.
* **Elastic membership** — rank liveness from RPC traffic plus client
  heartbeats (``MXNET_KV_DEAD_S``). A dead rank fails sync rounds and
  barriers FAST with an error naming the rank(s) instead of hanging;
  in async mode workers may leave and rejoin (HELLO re-registers,
  bumping the rank's membership epoch) without blocking anyone.

Roles resolve from env like the reference's DMLC_ROLE:
``MXNET_TPU_ROLE`` in {server, worker, scheduler},
``MXNET_TPU_PS_URI``/``MXNET_TPU_PS_PORT``, ``MXNET_TPU_NUM_WORKERS``,
``MXNET_TPU_RANK`` (set by tools/launch.py).
"""
from __future__ import annotations

import logging
import os
import pickle
import select
import socket
import struct
import threading
import time
import zlib

import numpy as np

from . import fault as _fault
from . import telemetry as _tm
from . import tracing as _tr
from .base import MXNetError
from .fault import FaultInjected, PartitionError, TransientKVError

__all__ = ["KVStoreServer", "send_msg", "recv_msg", "serve_forever"]

_LEN = struct.Struct("!Q")
_SNAP_MAGIC = b"MXKVSNAP"
_SNAP_FORMAT = 1

# ops that mutate server state; their RPCs carry a client-assigned
# sequence number and are deduplicated per rank (at-most-once apply
# under worker retries/reconnects)
_MUTATING_OPS = frozenset(
    ("PUSH", "INIT", "SET_OPTIMIZER", "SET_COMPRESSION", "BARRIER"))


def send_msg(sock, obj):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def recv_msg(sock):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, n))


def _conn_dead(conn):
    """True when ``conn``'s peer is gone. Only valid while the peer is
    awaiting OUR response (strict request/response protocol): a
    readable socket mid-handle means EOF or RST, never a real
    message."""
    try:
        readable, _, _ = select.select([conn], [], [], 0)
        if not readable:
            return False
        return conn.recv(1, socket.MSG_PEEK) == b""
    except (OSError, ValueError):
        return True


class KVStoreServer(object):
    """Threaded PS: one handler thread per worker connection."""

    def __init__(self, port=0, num_workers=1, sync_mode=True,
                 bind_addr=None, token=None, snapshot_path=None,
                 snapshot_every_s=None, restore=False,
                 dead_timeout_s=None):
        from .config import get as _cfg
        self._store = {}
        self._pending = {}          # key -> {"sum", "count", "contribs"}
        self._versions = {}
        self._updater = None
        self._compressor = None
        self._compression_params = None
        self._num_workers = num_workers
        self._sync = sync_mode
        # The wire format is pickle: auth is a mandatory shared token for
        # any non-loopback bind (the transport itself must still be a
        # trusted network, like the reference's ps-lite/zmq).
        self._token = token if token is not None else \
            os.environ.get("MXNET_TPU_PS_TOKEN", "")
        bind_addr = bind_addr if bind_addr is not None else \
            os.environ.get("MXNET_TPU_PS_BIND", "127.0.0.1")
        if bind_addr != "127.0.0.1" and not self._token:
            raise ValueError("non-loopback PS bind requires "
                             "MXNET_TPU_PS_TOKEN to be set")
        self._lock = threading.Lock()
        self._round_done = threading.Condition(self._lock)
        # per-rank RPC dedup: rank -> {"seq", "done", "resp"} for the
        # most recent mutating RPC (see _client_loop). Bounded at one
        # entry per rank: a newer seq evicts the acked predecessor.
        self._seq_cond = threading.Condition()
        self._rank_rpc = {}
        self._barrier_waiting = 0
        self._barrier_gen = 0
        self._barrier_contribs = []  # [(rank, seq, t_arrival)] this gen
        self._start_time = time.monotonic()
        self._last_seen = {}        # rank -> monotonic seconds
        self._member_epoch = {}     # rank -> registration count
        self._dead_declared = set()  # ranks currently declared dead
        self._handling = {}         # rank -> in-flight handler count
        self._applied_seq = {}      # rank -> last committed mutating seq
        # failover identity: a fresh server draws a random incarnation,
        # a restored one continues the snapshot's + 1 — every response
        # carries it so clients can tell "same server" from "restarted"
        self.incarnation = int.from_bytes(os.urandom(4), "big")
        self._snapshot_path = snapshot_path if snapshot_path is not None \
            else (_cfg("MXNET_KV_SNAPSHOT_PATH") or None)
        self._snapshot_every_s = float(
            _cfg("MXNET_KV_SNAPSHOT_S") if snapshot_every_s is None
            else snapshot_every_s)
        self._dead_s = float(_cfg("MXNET_KV_DEAD_S") if dead_timeout_s
                             is None else dead_timeout_s)
        self._wait_tick = min(1.0, max(0.05, self._dead_s / 4.0))
        self._last_snapshot = time.monotonic()
        if restore:
            self._restore_snapshot()
        self._stop = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((bind_addr, port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]

    # -- snapshot / restore (failover) -------------------------------------
    def _commit_locked(self, rank, seq):
        """Record that ``rank``'s mutating RPC ``seq`` is applied. The
        map travels in every snapshot and reseeds the dedup cache on
        restore, so a resent RPC whose first copy committed before the
        crash replays its ack instead of re-applying."""
        if rank is not None and seq is not None:
            self._applied_seq[rank] = seq

    def _commit_round_locked(self, contribs, straggler=True):
        """Commit every contributor of a completed sync round / barrier
        and expose the round's straggler profile: gauge = how long the
        round had to wait for each rank after the first arrival."""
        if not contribs:
            return
        t_first = min(c[2] for c in contribs)
        for c in contribs:
            rank, seq, t = c[0], c[1], c[2]
            self._commit_locked(rank, seq)
            if straggler and rank is not None and _tm._enabled:
                _tm.gauge("kvstore/straggler_seconds",
                          "Seconds after the round's first push that "
                          "this rank's contribution arrived (the round "
                          "completes at the max over ranks)",
                          ("rank",)).labels(str(rank)).set(t - t_first)

    def _snapshot_locked(self, force=False):
        """Crash-consistent state snapshot via the atomic
        write-temp→fsync→rename path. Called with ``self._lock`` held,
        in the SAME critical section as the mutation it commits and
        BEFORE any worker is acked — in sync mode that makes the
        snapshot the round's commit record (acked ⇒ snapshotted,
        unsnapshotted ⇒ unacked ⇒ the client resends). Async mode
        throttles to one snapshot per ``MXNET_KV_SNAPSHOT_S`` unless
        forced. A snapshot that fails to write degrades (logged +
        counted), it never fails the RPC whose apply already
        happened.

        Cost model: each snapshot pickles the WHOLE store under the
        server lock, so sync-mode failover writes O(total state) per
        committed round per key — and key INITs snapshot too (an acked
        INIT lost on failover would KeyError every later push of the
        key; an init burst of N keys costs N snapshots of the growing
        store, one-time). That is correctness-first by design
        and sized for this server's role — the DCN *coordination* tier
        (async/elastic updates, sparse rows, barriers; ROADMAP keeps
        bulk sync gradient traffic on XLA collectives). Do not enable
        sync snapshots for a store holding bulk model weights; an
        incremental commit log is the upgrade path if that need
        appears."""
        if not self._snapshot_path:
            return False
        now = time.monotonic()
        if not force and now - self._last_snapshot < self._snapshot_every_s:
            return False
        t0 = time.perf_counter()
        try:
            _fault.inject("kv.server.snapshot")
            state = {
                "format": _SNAP_FORMAT,
                "incarnation": self.incarnation,
                "sync": self._sync,
                "num_workers": self._num_workers,
                "store": {k: np.asarray(v)
                          for k, v in self._store.items()},
                "versions": dict(self._versions),
                "barrier_gen": self._barrier_gen,
                "applied_seq": dict(self._applied_seq),
                "member_epoch": dict(self._member_epoch),
                "updater_states": (
                    self._updater.get_states(dump_optimizer=True)
                    if self._updater is not None else None),
                "compression_params": self._compression_params,
            }
            payload = pickle.dumps(state,
                                   protocol=pickle.HIGHEST_PROTOCOL)
            from .checkpoint import atomic_writer
            with atomic_writer(self._snapshot_path) as f:
                f.write(_SNAP_MAGIC)
                f.write(_LEN.pack(len(payload)))
                f.write(struct.pack("!I", zlib.crc32(payload)
                                    & 0xFFFFFFFF))
                f.write(payload)
        except Exception:
            logging.exception(
                "kvstore server snapshot to %r failed; state kept in "
                "memory, failover falls back to the previous snapshot",
                self._snapshot_path)
            if _tm._enabled:
                _tm.counter("kvstore/snapshot_failures_total",
                            "Server state snapshots that failed to "
                            "write").inc()
            return False
        self._last_snapshot = now
        if _tm._enabled:
            _tm.counter("kvstore/snapshots_total",
                        "Server state snapshots written").inc()
            _tm.histogram("kvstore/snapshot_seconds",
                          "Wall time of one server state snapshot "
                          "(serialize + atomic write)").observe(
                time.perf_counter() - t0)
        return True

    def _restore_snapshot(self):
        """Resume from the snapshot at ``self._snapshot_path``. A
        missing file starts fresh with a warning (first launch of a
        supervised server); a torn or corrupt file raises an
        ``MXNetError`` naming exactly what failed — silently starting
        empty would discard state the operator believes is saved."""
        path = self._snapshot_path
        if not path or not os.path.exists(path):
            logging.warning(
                "kvstore server restore requested but no snapshot at "
                "%r; starting fresh", path)
            return False
        with open(path, "rb") as f:
            blob = f.read()
        head = len(_SNAP_MAGIC) + _LEN.size + 4
        if len(blob) < head or not blob.startswith(_SNAP_MAGIC):
            raise MXNetError(
                "kvstore snapshot %r is not a snapshot file "
                "(bad magic)" % path)
        off = len(_SNAP_MAGIC)
        (n,) = _LEN.unpack(blob[off:off + _LEN.size])
        off += _LEN.size
        (crc,) = struct.unpack("!I", blob[off:off + 4])
        off += 4
        payload = blob[off:off + n]
        if len(payload) != n:
            raise MXNetError(
                "kvstore snapshot %r is truncated: %d of %d payload "
                "bytes" % (path, len(payload), n))
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            raise MXNetError(
                "kvstore snapshot %r fails its checksum" % path)
        try:
            state = pickle.loads(payload)
        except Exception as e:
            raise MXNetError(
                "kvstore snapshot %r payload does not deserialize "
                "(%s)" % (path, e)) from e
        # the snapshot's cluster shape must match this launch: restoring
        # sync commit records / barrier generations into a different
        # mode or world size would produce confusing hangs and dedup
        # misfires instead of this clear error (delete the snapshot to
        # start the resized cluster fresh)
        if bool(state.get("sync", self._sync)) != self._sync:
            raise MXNetError(
                "kvstore snapshot %r was taken in %s mode but the "
                "server was started in %s mode"
                % (path, "sync" if state.get("sync") else "async",
                   "sync" if self._sync else "async"))
        snap_nw = int(state.get("num_workers", self._num_workers))
        if snap_nw != self._num_workers:
            raise MXNetError(
                "kvstore snapshot %r was taken with num_workers=%d but "
                "the server was started with num_workers=%d"
                % (path, snap_nw, self._num_workers))
        self._store = {k: np.array(v)
                       for k, v in state["store"].items()}
        self._versions = dict(state["versions"])
        self._barrier_gen = int(state["barrier_gen"])
        self._applied_seq = dict(state["applied_seq"])
        self._member_epoch = dict(state.get("member_epoch", {}))
        # reseed the dedup cache from the commit records: a worker
        # resending the RPC it never got an ack for either finds its
        # seq here (first copy committed — replay the ack) or not
        # (first copy died uncommitted — re-execute)
        with self._seq_cond:
            for rank, seq in self._applied_seq.items():
                self._rank_rpc[rank] = {"seq": seq, "done": True,
                                        "resp": ("OK", None),
                                        "spans": None}
        if state.get("updater_states"):
            from .optimizer import get_updater
            blob_states = state["updater_states"]
            # get_states(dump_optimizer=True) payloads are
            # (states, optimizer); rebuild the updater around the
            # pickled optimizer, then restore its slot states
            _states, opt = pickle.loads(blob_states)
            upd = get_updater(opt)
            upd.set_states(blob_states)
            self._updater = upd
        if state.get("compression_params"):
            from .gradient_compression import create_compressor
            self._compression_params = dict(state["compression_params"])
            self._compressor = create_compressor(self._compression_params)
        self.incarnation = (int(state["incarnation"]) + 1) & 0xFFFFFFFF
        logging.info(
            "kvstore server restored from %r: %d keys, barrier "
            "generation %d, incarnation %d", path, len(self._store),
            self._barrier_gen, self.incarnation)
        return True

    # -- membership / liveness ---------------------------------------------
    def _mark_dead_locked(self, rank):
        """Declare ``rank`` dead immediately (its connection EOF'd
        mid-round): age its heartbeat past the timeout so every waiter
        fails fast instead of waiting out ``MXNET_KV_DEAD_S``."""
        if rank is None:
            return
        self._last_seen[rank] = time.monotonic() - self._dead_s - 1.0
        self._dead_declared.add(rank)

    def _dead_ranks_locked(self, timeout=None):
        """Ranks with no traffic for ``timeout`` seconds. A rank with an
        RPC currently being handled is alive by definition (sync
        pushes/barriers park inside the handler for the whole round);
        a parked rank whose socket died is unmasked by its own handler
        thread via :func:`_conn_dead`. Never-connected ranks get a
        grace period from server start."""
        timeout = self._dead_s if timeout is None else timeout
        now = time.monotonic()
        dead = []
        for r in range(self._num_workers):
            if self._handling.get(r, 0) > 0:
                continue
            silent = now - self._last_seen.get(r, self._start_time)
            if silent > timeout:
                dead.append(r)
                # membership DECLARATION keys on the cluster timeout,
                # never the query's: an observational DEAD_NODES probe
                # with a short timeout must not manufacture deaths (and
                # therefore fake rejoins) for healthy ranks between
                # heartbeats
                if silent > self._dead_s:
                    self._dead_declared.add(r)
        return dead

    def _wait_round_locked(self, done_fn, rank, conn, what, on_fail=None):
        """Park inside a sync round/barrier until ``done_fn()`` or the
        round becomes impossible. Returns None on success or an
        ``("ERR", msg)`` response naming the dead rank(s); raises
        ``ConnectionError`` when OUR worker's socket died mid-wait (no
        response is deliverable — the rank is marked dead on the spot
        so peers fail fast too). ``on_fail`` undoes this waiter's
        contribution before either exit."""
        t0 = time.monotonic()
        while not done_fn() and not self._stop.is_set():
            self._round_done.wait(timeout=self._wait_tick)
            if done_fn() or self._stop.is_set():
                break
            if conn is not None and _conn_dead(conn):
                if on_fail is not None:
                    on_fail()
                self._mark_dead_locked(rank)
                self._round_done.notify_all()
                raise ConnectionError(
                    "rank %s connection died while parked in %s"
                    % (rank, what))
            dead = self._dead_ranks_locked()
            dead = [r for r in dead if r != rank]
            if dead:
                if on_fail is not None:
                    on_fail()
                self._round_done.notify_all()
                return ("ERR",
                        "%s cannot complete: rank(s) %s declared dead "
                        "(no heartbeat for %.1fs; liveness timeout "
                        "MXNET_KV_DEAD_S=%.1f, waited %.1fs)"
                        % (what, dead, self._dead_s, self._dead_s,
                           time.monotonic() - t0))
        if self._stop.is_set() and not done_fn():
            # server stopping with this round incomplete: the update was
            # NOT applied and is NOT in the shutdown snapshot — a
            # success ack here would silently lose it (commit-before-ack
            # violation). RETRY makes the client resend, under the same
            # seq, to the --restore successor.
            if on_fail is not None:
                on_fail()
            return ("RETRY",
                    "%s aborted: server stopping before the round "
                    "completed (update not applied; resend reaches the "
                    "restarted server)" % what)
        return None

    # -- request handlers --------------------------------------------------
    def _decompress(self, value):
        if self._compressor is not None and isinstance(value, tuple):
            payload, shape = value
            return self._compressor.decompress(payload, shape)
        return value

    def _handle(self, op, key=None, value=None, rank=None, seq=None,
                conn=None):
        _fault.inject("kv.server")
        if op == "INIT":
            with self._lock:
                # rank-0 init wins; later INITs for the key are ignored
                # (reference: kvstore_dist.h rank-0 init + broadcast).
                # dtype is preserved: fp16/bf16 weights stay what the
                # worker declared. A rejoining worker's INIT is a
                # no-op: the server-side (current) weights win.
                if key not in self._store:
                    self._store[key] = np.array(value)
                    self._versions[key] = 0
                    self._commit_locked(rank, seq)
                    self._snapshot_locked(force=True)
                else:
                    self._commit_locked(rank, seq)
            return ("OK", None)
        if op == "PUSH":
            grad = self._decompress(value)
            with self._lock:
                if self._sync:
                    slot = self._pending.setdefault(
                        key, {"sum": np.zeros_like(self._store[key]),
                              "count": 0, "contribs": []})
                    slot["sum"] = slot["sum"] + grad
                    slot["count"] += 1
                    slot["contribs"].append((rank, seq,
                                             time.monotonic()))
                    if slot["count"] == self._num_workers:
                        self._apply(key, slot["sum"])
                        del self._pending[key]
                        self._versions[key] += 1
                        self._commit_round_locked(slot["contribs"])
                        # the round's commit record: written before any
                        # contributor is acked, so a SIGKILL can never
                        # lose an acked update nor double an unacked one
                        self._snapshot_locked(force=True)
                        self._round_done.notify_all()
                    else:
                        v = self._versions[key]
                        err = self._wait_round_locked(
                            lambda: self._versions[key] != v,
                            rank=rank, conn=conn,
                            what="sync push round for key %r (version "
                                 "%d)" % (key, v),
                            on_fail=lambda: self._pending.pop(key, None))
                        if err is not None:
                            return err
                else:
                    self._apply(key, grad)
                    self._versions[key] += 1
                    self._commit_locked(rank, seq)
                    self._snapshot_locked()
            return ("OK", None)
        if op == "PULL":
            with self._lock:
                return ("OK", self._store[key].copy())
        if op == "PULL_ROWS":
            with self._lock:
                rows = np.asarray(value, np.int64)
                return ("OK", self._store[key][rows].copy())
        if op == "BARRIER":
            with self._lock:
                gen = self._barrier_gen
                # a parked participant whose socket died must not count
                # toward the rendezvous: completing a barrier with a
                # ghost would hand the generation to a rank that can
                # never proceed past it. Sweep before counting.
                ghosts = [c for c in self._barrier_contribs
                          if c[3] is not None and _conn_dead(c[3])]
                for c in ghosts:
                    self._barrier_contribs.remove(c)
                    self._barrier_waiting -= 1
                    self._mark_dead_locked(c[0])
                if ghosts:
                    self._round_done.notify_all()
                entry = [rank, seq, time.monotonic(), conn]
                self._barrier_waiting += 1
                self._barrier_contribs.append(entry)
                if self._barrier_waiting == self._num_workers:
                    self._barrier_waiting = 0
                    self._commit_round_locked(self._barrier_contribs,
                                              straggler=False)
                    self._barrier_contribs = []
                    self._barrier_gen += 1
                    self._snapshot_locked(force=True)
                    self._round_done.notify_all()
                else:
                    def _withdraw():
                        # idempotent: a peer's ghost-sweep may have
                        # withdrawn this entry already
                        if entry in self._barrier_contribs:
                            self._barrier_contribs.remove(entry)
                            self._barrier_waiting -= 1
                    err = self._wait_round_locked(
                        lambda: self._barrier_gen != gen,
                        rank=rank, conn=conn,
                        what="barrier (generation %d)" % gen,
                        on_fail=_withdraw)
                    if err is not None:
                        return err
            return ("OK", None)
        if op == "SET_OPTIMIZER":
            from .optimizer import get_updater
            opt = pickle.loads(value)
            with self._lock:
                self._updater = get_updater(opt)
                self._commit_locked(rank, seq)
                self._snapshot_locked(force=True)
            return ("OK", None)
        if op == "SET_COMPRESSION":
            from .gradient_compression import create_compressor
            with self._lock:
                self._compression_params = dict(value)
                self._compressor = create_compressor(value)
                self._commit_locked(rank, seq)
                self._snapshot_locked(force=True)
            return ("OK", None)
        if op == "HELLO":
            # rank registration + heartbeat (reference: ps-lite node
            # liveness behind kvstore.h:353 get_num_dead_node). A HELLO
            # from a rank currently declared dead is a REJOIN: its
            # membership epoch bumps and the cluster re-admits it.
            r = int(value)
            with self._lock:
                # a re-registration after silence past the liveness
                # bound is a rejoin even if nothing ever OBSERVED the
                # death (pure-async clusters have no sync waiter or
                # DEAD_NODES probe to populate _dead_declared): a live
                # client's heartbeats run at a third of the bound, so
                # this much silence means the process was gone
                rejoined = r in self._dead_declared or (
                    r in self._member_epoch
                    and time.monotonic()
                    - self._last_seen.get(r, self._start_time)
                    > self._dead_s)
                if r not in self._member_epoch:
                    self._member_epoch[r] = 1
                elif rejoined:
                    self._member_epoch[r] += 1
                    if _tm._enabled:
                        _tm.counter(
                            "kvstore/worker_rejoins_total",
                            "Ranks re-admitted after being declared "
                            "dead", ("rank",)).labels(str(r)).inc()
                    try:
                        from . import blackbox as _bb
                        _bb.record_event(
                            "rejoin", rank=r,
                            member_epoch=self._member_epoch[r])
                    except Exception:
                        pass
                self._dead_declared.discard(r)
                self._last_seen[r] = time.monotonic()
                return ("OK", {"incarnation": self.incarnation,
                               "barrier_gen": self._barrier_gen,
                               "member_epoch": self._member_epoch[r],
                               "num_workers": self._num_workers,
                               "mode": "sync" if self._sync
                               else "async"})
        if op == "DEAD_NODES":
            timeout = self._dead_s if value is None else float(value)
            with self._lock:
                dead = self._dead_ranks_locked(timeout)
            return ("OK", dead)
        if op == "PROFILER":
            # remote profiler control from workers (reference:
            # KVStoreServerProfilerCommand kSetConfig/kState/kDump,
            # include/mxnet/kvstore.h:49): runs against THIS server
            # process's profiler so its own timeline is captured
            from . import profiler as _prof
            if key == "set_config":
                _prof.set_config(**value)
            elif key == "state":
                _prof.set_state(value)
            elif key == "dump":
                _prof.dump(finished=bool(value))
            else:
                return ("ERR", "unknown profiler command %r" % key)
            return ("OK", None)
        if op == "STOP":
            with self._lock:
                self._snapshot_locked(force=True)
            self._stop.set()
            with self._lock:
                self._round_done.notify_all()
            return ("OK", None)
        return ("ERR", "unknown op %r" % op)

    def _apply(self, key, agg):
        """ApplyUpdates (kvstore_dist_server.h:346): updater if present,
        else store the aggregate (reference test semantics)."""
        if self._updater is not None:
            from .ndarray.ndarray import NDArray, array
            w = array(self._store[key])
            self._updater(key, array(agg), w)
            self._store[key] = w.asnumpy()
        else:
            self._store[key] = np.asarray(agg, self._store[key].dtype)

    # -- socket loop -------------------------------------------------------
    def _client_loop(self, conn):
        rank = None
        try:
            if self._token:
                # first message must be the shared token (AUTH, None, tok)
                msg = recv_msg(conn)
                if msg[0] != "AUTH" or msg[2] != self._token:
                    send_msg(conn, ("ERR", "auth failed"))
                    return
                send_msg(conn, ("OK", None))
            while not self._stop.is_set():
                msg = recv_msg(conn)
                # wire compat: (op[, key[, value[, seq[, tctx]]]]) all
                # legal; tctx is the client's serialized span context
                op = msg[0]
                key = msg[1] if len(msg) > 1 else None
                value = msg[2] if len(msg) > 2 else None
                seq = msg[3] if len(msg) > 3 else None
                tctx = msg[4] if len(msg) > 4 else None
                # server spans recorded for THIS rpc collect here and
                # ship back inside the response, surfacing under the
                # client's trace
                sink = []
                tr_ctx = _tr.from_wire(tctx, sink=sink)
                if op == "HELLO":
                    rank = int(value)
                elif rank is not None:
                    # heartbeat BEFORE handling: sync PUSH/BARRIER block
                    # inside _handle waiting for stragglers, and a
                    # blocked-but-alive worker must not read as dead
                    with self._lock:
                        self._last_seen[rank] = time.monotonic()
                # replay shield: a worker that reconnected and resent a
                # mutating RPC whose first copy already ran (the reply
                # died with the old connection) must get that copy's
                # response, not a second apply — at-most-once under the
                # client retry policy
                ent = None
                dedup = None
                if seq is not None and rank is not None \
                        and op in _MUTATING_OPS:
                    t_c0 = time.perf_counter()
                    with self._seq_cond:
                        cur = self._rank_rpc.get(rank)
                        if cur is not None and cur["seq"] == seq:
                            while not cur["done"] and \
                                    not self._stop.is_set():
                                self._seq_cond.wait(1.0)
                            dedup = (cur["resp"] if cur["resp"]
                                     is not None else
                                     ("ERR", "duplicate rpc interrupted"))
                            orig_spans = list(cur.get("spans") or ())
                        else:
                            ent = {"seq": seq, "done": False,
                                   "resp": None, "spans": None}
                            self._rank_rpc[rank] = ent
                    if dedup is not None:
                        # at-most-once applies to observability too: the
                        # replay served from the seq-cache gets a span
                        # marked cached=true covering only the cache
                        # lookup, NOT a re-recorded handler latency; the
                        # original execution's spans are re-shipped (the
                        # first reply may have died with the old
                        # connection) and the client deduplicates them
                        # by span id
                        if tr_ctx is not None:
                            _tr.record_span(
                                "kv.server", tr_ctx, t_c0,
                                time.perf_counter(),
                                attrs={"op": op, "cached": True})
                        spans = orig_spans + sink
                        # (proc_token, server_now, spans): the token +
                        # clock reading let the client rebase a foreign
                        # perf_counter epoch, and ONLY a foreign one
                        out = dedup + (self.incarnation,)
                        if spans:
                            out += ((_tr._PROC_TOKEN,
                                     time.perf_counter(), spans),)
                        send_msg(conn, out)
                        continue
                t_h0 = time.perf_counter()
                if rank is not None:
                    with self._lock:
                        self._handling[rank] = \
                            self._handling.get(rank, 0) + 1
                resp = None
                try:
                    from . import profiler as _prof

                    def _execute():
                        if _prof.is_running() and op != "PROFILER":
                            # server-side op timeline for the remote
                            # profiler (reference: the PS server
                            # registers its handlers with the process
                            # profiler)
                            with _prof.scope("kvstore_" + op, "kvstore"):
                                return self._handle(op, key, value,
                                                    rank=rank, seq=seq,
                                                    conn=conn)
                        return self._handle(op, key, value, rank=rank,
                                            seq=seq, conn=conn)

                    if tr_ctx is not None:
                        with _tr.start_span("kv.server", ctx=tr_ctx,
                                            attrs={"op": op}):
                            resp = _execute()
                    else:
                        resp = _execute()
                except PartitionError:
                    # injected partition: drop the connection with NO
                    # response — the worker sees a vanished server
                    resp = None
                except ConnectionError:
                    # our worker's socket died mid-handle (unmasked by
                    # the round wait): nothing to respond to
                    resp = None
                except (TransientKVError, FaultInjected) as e:
                    # transient: tell the worker to retry (its transport
                    # layer backs off and resends with the same seq)
                    resp = ("RETRY", str(e))
                except Exception:
                    # surface handler failures to the worker instead of
                    # dropping the connection (the reference propagates
                    # server errors back through ps-lite responses)
                    import traceback
                    resp = ("ERR", traceback.format_exc())
                finally:
                    if rank is not None:
                        with self._lock:
                            n = self._handling.get(rank, 1) - 1
                            if n <= 0:
                                self._handling.pop(rank, None)
                            else:
                                self._handling[rank] = n
                    if ent is not None:
                        # resolve the dedup entry on EVERY exit path: a
                        # not-done entry left behind would park the
                        # rank's resent RPC forever
                        with self._seq_cond:
                            ent["done"] = True
                            ent["resp"] = resp if resp is not None else \
                                ("ERR", "connection lost mid-rpc")
                            ent["spans"] = list(sink)
                            if (resp is None or resp[0] != "OK") and \
                                    self._rank_rpc.get(rank) is ent:
                                # failed attempts must re-execute on
                                # retry, not replay the failure
                                del self._rank_rpc[rank]
                            self._seq_cond.notify_all()
                if resp is None:
                    raise ConnectionError("dropping client connection")
                if _tm._enabled:
                    # real executions only — the dedup path above never
                    # reaches here, so a replayed RPC cannot
                    # double-count handler latency
                    _tm.histogram(
                        "kvstore/server_handle_seconds",
                        "PS server request handling latency "
                        "(real executions; seq-cache replays excluded)",
                        ("op",)).labels(op).observe(
                        time.perf_counter() - t_h0,
                        trace_id=tr_ctx.trace_id if tr_ctx else None)
                out = resp + (self.incarnation,)
                if sink:
                    out += ((_tr._PROC_TOKEN, time.perf_counter(),
                             sink),)
                send_msg(conn, out)
                if op == "STOP":
                    break
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def serve_forever(self):
        self._sock.settimeout(1.0)
        threads = []
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=self._client_loop, args=(conn,),
                                 daemon=True)
            t.start()
            threads.append(t)
        self._sock.close()

    def start_background(self):
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    def stop(self):
        self._stop.set()


def serve_forever(argv=None):
    """Entry point for a server-role process (reference:
    kvstore_server.py _init_kvstore_server_module). ``--restore``
    resumes from the snapshot at ``--snapshot``/
    ``MXNET_KV_SNAPSHOT_PATH`` — the supervisor pattern is to launch
    with ``--restore`` from the start: a first run warns and starts
    fresh, every relaunch after a crash picks up the committed state."""
    import argparse
    ap = argparse.ArgumentParser(prog="mxnet_tpu.kvstore_server")
    ap.add_argument("--port", type=int, default=None,
                    help="listen port (default MXNET_TPU_PS_PORT)")
    ap.add_argument("--snapshot", default=None,
                    help="state snapshot file "
                         "(default MXNET_KV_SNAPSHOT_PATH)")
    ap.add_argument("--restore", action="store_true",
                    help="resume from the snapshot if one exists")
    # argv=None = programmatic caller (kv_run_server): env-only config,
    # never this process's unrelated sys.argv
    args = ap.parse_args(argv if argv is not None else [])
    port = args.port if args.port is not None else \
        int(os.environ.get("MXNET_TPU_PS_PORT", "9090"))
    nw = int(os.environ.get("MXNET_TPU_NUM_WORKERS", "1"))
    sync = os.environ.get("MXNET_TPU_PS_MODE", "sync") == "sync"
    server = KVStoreServer(port=port, num_workers=nw, sync_mode=sync,
                           snapshot_path=args.snapshot,
                           restore=args.restore)
    print("kvstore server listening on %d (workers=%d sync=%s "
          "incarnation=%d)" % (server.port, nw, sync,
                               server.incarnation), flush=True)
    server.serve_forever()


if __name__ == "__main__":
    import sys
    serve_forever(sys.argv[1:])
