"""Device mesh management.

Reference analog: the context lists passed to Module/-Trainer
(`ctx=[mx.gpu(0), mx.gpu(1), ...]`, executor_group.py:143) and the KVStore
device topology (comm_tree.h link solver). On TPU the mesh IS the
topology: axes map onto ICI rings, so laying out ('dp','tp') over a pod
slice makes gradient reduction ride ICI without any tree solver.
"""
from __future__ import annotations

import threading

__all__ = ["make_mesh", "current_mesh", "set_mesh", "data_parallel_sharding",
           "replicated_sharding"]

_state = threading.local()


def make_mesh(shape=None, axis_names=("dp",), devices=None):
    """Create a Mesh over the visible devices.

    ``shape``: tuple of axis sizes (product must divide the device count),
    or None to put every device on the first axis."""
    import jax
    import numpy as np
    devs = devices if devices is not None else jax.devices()
    if shape is None:
        shape = (len(devs),)
    shape = tuple(int(s) for s in shape)
    n = int(np.prod(shape))
    if n > len(devs):
        raise ValueError("mesh shape %s needs %d devices, have %d"
                         % (shape, n, len(devs)))
    arr = np.asarray(devs[:n]).reshape(shape)
    from jax.sharding import Mesh
    return Mesh(arr, axis_names[:len(shape)])


def set_mesh(mesh):
    prev = getattr(_state, "mesh", None)
    _state.mesh = mesh
    return prev


def current_mesh():
    return getattr(_state, "mesh", None)


def data_parallel_sharding(mesh, axis="dp", ndim=2):
    """NamedSharding splitting the leading (batch) dim over ``axis``."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def replicated_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P())
