"""Gluon vision transforms.

Reference: python/mxnet/gluon/data/vision/transforms.py (Compose, Cast,
ToTensor, Normalize, Resize, CenterCrop, RandomResizedCrop, flips,
color jitter). Image tensors are HWC uint8 in, like the reference.
"""
from __future__ import annotations

import random as _pyrandom

import numpy as _np

from ...block import Block, HybridBlock
from ...nn.basic_layers import Sequential, HybridSequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize",
           "CenterCrop", "RandomResizedCrop", "RandomFlipLeftRight",
           "RandomFlipTopBottom", "RandomBrightness", "RandomContrast",
           "RandomSaturation", "RandomColorJitter", "RandomLighting"]


class Compose(Sequential):
    """Sequentially compose transforms (reference: transforms.py Compose)."""

    def __init__(self, transforms):
        super(Compose, self).__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super(Cast, self).__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return x.astype(self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] -> CHW float32 [0,1]
    (reference: transforms.py ToTensor; op src/operator/image/)."""

    def hybrid_forward(self, F, x):
        x = x.astype("float32") / 255.0
        if x.ndim == 3:
            return x.transpose((2, 0, 1))
        return x.transpose((0, 3, 1, 2))


class Normalize(HybridBlock):
    """(x - mean) / std per channel on CHW input
    (reference: transforms.py Normalize)."""

    def __init__(self, mean=0.0, std=1.0):
        super(Normalize, self).__init__()
        self._mean = mean
        self._std = std

    def hybrid_forward(self, F, x):
        from ....ndarray.ndarray import array
        mean = _np.asarray(self._mean, dtype=_np.float32)
        std = _np.asarray(self._std, dtype=_np.float32)
        extra = (1,) * (x.ndim - 3)
        mean = array(mean.reshape(extra + (-1, 1, 1))
                     if mean.ndim else mean.reshape(()))
        std = array(std.reshape(extra + (-1, 1, 1))
                    if std.ndim else std.reshape(()))
        return (x - mean) / std


class Resize(Block):
    """Bilinear resize HWC image (reference: transforms.py Resize)."""

    def __init__(self, size, keep_ratio=False, interpolation=1):
        super(Resize, self).__init__()
        self._size = size
        self._keep = keep_ratio

    def forward(self, x):
        from .... import image
        if isinstance(self._size, int):
            if self._keep:
                h, w = x.shape[0], x.shape[1]
                if h < w:
                    new_h, new_w = self._size, int(w * self._size / h)
                else:
                    new_h, new_w = int(h * self._size / w), self._size
            else:
                new_h = new_w = self._size
        else:
            new_w, new_h = self._size
        return image.imresize(x, new_w, new_h)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super(CenterCrop, self).__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        from .... import image
        w, h = self._size
        return image.center_crop(x, (w, h))[0]


class RandomResizedCrop(Block):
    """Random area+aspect crop then resize
    (reference: transforms.py RandomResizedCrop)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4., 4. / 3.),
                 interpolation=1):
        super(RandomResizedCrop, self).__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        from .... import image
        w, h = self._size
        return image.random_size_crop(x, (w, h), self._scale, self._ratio)[0]


class _RandomApply(Block):
    def forward(self, x):
        raise NotImplementedError


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if _pyrandom.random() < 0.5:
            from .... import ndarray as nd
            return nd.reverse(x, axis=1)
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if _pyrandom.random() < 0.5:
            from .... import ndarray as nd
            return nd.reverse(x, axis=0)
        return x


class RandomBrightness(Block):
    def __init__(self, brightness):
        super(RandomBrightness, self).__init__()
        self._brightness = brightness

    def forward(self, x):
        alpha = 1.0 + _pyrandom.uniform(-self._brightness, self._brightness)
        return x * alpha


class RandomContrast(Block):
    def __init__(self, contrast):
        super(RandomContrast, self).__init__()
        self._contrast = contrast

    def forward(self, x):
        alpha = 1.0 + _pyrandom.uniform(-self._contrast, self._contrast)
        gray = x.astype("float32").mean()
        return x * alpha + gray * (1.0 - alpha)


class RandomSaturation(Block):
    def __init__(self, saturation):
        super(RandomSaturation, self).__init__()
        self._saturation = saturation

    def forward(self, x):
        from .... import ndarray as nd
        alpha = 1.0 + _pyrandom.uniform(-self._saturation, self._saturation)
        gray = nd.mean(x.astype("float32"), axis=-1, keepdims=True)
        return x * alpha + gray * (1.0 - alpha)


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super(RandomColorJitter, self).__init__()
        self._transforms = []
        if brightness:
            self._transforms.append(RandomBrightness(brightness))
        if contrast:
            self._transforms.append(RandomContrast(contrast))
        if saturation:
            self._transforms.append(RandomSaturation(saturation))

    def forward(self, x):
        order = list(self._transforms)
        _pyrandom.shuffle(order)
        for t in order:
            x = t(x)
        return x


class RandomLighting(Block):
    """AlexNet-style PCA lighting noise
    (reference: transforms.py RandomLighting)."""

    _eigval = _np.array([55.46, 4.794, 1.148], dtype=_np.float32)
    _eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                         [-0.5808, -0.0045, -0.8140],
                         [-0.5836, -0.6948, 0.4203]], dtype=_np.float32)

    def __init__(self, alpha):
        super(RandomLighting, self).__init__()
        self._alpha = alpha

    def forward(self, x):
        from ....ndarray.ndarray import array
        alpha = _np.random.normal(0, self._alpha, size=(3,)) \
            .astype(_np.float32)
        rgb = (self._eigvec * alpha * self._eigval).sum(axis=1)
        return x + array(rgb)
