// A C++ client training an MLP on MNIST-format data through the full
// C ABI surface: DataIter (MXDataIterCreateIter/MNISTIter), autograd +
// generated op wrappers, KVStore gradient aggregation, the SGD
// optimizer wrapper, and the process profiler.
//
// Capability analog of the reference's cpp-package/example/mlp_cpu.cpp
// (cpp-package/include/mxnet-cpp/MxNetCpp.h training loop).
//
// Usage: train_mnist_mlp <images.idx> <labels.idx> [profile.json]
// Build + run: see tests/test_c_api.py::test_cpp_mlp_trains_via_full_abi.
#include <cstdio>
#include <map>
#include <string>
#include <vector>

#include "mxnet_tpu_cpp/MxNetCpp.h"

using namespace mxnet_tpu_cpp;  // NOLINT

namespace {

NDArray RandomParam(const std::vector<uint32_t>& shape, float scale,
                    unsigned* seed) {
  size_t n = 1;
  for (uint32_t d : shape) n *= d;
  std::vector<float> host(n);
  for (size_t i = 0; i < n; ++i) {
    *seed = *seed * 1103515245u + 12345u;
    host[i] = (((*seed >> 16) & 0x7fff) / 32768.0f - 0.5f) * 2.0f * scale;
  }
  NDArray a(shape);
  a.CopyFrom(host);
  a.AttachGrad();
  return a;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr, "usage: %s <images.idx> <labels.idx> "
                 "[profile.json]\n", argv[0]);
    return 2;
  }
  const char* profile_path = argc > 3 ? argv[3] : nullptr;

  if (profile_path != nullptr) {
    const char* keys[] = {"filename", "profile_all"};
    const char* vals[] = {profile_path, "True"};
    if (MXSetProcessProfilerConfig(2, keys, vals) != 0 ||
        MXSetProcessProfilerState(1) != 0) {
      std::fprintf(stderr, "profiler setup failed: %s\n", MXGetLastError());
      return 1;
    }
  }

  const uint32_t kBatch = 64, kHidden = 128, kClasses = 10, kIn = 784;
  DataIter train("MNISTIter",
                 {{"image", argv[1]}, {"label", argv[2]},
                  {"batch_size", std::to_string(kBatch)},
                  {"flat", "True"}, {"shuffle", "True"}});

  unsigned seed = 20260730u;
  // FullyConnected weights are (num_hidden, input_dim)
  NDArray w1 = RandomParam({kHidden, kIn}, 0.07f, &seed);
  NDArray b1 = RandomParam({kHidden}, 0.0f, &seed);
  NDArray w2 = RandomParam({kClasses, kHidden}, 0.15f, &seed);
  NDArray b2 = RandomParam({kClasses}, 0.0f, &seed);
  std::vector<NDArray*> params = {&w1, &b1, &w2, &b2};
  std::vector<std::string> keys = {"w1", "b1", "w2", "b2"};

  KVStore kv("local");
  {
    std::vector<const NDArray*> init(params.begin(), params.end());
    kv.Init(keys, init);
  }
  SGDOptimizer opt(0.2f, 0.9f);

  auto forward = [&](const NDArray& x) {
    // the generated wrapper exposes the required (data, weight) inputs;
    // pass bias through the variadic Invoke like the reference's
    // optional-input ops
    NDArray h = op::relu(Invoke(
        "FullyConnected", {&x, &w1, &b1},
        {{"num_hidden", std::to_string(kHidden)}}));
    return Invoke("FullyConnected", {&h, &w2, &b2},
                  {{"num_hidden", std::to_string(kClasses)}});
  };

  float loss_val = 0.0f;
  for (int epoch = 0; epoch < 6; ++epoch) {
    train.Reset();
    while (train.Next()) {
      NDArray x = train.Data();
      NDArray y = train.Label();
      NDArray loss;
      {
        AutogradRecord rec;
        NDArray logit = forward(x);
        NDArray logp = op::log_softmax(logit);
        NDArray nll = op::negative(op::pick(logp, y));
        loss = op::mean(nll);
      }
      loss.Backward();
      // aggregate through the kvstore (identity at one worker, the
      // same call pattern a multi-device loop uses), then update
      for (size_t i = 0; i < params.size(); ++i) {
        NDArray g = params[i]->Grad();
        kv.Push({keys[i]}, {&g});
        kv.Pull({keys[i]}, {&g});
        opt.Update(static_cast<int>(i), params[i], g);
      }
      loss_val = loss.CopyTo()[0];
    }
    std::printf("epoch %d loss %.4f\n", epoch, loss_val);
  }

  // training-set accuracy through the same ABI ops
  size_t correct = 0, total = 0;
  train.Reset();
  while (train.Next()) {
    NDArray x = train.Data();
    NDArray y = train.Label();
    NDArray pred = op::argmax(forward(x), {{"axis", "-1"}});
    std::vector<float> p = pred.CopyTo(), t = y.CopyTo();
    int pad = train.PadNum();
    for (size_t i = 0; i + pad < p.size(); ++i) {
      correct += (p[i] == t[i]);
      ++total;
    }
  }
  float acc = total ? static_cast<float>(correct) / total : 0.0f;
  std::printf("kvstore type=%s rank=%d size=%d\n", kv.Type().c_str(),
              kv.Rank(), kv.GroupSize());
  std::printf("ACC %.4f\n", acc);

  if (profile_path != nullptr) {
    if (MXSetProcessProfilerState(0) != 0 ||
        MXDumpProcessProfile(1) != 0) {
      std::fprintf(stderr, "profiler dump failed: %s\n", MXGetLastError());
      return 1;
    }
  }
  if (acc < 0.9f) {
    std::printf("TRAIN FAILED\n");
    return 1;
  }
  std::printf("TRAIN OK\n");
  return 0;
}
