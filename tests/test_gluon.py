"""Gluon Block/Parameter/Trainer/layers tests
(mirrors reference tests/python/unittest/test_gluon.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn, rnn


def test_parameter():
    p = gluon.Parameter("weight", shape=(10, 10))
    p.initialize(init="xavier")
    assert p.data().shape == (10, 10)
    assert p.grad().shape == (10, 10)
    assert p.list_ctx() == [mx.context.current_context()]


def test_parameter_deferred_init():
    p = gluon.Parameter("weight", shape=(4, 0), allow_deferred_init=True)
    p.initialize()
    with pytest.raises(gluon.DeferredInitializationError):
        p.data()
    p._set_shape_from((4, 7))
    p._finish_deferred_init()
    assert p.data().shape == (4, 7)


def test_paramdict_save_load(tmp_path):
    params = gluon.ParameterDict("net_")
    w = params.get("weight", shape=(3, 3))
    params.initialize()
    fname = str(tmp_path / "p.params")
    params.save(fname)
    params2 = gluon.ParameterDict("net_")
    w2 = params2.get("weight", shape=(3, 3))
    params2.load(fname)
    assert np.allclose(w.data().asnumpy(), w2.data().asnumpy())


def test_dense():
    net = nn.Dense(8, in_units=4, activation="relu")
    net.initialize()
    x = mx.nd.array(np.random.rand(2, 4))
    out = net(x)
    assert out.shape == (2, 8)
    assert (out.asnumpy() >= 0).all()


def test_dense_deferred():
    net = nn.Dense(8)
    net.initialize()
    out = net(mx.nd.array(np.random.rand(5, 3)))
    assert out.shape == (5, 8)
    assert net.weight.shape == (8, 3)


def test_sequential_train_step():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"),
                nn.Dropout(0.5), nn.Dense(2))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    x = mx.nd.array(np.random.rand(8, 4))
    y = mx.nd.array(np.random.rand(8, 2))
    lfn = gluon.loss.L2Loss()
    losses = []
    for _ in range(5):
        with autograd.record():
            loss = lfn(net(x), y)
        loss.backward()
        trainer.step(8)
        losses.append(loss.mean().asscalar())
    assert losses[-1] < losses[0]


def test_hybridize_matches_eager():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="tanh"), nn.Dense(3))
    net.initialize()
    x = mx.nd.array(np.random.rand(4, 5))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    assert np.allclose(eager, hybrid, atol=1e-6)


def test_hybridize_dropout_is_random_per_call():
    net = nn.Dropout(0.5)
    net.hybridize()
    x = mx.nd.ones((100,))
    with autograd.record():
        a = net(x).asnumpy()
        b = net(x).asnumpy()
    assert not np.allclose(a, b)      # fresh mask per call
    assert (a == 0).sum() > 10        # actually dropping


def test_hybridize_batchnorm_aux_updates():
    net = nn.BatchNorm(in_channels=3)
    net.initialize()
    net.hybridize()
    x = mx.nd.array(np.random.rand(4, 3, 2, 2) * 5 + 7)
    with autograd.record():
        net(x)
    rm = net.running_mean.data().asnumpy()
    assert np.abs(rm).sum() > 0


def test_batchnorm_train_vs_eval():
    net = nn.BatchNorm(in_channels=2)
    net.initialize()
    x = mx.nd.array(np.random.rand(8, 2) * 10)
    with autograd.record():
        train_out = net(x).asnumpy()
    eval_out = net(x).asnumpy()
    assert not np.allclose(train_out, eval_out)


def test_conv2d_shapes():
    net = nn.Conv2D(4, kernel_size=3, padding=1, strides=2)
    net.initialize()
    out = net(mx.nd.array(np.random.rand(2, 3, 8, 8)))
    assert out.shape == (2, 4, 4, 4)
    assert net.weight.shape == (4, 3, 3, 3)


def test_conv_transpose():
    net = nn.Conv2DTranspose(4, kernel_size=2, strides=2, in_channels=3)
    net.initialize()
    out = net(mx.nd.array(np.random.rand(2, 3, 4, 4)))
    assert out.shape == (2, 4, 8, 8)


def test_pool_layers():
    x = mx.nd.array(np.random.rand(2, 3, 8, 8))
    assert nn.MaxPool2D(2)(x).shape == (2, 3, 4, 4)
    assert nn.AvgPool2D(2)(x).shape == (2, 3, 4, 4)
    assert nn.GlobalAvgPool2D()(x).shape == (2, 3, 1, 1)


def test_embedding_layer():
    net = nn.Embedding(10, 6)
    net.initialize()
    out = net(mx.nd.array([1, 2, 3]))
    assert out.shape == (3, 6)


def test_layernorm_layer():
    net = nn.LayerNorm(in_channels=5)
    net.initialize()
    out = net(mx.nd.array(np.random.rand(4, 5)))
    assert out.shape == (4, 5)
    assert abs(out.asnumpy().mean()) < 1e-5


def test_block_save_load(tmp_path):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    net.initialize()
    fname = str(tmp_path / "net.params")
    net.save_parameters(fname)
    net2 = nn.HybridSequential()
    with net2.name_scope():
        net2.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    net2.load_parameters(fname)
    x = mx.nd.array(np.random.rand(2, 3))
    assert np.allclose(net(x).asnumpy(), net2(x).asnumpy())


def test_collect_params_select():
    net = nn.HybridSequential(prefix="model_")
    with net.name_scope():
        net.add(nn.Dense(4, in_units=3))
    all_p = net.collect_params()
    w_only = net.collect_params(".*weight")
    assert len(w_only) == 1
    assert len(all_p) == 2


def test_losses():
    pred = mx.nd.array(np.random.rand(4, 5))
    label_cls = mx.nd.array(np.random.randint(0, 5, (4,)))
    label_reg = mx.nd.array(np.random.rand(4, 5))
    assert gluon.loss.L2Loss()(pred, label_reg).shape == (4,)
    assert gluon.loss.L1Loss()(pred, label_reg).shape == (4,)
    l = gluon.loss.SoftmaxCrossEntropyLoss()(pred, label_cls)
    assert l.shape == (4,)
    # cross-check vs manual log-softmax pick
    logp = pred.asnumpy() - np.log(
        np.exp(pred.asnumpy()).sum(-1, keepdims=True))
    expect = -logp[np.arange(4), label_cls.asnumpy().astype(int)]
    assert np.allclose(l.asnumpy(), expect, atol=1e-5)
    assert gluon.loss.HuberLoss()(pred, label_reg).shape == (4,)
    assert gluon.loss.HingeLoss()(pred, label_reg).shape == (4,)
    assert gluon.loss.SigmoidBCELoss()(pred, label_reg).shape == (4,)
    assert gluon.loss.KLDivLoss()(
        mx.nd.log_softmax(pred), mx.nd.softmax(label_reg)).shape == (4,)


def test_lstm_cell_unroll():
    cell = rnn.LSTMCell(8, input_size=6)
    cell.initialize()
    seq = mx.nd.array(np.random.rand(3, 5, 6))   # NTC
    outs, states = cell.unroll(5, seq, layout="NTC", merge_outputs=True)
    assert outs.shape == (3, 5, 8)
    assert states[0].shape == (3, 8)


def test_gru_cell():
    cell = rnn.GRUCell(4, input_size=3)
    cell.initialize()
    x = mx.nd.array(np.random.rand(2, 3))
    h = cell.begin_state(2)
    out, new_h = cell(x, h)
    assert out.shape == (2, 4)


def test_sequential_rnn_cells():
    stack = rnn.SequentialRNNCell()
    stack.add(rnn.LSTMCell(8, input_size=4))
    stack.add(rnn.LSTMCell(8, input_size=8))
    stack.initialize()
    seq = mx.nd.array(np.random.rand(2, 5, 4))
    outs, states = stack.unroll(5, seq, layout="NTC", merge_outputs=True)
    assert outs.shape == (2, 5, 8)
    assert len(states) == 4


def test_fused_lstm_layer():
    layer = rnn.LSTM(8, num_layers=2)
    layer.initialize()
    seq = mx.nd.array(np.random.rand(5, 3, 6))   # TNC
    out = layer(seq)
    assert out.shape == (5, 3, 8)
    states = layer.begin_state(3)
    out, st = layer(seq, states)
    assert out.shape == (5, 3, 8)
    assert st[0].shape == (2, 3, 8) and st[1].shape == (2, 3, 8)


def test_fused_bidirectional_gru_grad():
    layer = rnn.GRU(4, num_layers=1, bidirectional=True)
    layer.initialize()
    seq = mx.nd.array(np.random.rand(3, 2, 5))
    with autograd.record():
        out = layer(seq)
        loss = out.sum()
    loss.backward()
    g = layer.l0_i2h_weight.grad().asnumpy()
    assert np.abs(g).sum() > 0


def test_trainer_allreduce_noop_single_device():
    net = nn.Dense(2, in_units=3)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                       kvstore="local")
    x = mx.nd.array(np.random.rand(4, 3))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    w_before = net.weight.data().asnumpy().copy()
    tr.step(4)
    assert not np.allclose(w_before, net.weight.data().asnumpy())


def test_gluon_utils_split_and_load():
    data = mx.nd.array(np.arange(12).reshape(6, 2))
    ctxs = [mx.context.current_context()] * 2
    parts = gluon.utils.split_and_load(data, ctxs)
    assert parts[0].shape == (3, 2)
    total = gluon.utils.clip_global_norm([mx.nd.ones((2,)) * 3,
                                          mx.nd.ones((2,)) * 4], 1.0)
    assert abs(total - np.sqrt(9 * 2 + 16 * 2)) < 1e-4


def test_export_nested_block_roundtrip(tmp_path):
    """export() on a NESTED HybridBlock (children dispatch on the symbol
    namespace during tracing — regression: child forward() used to
    hard-code the ndarray namespace) → SymbolBlock.imports serves the
    same outputs; BN running stats classify as auxiliary states."""
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.block import SymbolBlock
    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, kernel_size=3, padding=1),
            nn.BatchNorm(),
            nn.Activation("relu"),
            nn.MaxPool2D(pool_size=2),
            nn.Flatten(),
            nn.Dense(3))
    net.initialize()
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(2, 3, 8, 8).astype(np.float32))
    ref = net(x)

    prefix = str(tmp_path / "exported")
    sym = net.export(prefix, epoch=3)
    assert len(sym.list_auxiliary_states()) == 2      # BN moving stats
    loaded = SymbolBlock.imports(prefix + "-symbol.json", ["data"],
                                 prefix + "-0003.params")
    out = loaded(x)
    np.testing.assert_allclose(out.asnumpy(), ref.asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_export_zoo_model_traces(tmp_path):
    """A deep zoo model (nested Sequentials + BN everywhere) traces to a
    symbol whose executor reproduces the gluon forward exactly."""
    from mxnet_tpu.gluon.model_zoo.vision import get_model
    net = get_model("squeezenet1.0", classes=10)
    net.initialize()
    rng = np.random.RandomState(1)
    x = mx.nd.array(rng.randn(1, 3, 64, 64).astype(np.float32))
    ref = net(x)
    sym = net._trace_symbol()
    exe = sym.simple_bind(data=(1, 3, 64, 64))
    for n, p in net.collect_params().items():
        if n in exe.arg_dict:
            exe.arg_dict[n][:] = p.data()
        else:
            exe.aux_dict[n][:] = p.data()
    exe.arg_dict["data"][:] = x
    out = exe.forward(is_train=False)[0]
    np.testing.assert_allclose(out.asnumpy(), ref.asnumpy(),
                               rtol=1e-4, atol=1e-5)
