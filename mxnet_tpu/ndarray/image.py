"""nd.image namespace (reference: generated _image_* bindings from
src/operator/image/image_random-inl.h)."""
from __future__ import annotations

from .ndarray import invoke_op

__all__ = ["to_tensor", "normalize", "flip_left_right", "flip_top_bottom",
           "random_flip_left_right", "random_flip_top_bottom", "crop",
           "random_brightness", "random_contrast", "random_saturation",
           "resize"]


def to_tensor(data):
    return invoke_op("_image_to_tensor", [data], {})


def normalize(data, mean=0.0, std=1.0):
    mean = tuple(mean) if hasattr(mean, "__len__") else (float(mean),)
    std = tuple(std) if hasattr(std, "__len__") else (float(std),)
    return invoke_op("_image_normalize", [data], {"mean": mean, "std": std})


def flip_left_right(data):
    return invoke_op("_image_flip_left_right", [data], {})


def flip_top_bottom(data):
    return invoke_op("_image_flip_top_bottom", [data], {})


def random_flip_left_right(data):
    return invoke_op("_image_random_flip_left_right", [data], {})


def random_flip_top_bottom(data):
    return invoke_op("_image_random_flip_top_bottom", [data], {})


def crop(data, x, y, width, height):
    return invoke_op("_image_crop", [data],
                     {"x": x, "y": y, "width": width, "height": height})


def random_brightness(data, min_factor, max_factor):
    return invoke_op("_image_random_brightness", [data],
                     {"min_factor": min_factor, "max_factor": max_factor})


def random_contrast(data, min_factor, max_factor):
    return invoke_op("_image_random_contrast", [data],
                     {"min_factor": min_factor, "max_factor": max_factor})


def random_saturation(data, min_factor, max_factor):
    return invoke_op("_image_random_saturation", [data],
                     {"min_factor": min_factor, "max_factor": max_factor})


def resize(data, size, keep_ratio=False, interp=1):
    size = tuple(size) if hasattr(size, "__len__") else (size, size)
    return invoke_op("_image_resize", [data],
                     {"size": size, "keep_ratio": keep_ratio,
                      "interp": interp})
