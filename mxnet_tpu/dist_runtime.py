"""Multi-host runtime lifecycle for ``dist_tpu_sync``.

One idempotent, refcounted wrapper around ``jax.distributed`` so the
kvstore (and anything else that needs the global device view) can say
"make sure the cluster runtime is up" without owning its lifecycle:

* :func:`acquire` — initialize ``jax.distributed`` exactly once per
  process (explicit ``MXNET_DIST_*`` env first, standard cluster
  autodetection second), or adopt an already-initialized runtime (a
  launcher that called ``jax.distributed.initialize`` itself).
* :func:`release` — drop one reference; when the LAST holder releases
  AND this module performed the initialization, ``shutdown()`` tears
  the coordinator connection down cleanly.  A runtime initialized by
  someone else is never shut down from here.

Configuration (config.py):

* ``MXNET_DIST_COORDINATOR`` — ``host:port`` of process 0's
  coordinator service.  Setting it (plus the two below) is the
  explicit, works-anywhere route — the CPU/gloo acceptance tests and
  the ``dist_train_sync`` bench use it.
* ``MXNET_DIST_NUM_PROCESSES`` / ``MXNET_DIST_PROCESS_ID`` — world
  size and this process's rank.

Without ``MXNET_DIST_*``, :func:`env_configured` falls back to the
standard signals ``jax.distributed.initialize()`` autodetects itself
(Cloud TPU metadata, SLURM, Open MPI) so a TPU pod slice launched
through the normal tooling needs no extra variables.

On a CPU backend the gloo collectives implementation is selected
before initialization when this jax exposes the knob (the raw CPU
backend cannot run multiprocess computations) — the same live-probed
gate ``tests/test_kvstore_multiprocess.py`` uses.
"""
from __future__ import annotations

import logging
import os
import threading

from .base import MXNetError

__all__ = ["acquire", "release", "initialize", "shutdown",
           "is_initialized", "env_configured", "process_count",
           "process_index"]

_log = logging.getLogger(__name__)

_lock = threading.Lock()
_refs = [0]          # live acquire() holders
_owned = [False]     # did THIS module run jax.distributed.initialize?

# standard env signals jax.distributed.initialize() can autodetect a
# cluster from without explicit arguments
_AUTO_ENV = ("SLURM_JOB_ID", "OMPI_COMM_WORLD_SIZE",
             "TPU_WORKER_HOSTNAMES", "MEGASCALE_COORDINATOR_ADDRESS",
             "COORDINATOR_ADDRESS")


def _cfg(name):
    from .config import get
    return get(name)


def is_initialized():
    """Whether this process already has a live ``jax.distributed``
    runtime (ours or anyone's)."""
    try:
        from jax._src import distributed as _d
        return _d.global_state.client is not None
    except Exception:
        return False


def env_configured():
    """Whether the environment describes a multi-process cluster this
    process could join: explicit ``MXNET_DIST_*`` settings, or one of
    the standard signals jax autodetects."""
    if _cfg("MXNET_DIST_COORDINATOR"):
        return True
    return any(os.environ.get(v) for v in _AUTO_ENV)


def _select_cpu_collectives():
    """Route multiprocess CPU computations over gloo when this jax has
    the knob; a no-op on accelerator backends and older jax (where the
    raw CPU backend simply cannot run multiprocess programs)."""
    import jax
    if os.environ.get("JAX_PLATFORMS", "").lower() != "cpu" and \
            _cfg("MXNET_TPU_PLATFORM") != "cpu":
        return
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass


def initialize():
    """Idempotent ``jax.distributed.initialize``.

    Returns True when THIS call initialized the runtime, False when it
    was already up or no cluster is configured.  Raises
    :class:`MXNetError` when the environment names a cluster but the
    join fails — silently training single-process after a botched
    rendezvous would corrupt the run, not degrade it."""
    import jax
    if is_initialized():
        return False
    coord = _cfg("MXNET_DIST_COORDINATOR")
    try:
        if coord:
            _select_cpu_collectives()
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=int(_cfg("MXNET_DIST_NUM_PROCESSES")),
                process_id=int(_cfg("MXNET_DIST_PROCESS_ID")))
            _owned[0] = True
            return True
        if any(os.environ.get(v) for v in _AUTO_ENV):
            _select_cpu_collectives()
            jax.distributed.initialize()   # standard autodetection
            _owned[0] = True
            return True
    except MXNetError:
        raise
    except Exception as e:
        raise MXNetError(
            "jax.distributed.initialize failed for the configured "
            "cluster (%s): %s" % (coord or "autodetected env", e))
    return False


def _shutdown_locked():
    """Tear down the runtime IF this module initialized it (no-op
    otherwise — never shut down a launcher-owned runtime).  Caller
    holds ``_lock``, so a concurrent :func:`acquire` cannot adopt the
    runtime between the ownership check and the teardown."""
    if not _owned[0]:
        return
    _owned[0] = False
    try:
        import jax
        jax.distributed.shutdown()
    except Exception as e:           # already down / interpreter exit
        _log.debug("jax.distributed.shutdown: %s", e)


def shutdown():
    with _lock:
        _shutdown_locked()


def acquire():
    """Refcounted ensure-initialized; pair with :func:`release`.

    Initialization is attempted whenever no runtime is live — NOT only
    on the first reference: an early holder acquired before the cluster
    env was set (e.g. ``io.dist_parts`` on a laptop) must not suppress
    a later holder's rendezvous."""
    with _lock:
        if not is_initialized():
            initialize()       # marks _owned when it performs the init
        _refs[0] += 1


def release():
    """Drop one :func:`acquire` reference; the last release shuts the
    runtime down when this module owns it."""
    with _lock:
        if _refs[0] > 0:
            _refs[0] -= 1
            if _refs[0] == 0:
                _shutdown_locked()


def process_count():
    try:
        import jax
        return int(jax.process_count())
    except Exception:
        return 1


def process_index():
    try:
        import jax
        return int(jax.process_index())
    except Exception:
        return 0
