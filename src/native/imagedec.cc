// Parallel JPEG decode + augment into a preallocated batch buffer.
//
// TPU-native replacement for the reference's OMP decode hot path
// (reference: src/io/iter_image_recordio_2.cc:78 ParseChunk — decode
// threads write straight into the output batch tensor). Design differs
// deliberately: a persistent std::thread pool fed whole batches over a
// C ABI (ctypes releases the GIL for the call, so Python's prefetch
// thread overlaps this with the device step), and the augmentation RNG
// is keyed per IMAGE (seed, stream position) rather than per thread —
// results are bit-identical for any thread count or schedule.
//
// Pipeline per image, matching mxnet_tpu/image.py CreateAugmenter
// semantics: imdecode(BGR) -> RGB -> resize short side (INTER_CUBIC)
// -> random/center crop (resize when the source is smaller) ->
// optional horizontal mirror -> (x - mean) / std -> float32 CHW.
#include <opencv2/core.hpp>
#include <opencv2/imgcodecs.hpp>
#include <opencv2/imgproc.hpp>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Job {
  int n = 0;
  const uint8_t* const* bufs = nullptr;
  const int64_t* lens = nullptr;
  uint64_t base = 0;        // stream position of bufs[0] (RNG key part)
  float* out = nullptr;
};

class Decoder {
 public:
  Decoder(int threads, int out_h, int out_w, int channels, int resize,
          int rand_crop, int rand_mirror, const float* mean,
          const float* stdv, uint64_t seed)
      : out_h_(out_h), out_w_(out_w), channels_(channels), resize_(resize),
        rand_crop_(rand_crop), rand_mirror_(rand_mirror), seed_(seed) {
    for (int c = 0; c < 3; ++c) {
      mean_[c] = 0.f;
      std_[c] = 1.f;
    }
    // grayscale callers pass 1-element mean/std buffers: only read
    // what the channel count guarantees exists
    int nc = channels == 1 ? 1 : 3;
    for (int c = 0; c < nc; ++c) {
      if (mean) mean_[c] = mean[c];
      if (stdv) std_[c] = stdv[c];
    }
    int nt = threads > 0 ? threads : (int)std::thread::hardware_concurrency();
    if (nt < 1) nt = 1;
    workers_.reserve(nt);
    for (int t = 0; t < nt; ++t)
      workers_.emplace_back([this] { WorkerLoop(); });
  }

  ~Decoder() {
    {
      std::lock_guard<std::mutex> g(mu_);
      quit_ = true;
    }
    cv_job_.notify_all();
    for (auto& w : workers_) w.join();
  }

  int Decode(int n, const uint8_t* const* bufs, const int64_t* lens,
             uint64_t base, float* out) {
    if (n <= 0) return 0;
    {
      std::lock_guard<std::mutex> g(mu_);
      job_ = Job{n, bufs, lens, base, out};
      next_ = 0;
      pending_.store(n, std::memory_order_relaxed);
      failed_.store(0, std::memory_order_relaxed);
      epoch_++;
    }
    cv_job_.notify_all();
    {
      // wait until every image is done AND every worker has LEFT the
      // job (a worker still in its claim loop holds stale pointers and
      // must not race the next job's reset of next_/pending_)
      std::unique_lock<std::mutex> g(mu_);
      cv_done_.wait(g, [this] {
        return pending_.load(std::memory_order_acquire) == 0 &&
               running_ == 0;
      });
    }
    return failed_.load(std::memory_order_relaxed) ? -1 : 0;
  }

  const char* Error() {
    std::lock_guard<std::mutex> g(err_mu_);
    return err_.c_str();
  }

 private:
  void WorkerLoop() {
    uint64_t seen = 0;
    for (;;) {
      Job job;
      {
        std::unique_lock<std::mutex> g(mu_);
        cv_job_.wait(g, [&] { return quit_ || epoch_ != seen; });
        if (quit_) return;
        seen = epoch_;
        job = job_;
        running_++;
      }
      for (;;) {
        int i;
        {
          // claim under the job mutex, re-validating the epoch: a
          // worker that joined a job in the window after Decode()
          // returned but before the NEXT Decode() installed its job
          // must not claim indices against the new job's counter with
          // this (stale, freed) job's pointers
          std::lock_guard<std::mutex> g(mu_);
          if (epoch_ != seen) break;
          i = next_++;
        }
        if (i >= job.n) break;
        try {
          DecodeOne(job.bufs[i], job.lens[i], job.base + (uint64_t)i,
                    job.out + (size_t)i * channels_ * out_h_ * out_w_);
        } catch (const std::exception& e) {
          failed_.store(1, std::memory_order_relaxed);
          std::lock_guard<std::mutex> g(err_mu_);
          err_ = e.what();
        } catch (...) {
          failed_.store(1, std::memory_order_relaxed);
          std::lock_guard<std::mutex> g(err_mu_);
          err_ = "unknown decode error";
        }
        pending_.fetch_sub(1, std::memory_order_acq_rel);
      }
      {
        std::lock_guard<std::mutex> g(mu_);
        if (--running_ == 0 &&
            pending_.load(std::memory_order_acquire) == 0)
          cv_done_.notify_all();
      }
    }
  }

  void DecodeOne(const uint8_t* buf, int64_t len, uint64_t pos, float* out) {
    cv::Mat raw(1, (int)len, CV_8UC1, const_cast<uint8_t*>(buf));
    cv::Mat img = cv::imdecode(
        raw, channels_ == 3 ? cv::IMREAD_COLOR : cv::IMREAD_GRAYSCALE);
    if (img.empty()) throw std::runtime_error("cannot decode image");
    if (channels_ == 3) cv::cvtColor(img, img, cv::COLOR_BGR2RGB);

    if (resize_ > 0) {
      int h = img.rows, w = img.cols, nh, nw;
      if (h > w) { nw = resize_; nh = (int)((int64_t)resize_ * h / w); }
      else       { nh = resize_; nw = (int)((int64_t)resize_ * w / h); }
      cv::resize(img, img, cv::Size(nw, nh), 0, 0, cv::INTER_CUBIC);
    }

    // deterministic per-image stream: any thread that picks this image
    // draws the same crop/mirror decisions
    std::mt19937_64 rng(seed_ ^ (0x9E3779B97F4A7C15ULL * (pos + 1)));
    int cw = std::min(out_w_, img.cols), ch = std::min(out_h_, img.rows);
    int x0, y0;
    if (rand_crop_) {
      x0 = (int)(rng() % (uint64_t)(img.cols - cw + 1));
      y0 = (int)(rng() % (uint64_t)(img.rows - ch + 1));
    } else {
      x0 = (img.cols - cw) / 2;
      y0 = (img.rows - ch) / 2;
    }
    cv::Mat crop = img(cv::Rect(x0, y0, cw, ch));
    if (cw != out_w_ || ch != out_h_)
      cv::resize(crop, crop, cv::Size(out_w_, out_h_), 0, 0,
                 cv::INTER_CUBIC);
    bool mirror = rand_mirror_ &&
        ((rng() >> 11) * 0x1.0p-53 < 0.5);   // uniform [0,1) < p
    if (mirror) cv::flip(crop, crop, 1);

    // HWC uint8 -> CHW float32 with per-channel normalisation
    const int hw = out_h_ * out_w_;
    if (channels_ == 3) {
      for (int y = 0; y < out_h_; ++y) {
        const uint8_t* row = crop.ptr<uint8_t>(y);
        float* o0 = out + y * out_w_;
        float* o1 = o0 + hw;
        float* o2 = o1 + hw;
        for (int x = 0; x < out_w_; ++x) {
          o0[x] = (row[3 * x + 0] - mean_[0]) / std_[0];
          o1[x] = (row[3 * x + 1] - mean_[1]) / std_[1];
          o2[x] = (row[3 * x + 2] - mean_[2]) / std_[2];
        }
      }
    } else {
      for (int y = 0; y < out_h_; ++y) {
        const uint8_t* row = crop.ptr<uint8_t>(y);
        float* o = out + y * out_w_;
        for (int x = 0; x < out_w_; ++x)
          o[x] = (row[x] - mean_[0]) / std_[0];
      }
    }
  }

  const int out_h_, out_w_, channels_, resize_, rand_crop_, rand_mirror_;
  float mean_[3], std_[3];
  const uint64_t seed_;

  std::mutex mu_, err_mu_;
  std::condition_variable cv_job_, cv_done_;
  std::vector<std::thread> workers_;
  Job job_;
  uint64_t epoch_ = 0;
  int running_ = 0;
  int next_ = 0;                // guarded by mu_ (claims re-check epoch)
  bool quit_ = false;
  std::atomic<int> pending_{0}, failed_{0};
  std::string err_;
};

}  // namespace

extern "C" {

void* imgdec_create(int threads, int out_h, int out_w, int channels,
                    int resize, int rand_crop, int rand_mirror,
                    const float* mean, const float* stdv, uint64_t seed) {
  if (channels != 1 && channels != 3) return nullptr;
  try {
    return new Decoder(threads, out_h, out_w, channels, resize, rand_crop,
                       rand_mirror, mean, stdv, seed);
  } catch (...) {
    return nullptr;
  }
}

int imgdec_decode_batch(void* h, int n, const uint8_t* const* bufs,
                        const int64_t* lens, uint64_t base, float* out) {
  if (!h) return -1;
  return static_cast<Decoder*>(h)->Decode(n, bufs, lens, base, out);
}

const char* imgdec_last_error(void* h) {
  return h ? static_cast<Decoder*>(h)->Error() : "null decoder";
}

void imgdec_destroy(void* h) { delete static_cast<Decoder*>(h); }

}  // extern "C"
