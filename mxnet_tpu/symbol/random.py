"""sym.random namespace (reference: python/mxnet/symbol/random.py) —
sampler symbols whose PRNG keys the executor threads per step,
mirroring nd.random."""
from __future__ import annotations

from ..base import np_dtype

__all__ = ["uniform", "normal", "gamma", "exponential", "poisson",
           "randint", "negative_binomial", "multinomial"]


def _shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


def _sample(opname, attrs, name):
    import mxnet_tpu.symbol as S      # generated op functions
    return getattr(S, opname)(name=name, **attrs)


def uniform(low=0.0, high=1.0, shape=(), dtype="float32", name=None):
    return _sample("_random_uniform",
                   {"low": low, "high": high, "shape": _shape(shape),
                    "dtype": np_dtype(dtype).name}, name)


def normal(loc=0.0, scale=1.0, shape=(), dtype="float32", name=None):
    return _sample("_random_normal",
                   {"loc": loc, "scale": scale, "shape": _shape(shape),
                    "dtype": np_dtype(dtype).name}, name)


def gamma(alpha=1.0, beta=1.0, shape=(), dtype="float32", name=None):
    return _sample("_random_gamma",
                   {"alpha": alpha, "beta": beta, "shape": _shape(shape),
                    "dtype": np_dtype(dtype).name}, name)


def exponential(scale=1.0, shape=(), dtype="float32", name=None):
    # the op takes the RATE lam (reference op convention); the frontend
    # exposes the SCALE, as nd.random.exponential does
    return _sample("_random_exponential",
                   {"lam": 1.0 / scale, "shape": _shape(shape),
                    "dtype": np_dtype(dtype).name}, name)


def poisson(lam=1.0, shape=(), dtype="float32", name=None):
    return _sample("_random_poisson",
                   {"lam": lam, "shape": _shape(shape),
                    "dtype": np_dtype(dtype).name}, name)


def randint(low, high, shape=(), dtype="int32", name=None):
    return _sample("_random_randint",
                   {"low": low, "high": high, "shape": _shape(shape),
                    "dtype": np_dtype(dtype).name}, name)


def negative_binomial(k=1, p=1.0, shape=(), dtype="float32", name=None):
    return _sample("_random_negative_binomial",
                   {"k": k, "p": p, "shape": _shape(shape),
                    "dtype": np_dtype(dtype).name}, name)


def multinomial(data, shape=(), get_prob=False, dtype="int32", name=None):
    import mxnet_tpu.symbol as S
    return S._sample_multinomial(data, shape=_shape(shape),
                                 get_prob=get_prob,
                                 dtype=np_dtype(dtype).name, name=name)
