"""INT8 matmul with a fused per-channel rescale epilogue, as a Pallas
TPU kernel.

The serving-side hot op of the quantized inference path
(mxnet_tpu/quantize/): ``out[m, n] = (x_q[m, :] . w_q[n, :]) *
scale[n]`` where ``x_q``/``w_q`` are int8, the dot accumulates in int32
on the MXU, and the per-output-channel fp32 rescale happens INSIDE the
kernel epilogue — the int32 accumulator never round-trips through HBM
and no separate dequantize op exists for XLA to schedule apart from the
dot (the "Operator Fusion in XLA" framing: the rescale is an epilogue,
not a graph node).

Grid (m_blocks, n_blocks, k_blocks); the trailing k dimension iterates
sequentially per (m, n) tile, accumulating into an int32 VMEM scratch
exactly like flash attention's online-softmax accumulator; the last k
step multiplies by the (1, block_n) scale tile and writes fp32.

Off-TPU the pure-lax twin (``dot_general`` with
``preferred_element_type=int32`` + broadcast rescale) is the production
path — the tier-1 reference the kernel is parity-tested against in
interpret mode.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _interpret_default, _out_vma, _pad_to, _sds

__all__ = ["int8_matmul", "int8_conv_im2col"]

# kernel-contract registry: exported kernel -> module-level pure-lax
# twin (see tools/check_pallas_contracts.py)
PALLAS_KERNELS = {
    "int8_matmul": "_int8_matmul_xla",
    "int8_conv_im2col": "_int8_conv_xla",
}


def _int8_matmul_xla(x, w, scale):
    """Pure-lax twin of the kernel (same contract): int8 operands, int32
    MXU accumulation, per-channel fp32 rescale. XLA fuses the rescale
    into the dot's epilogue on TPU; on CPU this is the tier-1 path."""
    acc = lax.dot_general(
        x.astype(jnp.int8), w.astype(jnp.int8),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)                    # (m, n)
    return acc.astype(jnp.float32) * scale.astype(jnp.float32)[None, :]


def _kernel(x_ref, w_ref, s_ref, o_ref, acc_scr):
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # int8 x int8 -> int32 on the MXU; accumulate across k blocks
    acc_scr[:] += lax.dot_general(
        x_ref[:], w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)                    # (bm, bn)

    @pl.when(ki == nk - 1)
    def _fin():
        # fused epilogue: per-output-channel rescale, int32 -> fp32
        o_ref[:] = acc_scr[:].astype(jnp.float32) * s_ref[:]


@functools.partial(jax.jit, static_argnames=("block_m", "block_n",
                                             "block_k", "interpret"))
def _int8_matmul_pallas(x, w, scale, block_m, block_n, block_k, interpret):
    m, k = x.shape
    n = w.shape[0]
    xf = _pad_to(_pad_to(x, block_m, 0), block_k, 1)
    wf = _pad_to(_pad_to(w, block_n, 0), block_k, 1)
    sf = _pad_to(scale.astype(jnp.float32).reshape(1, n), block_n, 1)
    grid = (xf.shape[0] // block_m, wf.shape[0] // block_n,
            xf.shape[1] // block_k)

    out = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, block_k), lambda mi, ni, ki: (mi, ki)),
            pl.BlockSpec((block_n, block_k), lambda mi, ni, ki: (ni, ki)),
            pl.BlockSpec((1, block_n), lambda mi, ni, ki: (0, ni)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n),
                               lambda mi, ni, ki: (mi, ni)),
        out_shape=_sds((xf.shape[0], wf.shape[0]), jnp.float32,
                       _out_vma(x, w, scale)),
        scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.int32)],
        compiler_params=getattr(pltpu, "CompilerParams",
                                getattr(pltpu, "TPUCompilerParams", None))(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(xf, wf, sf)
    return out[:m, :n]


def int8_matmul(x, w, scale, block_m=128, block_n=128, block_k=128,
                interpret=None):
    """``(x . w^T) * scale[None, :]`` with int8 operands and int32 MXU
    accumulation.

    Parameters
    ----------
    x : (m, k) int8 — quantized activations.
    w : (n, k) int8 — per-channel-quantized weights (channel = axis 0).
    scale : (n,) float32 — fused epilogue factor per output channel
        (``w_scale[n] / act_scale`` for a quantized dense layer).
    block_m, block_n, block_k : VMEM tile sizes (multiples of the int8
        tile (32, 128) on TPU; inputs are zero-padded to block
        multiples, and zero int8 products contribute nothing).
    interpret : force pallas interpreter mode. Default: the compiled
        Mosaic kernel on TPU, the pure-lax twin elsewhere (int32
        accumulation is exact, so twin and kernel agree BITWISE —
        asserted by tests/test_quantize.py in interpret mode).
    """
    x = x.astype(jnp.int8)
    w = w.astype(jnp.int8)
    if interpret is None:
        if _interpret_default(x):
            return _int8_matmul_xla(x, w, scale)
        interpret = False
    m, k = x.shape

    def _ceil(v, mult):
        return -(-v // mult) * mult

    # tile-legal block shrink for small operands: block_m is an int8
    # SUBLANE dim (x block) -> multiple of 32; block_n is w's sublane
    # AND the fp32 out/scale LANE dim -> multiple of 128; block_k is
    # the int8 lane dim -> multiple of 128. (Inputs are zero-padded to
    # block multiples, so rounding UP never changes results.)
    block_m = min(block_m, _ceil(m, 32))
    block_n = min(block_n, _ceil(w.shape[0], 128))
    block_k = min(block_k, _ceil(k, 128))
    return _int8_matmul_pallas(x, w, scale, int(block_m), int(block_n),
                               int(block_k), bool(interpret))


# ---------------------------------------------------------------------------
# int8 conv via im2col — the PR 11 escape hatch: when XLA's epilogue
# fusion of conv + dequant falls short, lower the conv onto the SAME
# int8 MXU matmul kernel above (rescale stays fused in the epilogue)
# ---------------------------------------------------------------------------

def _int8_conv_xla(q, wq, scale, stride, dilate, pad, num_group):
    """Pure-lax twin of :func:`int8_conv_im2col`: the direct
    ``conv_general_dilated`` int32 route `_contrib_quantized_conv_int8`
    has always used (int32 accumulation is exact, so twin and im2col
    agree BITWISE)."""
    dn = lax.conv_dimension_numbers(q.shape, wq.shape,
                                    ("NCHW", "OIHW", "NCHW"))
    acc = lax.conv_general_dilated(
        q.astype(jnp.int32), wq.astype(jnp.int8).astype(jnp.int32),
        window_strides=stride, padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) * scale.astype(
        jnp.float32).reshape(1, -1, 1, 1)


def _im2col(q, kh, kw, stride, dilate, pad):
    """Unfold NCHW int8 activations into patch rows: strided slices
    (one per kernel tap — cheap layout ops XLA folds into the copy)
    stacked so the contraction axis orders (cin, kh, kw), matching
    ``wq.reshape(cout, -1)``."""
    b, cin, h, w = q.shape
    sh, sw = stride
    dh, dw = dilate
    ph, pw = pad
    oh = (h + 2 * ph - ((kh - 1) * dh + 1)) // sh + 1
    ow = (w + 2 * pw - ((kw - 1) * dw + 1)) // sw + 1
    xp = jnp.pad(q, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
    cols = []
    for ki in range(kh):
        for kj in range(kw):
            cols.append(lax.slice(
                xp, (0, 0, ki * dh, kj * dw),
                (b, cin, ki * dh + (oh - 1) * sh + 1,
                 kj * dw + (ow - 1) * sw + 1),
                (1, 1, sh, sw)))                     # (b, cin, oh, ow)
    # (kh*kw, b, cin, oh, ow) -> (b, oh, ow, cin, kh*kw)
    patches = jnp.stack(cols).transpose(1, 3, 4, 2, 0)
    return patches.reshape(b * oh * ow, cin * kh * kw), oh, ow


def int8_conv_im2col(q, wq, scale, stride, dilate, pad, num_group=1,
                     interpret=None):
    """2-D int8 convolution lowered onto the int8 MXU matmul.

    Parameters
    ----------
    q : (b, cin, h, w) int8 — quantized NCHW activations.
    wq : (cout, cin // num_group, kh, kw) int8 — OIHW weights.
    scale : (cout,) float32 — fused per-channel epilogue factor
        (``w_scale / act_scale`` for the quantized conv op).
    stride, dilate, pad : 2-tuples (symmetric padding).
    interpret : forwarded to :func:`int8_matmul`; ``None`` keeps the
        kernel dispatch contract (Mosaic on TPU, the matmul's lax twin
        off-TPU — int32 accumulation makes every route bitwise equal
        to :func:`_int8_conv_xla`).

    Returns (b, cout, oh, ow) float32.
    """
    cout, _, kh, kw = wq.shape
    cout_g = cout // num_group
    cin_g = wq.shape[1]
    outs = []
    for gi in range(num_group):
        qg = q[:, gi * cin_g:(gi + 1) * cin_g]
        wg = wq[gi * cout_g:(gi + 1) * cout_g]
        sg = scale[gi * cout_g:(gi + 1) * cout_g]
        patches, oh, ow = _im2col(qg, kh, kw, stride, dilate, pad)
        outs.append(int8_matmul(patches, wg.reshape(cout_g, -1), sg,
                                interpret=interpret))
    out = jnp.concatenate(outs, axis=-1) if num_group > 1 else outs[0]
    b = q.shape[0]
    return out.reshape(b, oh, ow, cout).transpose(0, 3, 1, 2)
