"""SequentialModule: chain modules end to end.

Capability parity with the reference container
(python/mxnet/module/sequential_module.py:28): each added module
consumes the previous module's outputs as its data; ``take_labels``
marks the modules that also receive the batch labels (typically the
last, the loss), and ``auto_wiring`` renames the previous outputs to
the next module's data names. Intermediate modules are bound with
``inputs_need_grad`` so gradients chain backward through the stack.
"""
from __future__ import annotations

import logging

from ..initializer import Uniform
from ..io import DataBatch, DataDesc
from .base_module import BaseModule

__all__ = ["SequentialModule"]


class SequentialModule(BaseModule):
    """A container chaining sub-modules (reference:
    sequential_module.py SequentialModule)."""

    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=logging):
        super(SequentialModule, self).__init__(logger=logger)
        self._modules = []
        self._metas = []
        self._data_shapes = None
        self._label_shapes = None

    def add(self, module, **kwargs):
        """Append ``module``; kwargs are the META_* flags. Returns self
        so adds chain."""
        known = {self.META_TAKE_LABELS, self.META_AUTO_WIRING}
        for key in kwargs:
            if key not in known:
                raise ValueError("unknown meta %r (have %s)"
                                 % (key, sorted(known)))
        self._modules.append(module)
        self._metas.append(dict(kwargs))
        # adding invalidates any existing binding state
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        return self

    # -- shapes / names ----------------------------------------------------

    @property
    def data_names(self):
        return self._modules[0].data_names if self._modules else []

    @property
    def output_names(self):
        return self._modules[-1].output_names if self._modules else []

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._modules[-1].output_shapes

    # -- params ------------------------------------------------------------

    def get_params(self):
        assert self.binded and self.params_initialized
        arg_params, aux_params = {}, {}
        for module in self._modules:
            arg, aux = module.get_params()
            arg_params.update(arg)
            aux_params.update(aux)
        return arg_params, aux_params

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        seen = {}
        for i, module in enumerate(self._modules):
            module.init_params(initializer=initializer,
                               arg_params=arg_params,
                               aux_params=aux_params,
                               allow_missing=allow_missing,
                               force_init=force_init,
                               allow_extra=allow_extra)
            arg, aux = module.get_params()
            for name in list(arg) + list(aux):
                if name in seen:
                    raise ValueError(
                        "duplicate parameter %r in modules %d and %d — "
                        "chained modules must have disjoint names"
                        % (name, seen[name], i))
                seen[name] = i
        self.params_initialized = True

    # -- bind / optimizer --------------------------------------------------

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        assert shared_module is None, \
            "shared_module is not supported for SequentialModule"
        assert self._modules, "add modules before binding"
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._data_shapes = [DataDesc(*ds) if not isinstance(ds, DataDesc)
                             else ds for ds in data_shapes]
        self._label_shapes = label_shapes

        cur_shapes = self._data_shapes
        last = len(self._modules) - 1
        for i, (module, meta) in enumerate(zip(self._modules, self._metas)):
            labels = label_shapes if meta.get(self.META_TAKE_LABELS) \
                else None
            # auto_wiring on THIS module renames the previous module's
            # outputs to this module's own data names
            if i > 0 and meta.get(self.META_AUTO_WIRING):
                names = module.data_names
                assert len(names) == len(cur_shapes), \
                    "auto_wiring: %d outputs feed %d inputs" % (
                        len(cur_shapes), len(names))
                cur_shapes = [DataDesc(n, d.shape)
                              for n, d in zip(names, cur_shapes)]
            # every module except the first must produce input grads so
            # the backward pass chains through
            need_grad = inputs_need_grad if i == 0 else for_training
            module.bind(data_shapes=cur_shapes, label_shapes=labels,
                        for_training=for_training,
                        inputs_need_grad=need_grad,
                        force_rebind=force_rebind, grad_req=grad_req)
            if i < last:
                cur_shapes = [os if isinstance(os, DataDesc)
                              else DataDesc(*os)
                              for os in module.output_shapes]
        self.binded = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        for module in self._modules:
            module.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                                  optimizer_params=optimizer_params,
                                  force_init=force_init)
        self.optimizer_initialized = True

    # -- compute -----------------------------------------------------------

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        batch = data_batch
        for i, module in enumerate(self._modules):
            module.forward(batch, is_train=is_train)
            if i + 1 == len(self._modules):
                break
            batch = DataBatch(data=module.get_outputs(),
                              label=data_batch.label,
                              pad=getattr(data_batch, "pad", 0))

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        grads = out_grads
        for i, module in reversed(list(enumerate(self._modules))):
            module.backward(out_grads=grads)
            if i == 0:
                break
            grads = module.get_input_grads()

    def update(self):
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        for module in self._modules:
            module.update()

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._modules[-1].get_outputs(
            merge_multi_context=merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized \
            and self.inputs_need_grad
        return self._modules[0].get_input_grads(
            merge_multi_context=merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        assert self.binded and self.params_initialized
        for module, meta in zip(self._modules, self._metas):
            if meta.get(self.META_TAKE_LABELS):
                module.update_metric(eval_metric, labels,
                                     pre_sliced=pre_sliced)

    def install_monitor(self, mon):
        assert self.binded
        for module in self._modules:
            module.install_monitor(mon)
