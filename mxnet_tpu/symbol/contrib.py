"""sym.contrib namespace (reference: python/mxnet/symbol/contrib.py) —
the ``_contrib_*`` ops under their public names, mirroring nd.contrib.
"""
from __future__ import annotations

from .register import populate_prefixed

__all__ = populate_prefixed(__name__, "_contrib_")
