"""NDArray package: eager tensor API + generated op namespace
(reference: python/mxnet/ndarray/__init__.py)."""
from .ndarray import (NDArray, invoke_op, array, zeros, ones, full, empty,
                      arange, concat, stack, waitall)
from .utils import save, load
from . import random
from . import _internal
from . import linalg
from . import contrib
from . import image
from . import sparse

# populate generated op functions (nd.relu, nd.FullyConnected, ...)
from . import register as _register
_register.populate(__name__, __package__ + "._internal")


def onehot_encode(indices, out):
    """Reference: python/mxnet/ndarray/ndarray.py onehot_encode."""
    depth = out.shape[1]
    return invoke_op("one_hot", [indices], {"depth": depth}, out=out)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    from .sparse import BaseSparseNDArray, dot as _sparse_dot
    if isinstance(lhs, BaseSparseNDArray) or \
            isinstance(rhs, BaseSparseNDArray):
        return _sparse_dot(lhs, rhs, transpose_a, transpose_b)
    return invoke_op("dot", [lhs, rhs], {"transpose_a": transpose_a,
                                         "transpose_b": transpose_b})


def cast_storage(arr, stype):
    """Convert between storage types (reference:
    src/operator/tensor/cast_storage-inl.h). Sparse conversions happen
    at the NDArray layer (the FComputeEx analog) since XLA programs keep
    static shapes."""
    from .sparse import BaseSparseNDArray, array as sparse_array
    if stype == "default":
        if isinstance(arr, BaseSparseNDArray):
            return arr.todense()
        return arr
    return sparse_array(arr, stype=stype)
