"""nd.contrib namespace.

Reference: python/mxnet/ndarray/contrib.py (control flow foreach/
while_loop/cond) + generated _contrib_* op bindings (ROIAlign, box_nms,
MultiBoxPrior, CTCLoss, quantization, transformer helpers).
"""
from __future__ import annotations

from ..ops.control_flow import foreach, while_loop, cond  # noqa: F401
from .ndarray import invoke_op

__all__ = ["foreach", "while_loop", "cond", "ROIAlign", "box_iou",
           "bipartite_matching", "box_non_maximum_suppression",
           "box_nms", "MultiBoxPrior", "CTCLoss", "ctc_loss",
           "AdaptiveAvgPooling2D", "BilinearResize2D", "div_sqrt_dim",
           "arange_like", "dot_product_attention", "flash_attention", "quantize",
           "quantize_v2", "dequantize", "requantize",
           "quantized_fully_connected", "quantized_conv",
           "quantized_pooling", "quantized_flatten"]


def _wrap(op_name, public):
    from .ndarray import NDArray

    def fn(*args, **kwargs):
        arrays = []
        for i, a in enumerate(args):
            if isinstance(a, NDArray):
                arrays.append(a)
            elif a is not None:   # None = optional input slot (reference
                raise TypeError(  # convention, e.g. quantized FC bias)
                    "%s: positional argument %d is not an NDArray; pass "
                    "operator parameters by keyword" % (public, i))
        attrs = {k: v for k, v in kwargs.items()
                 if not isinstance(v, NDArray)}
        arrays += [v for v in kwargs.values() if isinstance(v, NDArray)]
        return invoke_op(op_name, arrays, attrs)
    fn.__name__ = public
    return fn


ROIAlign = _wrap("_contrib_ROIAlign", "ROIAlign")
box_iou = _wrap("_contrib_box_iou", "box_iou")
box_nms = _wrap("_contrib_box_nms", "box_nms")
MultiBoxPrior = _wrap("_contrib_MultiBoxPrior", "MultiBoxPrior")
CTCLoss = _wrap("CTCLoss", "CTCLoss")
ctc_loss = CTCLoss
AdaptiveAvgPooling2D = _wrap("_contrib_AdaptiveAvgPooling2D",
                             "AdaptiveAvgPooling2D")
BilinearResize2D = _wrap("_contrib_BilinearResize2D", "BilinearResize2D")
div_sqrt_dim = _wrap("_contrib_div_sqrt_dim", "div_sqrt_dim")
arange_like = _wrap("_contrib_arange_like", "arange_like")
bipartite_matching = _wrap("_contrib_bipartite_matching",
                           "bipartite_matching")
box_non_maximum_suppression = _wrap("_contrib_box_nms",
                                    "box_non_maximum_suppression")
dot_product_attention = _wrap("_contrib_dot_product_attention",
                              "dot_product_attention")
def flash_attention(q, k, v, **kwargs):
    """Pallas flash attention (ops/pallas/flash_attention.py). The
    interpret flag is resolved here from the data's actual device —
    inside the op jit only tracers are visible."""
    if "interpret" not in kwargs:
        from ..ops.pallas.flash_attention import _interpret_default
        kwargs["interpret"] = _interpret_default(q._data)
    return invoke_op("_contrib_flash_attention", [q, k, v], kwargs)
quantize = _wrap("_contrib_quantize", "quantize")
quantize_v2 = _wrap("_contrib_quantize_v2", "quantize_v2")
dequantize = _wrap("_contrib_dequantize", "dequantize")
requantize = _wrap("_contrib_requantize", "requantize")
quantized_fully_connected = _wrap("_contrib_quantized_fully_connected",
                                  "quantized_fully_connected")
quantized_conv = _wrap("_contrib_quantized_conv", "quantized_conv")
quantized_pooling = _wrap("_contrib_quantized_pooling", "quantized_pooling")
quantized_flatten = _wrap("_contrib_quantized_flatten", "quantized_flatten")


def _populate_generated():
    """Expose every registered ``_contrib_*`` op under its public name,
    mirroring the reference's generated contrib bindings
    (python/mxnet/ndarray/register.py)."""
    from ..ops import registry as _reg
    g = globals()
    for op_name in _reg.list_ops():
        if not op_name.startswith("_contrib_"):
            continue
        public = op_name[len("_contrib_"):]
        if public not in g:
            g[public] = _wrap(op_name, public)
            __all__.append(public)


_populate_generated()


def __getattr__(name):  # PEP 562: resolve late-registered contrib ops
    from ..ops import registry as _reg
    op_name = "_contrib_" + name
    if op_name in _reg.list_ops():
        fn = _wrap(op_name, name)
        globals()[name] = fn
        return fn
    raise AttributeError("module %r has no attribute %r"
                         % (__name__, name))
