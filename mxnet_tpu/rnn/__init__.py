"""Legacy symbolic RNN API (reference: python/mxnet/rnn/ — the cell
zoo + BucketSentenceIter the BucketingModule workflow is built on).

TPU note: FusedRNNCell exists for API parity but builds the same
unrolled graph as the unfused cells — under jit, XLA fuses the step
math and the whole unrolled sequence compiles to one program, which is
the TPU analog of the reference's cuDNN fused kernels."""
from .rnn_cell import (RNNParams, BaseRNNCell, RNNCell, LSTMCell, GRUCell,
                       FusedRNNCell, SequentialRNNCell, BidirectionalCell,
                       DropoutCell)
from .io import BucketSentenceIter
from .rnn import (save_rnn_checkpoint, load_rnn_checkpoint,
                  do_rnn_checkpoint)
