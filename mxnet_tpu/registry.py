"""Generic object-registry factories (reference: python/mxnet/registry.py).

The reference manufactures ``register``/``alias``/``create`` functions
per base class (optimizers, initializers, ...) and stores the mapping in
the C registry; here the mapping is a plain per-class dict, and create()
keeps the same creation grammar: a name, ``"name"``/``("name", kwargs)``
pairs, or a JSON string ``'["name", {...}]'``.
"""
from __future__ import annotations

import json

from .base import MXNetError

__all__ = ["get_registry", "get_register_func", "get_alias_func",
           "get_create_func"]

_REGISTRIES = {}


def get_registry(base_class):
    """The (copy of the) name -> class mapping for ``base_class``."""
    return dict(_REGISTRIES.get(base_class, {}))


def get_register_func(base_class, nickname):
    """A decorator registering subclasses of ``base_class`` by
    lower-cased class name (or an explicit name)."""
    reg = _REGISTRIES.setdefault(base_class, {})

    def register(klass, name=None):
        if not issubclass(klass, base_class):
            raise MXNetError("cannot register %s: not a subclass of %s"
                             % (klass.__name__, base_class.__name__))
        key = (name or klass.__name__).lower()
        reg[key] = klass
        return klass

    register.__name__ = "register_" + nickname
    return register


def get_alias_func(base_class, nickname):
    """A decorator adding extra registry names for a class."""
    reg = _REGISTRIES.setdefault(base_class, {})

    def alias(*aliases):
        def wrap(klass):
            for a in aliases:
                reg[a.lower()] = klass
            return klass
        return wrap

    alias.__name__ = "alias_" + nickname
    return alias


def get_create_func(base_class, nickname):
    """A factory accepting an instance (pass-through), a registered
    name, a (name, kwargs) pair, or a JSON '["name", {...}]' string —
    the reference's creation grammar."""
    reg = _REGISTRIES.setdefault(base_class, {})

    def create(*args, **kwargs):
        if args and isinstance(args[0], base_class):
            if len(args) > 1 or kwargs:
                raise MXNetError(
                    "%s is already an instance; extra arguments are not "
                    "allowed" % nickname)
            return args[0]
        if not args:
            raise MXNetError("need a %s name to create" % nickname)
        name, args = args[0], args[1:]
        if isinstance(name, str) and name.startswith("["):
            if args or kwargs:
                raise MXNetError("JSON spec carries its own kwargs")
            spec = json.loads(name)
            name = spec[0]
            kwargs = spec[1] if len(spec) > 1 else {}
        key = str(name).lower()
        if key not in reg:
            raise MXNetError("%s %r is not registered (have: %s)"
                             % (nickname, name, ", ".join(sorted(reg))))
        return reg[key](*args, **kwargs)

    create.__name__ = "create_" + nickname
    return create
