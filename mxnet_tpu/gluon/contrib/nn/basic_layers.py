"""Contrib layers (reference: gluon/contrib/nn/basic_layers.py)."""
from __future__ import annotations

from ...nn.basic_layers import (Sequential, HybridSequential, Embedding,
                                BatchNorm)
from ...block import Block, HybridBlock


def _init(v):
    from ....initializer import create as _create
    return _create(v) if isinstance(v, str) else v

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm"]


class Concurrent(Sequential):
    """Feed the input to every child and concatenate the outputs
    (reference: contrib/nn/basic_layers.py:29)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super(Concurrent, self).__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from .... import ndarray as F
        return F.concat(*[block(x) for block in self._children.values()],
                        dim=self.axis)


class HybridConcurrent(HybridSequential):
    """Hybridizable Concurrent (reference: contrib/nn/basic_layers.py:62)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super(HybridConcurrent, self).__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        return F.concat(*[block(x) for block in self._children.values()],
                        dim=self.axis)


class Identity(HybridBlock):
    """Pass-through block, for skip branches inside Concurrent
    (reference: contrib/nn/basic_layers.py:95)."""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(Block):
    """Embedding whose weight gradient is ROW-SPARSE over the ids in
    the batch (reference: contrib/nn/basic_layers.py:116): O(batch)
    optimizer work per step via the lazy-update kernels instead of
    O(vocab). A Block (not hybridizable), as in the reference — the
    sparse cotangent rides the eager tape."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super(SparseEmbedding, self).__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=_init(weight_initializer),
                grad_stype="row_sparse")

    def forward(self, x):
        from ....ndarray import sparse as nd_sparse
        return nd_sparse.embedding(x, self.weight.data())

    def __repr__(self):
        return "SparseEmbedding(%d -> %d)" % (self._input_dim,
                                              self._output_dim)


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm (reference: contrib/nn/basic_layers.py:163
    over src/operator/contrib/sync_batch_norm-inl.h). Under the GSPMD
    data-parallel paths the batch axis is one logical axis so plain
    batch moments already reduce globally; under explicit shard_map
    pass the mapped axis via ``axis_name``."""

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", axis_name="",
                 **kwargs):
        super(SyncBatchNorm, self).__init__(
            axis=1, momentum=momentum, epsilon=epsilon, center=center,
            scale=scale, use_global_stats=use_global_stats,
            beta_initializer=beta_initializer,
            gamma_initializer=gamma_initializer,
            running_mean_initializer=running_mean_initializer,
            running_variance_initializer=running_variance_initializer,
            in_channels=in_channels, **kwargs)
        self._kwargs.update(ndev=num_devices or 1, key=self.name,
                            axis_name=axis_name)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        from .... import autograd
        if autograd.is_training() and not self._kwargs["use_global_stats"]:
            out, mean, var = F.SyncBatchNorm(
                x, gamma, beta, running_mean, running_var,
                output_mean_var=True, **self._kwargs)
            mom = self._kwargs["momentum"]
            self.running_mean.set_data(running_mean * mom + mean * (1 - mom))
            self.running_var.set_data(running_var * mom + var * (1 - mom))
            return out
        return F.SyncBatchNorm(x, gamma, beta, running_mean, running_var,
                               **self._kwargs)
