"""Spatial sampling ops: BilinearSampler, GridGenerator,
SpatialTransformer.

Reference: src/operator/bilinear_sampler.cc, grid_generator.cc,
spatial_transformer.cc (the STN stack). TPU-native formulation: the
per-pixel bilinear gather is expressed as four batched gathers +
weights, which XLA fuses into one kernel; everything is pure jnp so the
whole stack is differentiable through both data and grid (the reference
hand-writes both backward kernels)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import MXNetError
from .registry import register


def _bilinear_gather(data, xs, ys):
    """Sample data (N,C,H,W) at fractional pixel coords xs/ys (N,oh,ow)
    with zero padding outside the boundary. One shared interpolation
    kernel for the whole ops package: this is the deformable-conv
    gather (_bilinear_chw) vmapped over the batch."""
    from .deformable_ops import _bilinear_chw
    return jax.vmap(_bilinear_chw)(data, ys, xs)


@register("BilinearSampler", attr_defaults={"cudnn_off": False})
def _bilinear_sampler(data, grid, cudnn_off=False, **_ig):
    """data (N,C,H,W), grid (N,2,oh,ow) with normalized coords in
    [-1,1] (grid[:,0]=x, grid[:,1]=y); zero padding outside
    (reference: bilinear_sampler.cc)."""
    N, C, H, W = data.shape
    xs = (grid[:, 0] + 1.0) * (W - 1) / 2.0          # (N,oh,ow)
    ys = (grid[:, 1] + 1.0) * (H - 1) / 2.0
    return _bilinear_gather(data, xs, ys)


@register("GridGenerator",
          attr_defaults={"transform_type": "affine", "target_shape": (0, 0)})
def _grid_generator(data, transform_type="affine", target_shape=(0, 0),
                    **_ig):
    """Generate a normalized sampling grid (reference: grid_generator.cc).

    affine: data (N,6) row-major 2x3 affine applied to normalized
    target coords. warp: data (N,2,h,w) optical flow in pixels added to
    the identity pixel grid, then normalized."""
    if transform_type == "affine":
        h, w = int(target_shape[0]), int(target_shape[1])
        if h <= 0 or w <= 0:
            raise MXNetError("GridGenerator(affine) needs target_shape")
        theta = data.reshape(-1, 2, 3)
        yt, xt = jnp.meshgrid(jnp.linspace(-1.0, 1.0, h),
                              jnp.linspace(-1.0, 1.0, w), indexing="ij")
        ones = jnp.ones_like(xt)
        src = jnp.stack([xt, yt, ones], 0).reshape(3, h * w)   # (3, hw)
        grid = jnp.einsum("nij,jk->nik", theta, src)           # (N,2,hw)
        return grid.reshape(-1, 2, h, w)
    if transform_type == "warp":
        N, two, h, w = data.shape
        yt, xt = jnp.meshgrid(jnp.arange(h, dtype=data.dtype),
                              jnp.arange(w, dtype=data.dtype),
                              indexing="ij")
        x = data[:, 0] + xt
        y = data[:, 1] + yt
        # normalize to [-1, 1] (reference grid_generator.cc warp kernel)
        xn = x * 2.0 / jnp.maximum(w - 1, 1) - 1.0
        yn = y * 2.0 / jnp.maximum(h - 1, 1) - 1.0
        return jnp.stack([xn, yn], 1)
    raise MXNetError("GridGenerator: unknown transform_type %r"
                     % transform_type)


@register("SpatialTransformer",
          attr_defaults={"target_shape": (0, 0),
                         "transform_type": "affine",
                         "sampler_type": "bilinear", "cudnn_off": False})
def _spatial_transformer(data, loc, target_shape=(0, 0),
                         transform_type="affine",
                         sampler_type="bilinear", cudnn_off=False, **_ig):
    """STN: grid from ``loc``, bilinear-sample ``data`` on it
    (reference: spatial_transformer.cc)."""
    if transform_type != "affine" or sampler_type != "bilinear":
        raise MXNetError("SpatialTransformer supports affine+bilinear "
                         "(reference parity)")
    grid = _grid_generator(loc, "affine", target_shape)
    return _bilinear_sampler(data, grid)
