"""Flash attention as a Pallas TPU kernel.

Capability analog of the reference's fused transformer attention ops
(reference: src/operator/contrib/transformer-inl.h) redesigned for TPU:
instead of materialising the (S, S) score matrix in HBM, the kernel
streams K/V blocks through VMEM with an online-softmax accumulator, so
memory is O(S * d) and the matmuls stay on the MXU.

Forward  = Pallas kernel over grid (batch*heads, q_blocks, k_blocks);
           scratch accumulators (m, l, acc) persist across the k grid
           dimension (TPU grids iterate the trailing dim sequentially).
Backward = blockwise lax.scan recomputation from the saved per-row
           log-sum-exp (flash-attention-2 style: p = exp(qk - lse)),
           memory O(block * S), fully fused by XLA.

Layout: (batch, heads, seq, head_dim).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention", "paged_decode_attention",
           "flash_prefill_paged"]

# kernel-contract registry: every exported Pallas kernel maps to its
# module-level pure-lax twin (tools/check_pallas_contracts.py fails the
# suite if an exported kernel is missing here, its twin touches
# pallas_call, or tests/ lacks an interpret-mode parity test)
PALLAS_KERNELS = {
    "flash_attention": "_flash_fwd_xla",
    "paged_decode_attention": "_paged_decode_xla",
    "flash_prefill_paged": "_flash_prefill_xla",
}

NEG_INF = -1e30
_LANES = 128


def _interpret_default(x):
    """Interpret (emulate) the kernel unless the data actually lives on
    TPU: compiled Mosaic kernels only lower for the TPU backend, and jit
    follows committed input devices (a cpu(0)-context NDArray must not
    hit the TPU lowering, and vice versa)."""
    try:
        return any(d.platform != "tpu" for d in x.devices())
    except Exception:  # tracer inside an outer jit: no device info
        return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, sm_scale, causal, block_q, block_k,
                seq_len):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_start = qi * block_q
    k_start = ki * block_k

    def _body():
        q = q_ref[0].astype(jnp.float32) * sm_scale          # (bq, d)
        k = k_ref[0].astype(jnp.float32)                     # (bk, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bq, bk)

        # mask out-of-range keys (padding) and the causal triangle
        kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = kpos < seq_len
        if causal:
            qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            mask = jnp.logical_and(mask, kpos <= qpos)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, :1]                                # (bq, 1)
        m_cur = jnp.max(s, axis=-1, keepdims=True)           # (bq, 1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)                      # (bq, 1)
        p = jnp.exp(s - m_new)                               # (bq, bk)

        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        v = v_ref[0].astype(jnp.float32)                     # (bk, d)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bq, d)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # skip K blocks entirely above the causal diagonal
        @pl.when(k_start <= q_start + block_q - 1)
        def _():
            _body()
    else:
        _body()

    @pl.when(ki == nk - 1)
    def _fin():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse = (m_scr[:, :1] + jnp.log(l_safe))               # (bq, 1)
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:]).astype(
            lse_ref.dtype)


def _pad_to(x, mult, axis):
    rem = x.shape[axis] % mult
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, mult - rem)
    return jnp.pad(x, pad)


def _out_vma(*xs):
    """Union of the inputs' varying-across-mesh axes, so pallas_call
    outputs carry the right `vma` under shard_map(check_vma=True)."""
    vma = frozenset()
    for x in xs:
        try:
            vma |= frozenset(jax.typeof(x).vma)
        except Exception:
            pass
    return vma


def _sds(shape, dtype, vma):
    """ShapeDtypeStruct carrying `vma` where this jax supports it;
    jax 0.4.x has no varying-axes tracking to propagate (shard_map
    check_rep covers replication there)."""
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:
        return jax.ShapeDtypeStruct(shape, dtype)


def _flash_fwd_xla(q, k, v, causal, sm_scale):
    """Plain-XLA twin of the kernel (same (o, lse) contract).

    Used when the kernel would run under the Pallas *interpreter* inside
    a shard_map manual context: the interpreter's internal dynamic_slice
    ops trip check_vma there (JAX-internal limitation). Off the manual
    path the interpreter still exercises the real kernel logic, and on
    TPU the compiled Mosaic kernel always runs.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        qpos = jnp.arange(q.shape[2])[:, None]
        kpos = jnp.arange(k.shape[2])[None, :]
        s = jnp.where((kpos <= qpos)[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o = jnp.einsum("bhqk,bhkd->bhqd", p / l_safe, v.astype(jnp.float32))
    lse = (m + jnp.log(l_safe))[..., 0]
    return o.astype(q.dtype), lse


@functools.partial(jax.jit, static_argnames=("causal", "sm_scale", "block_q",
                                             "block_k", "interpret"))
def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    if interpret and _out_vma(q, k, v):
        return _flash_fwd_xla(q, k, v, causal, sm_scale)
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    qf = q.reshape(b * h, s_q, d)
    kf = k.reshape(b * h, s_k, d)
    vf = v.reshape(b * h, s_k, d)

    qf = _pad_to(qf, block_q, 1)
    kf = _pad_to(kf, block_k, 1)
    vf = _pad_to(vf, block_k, 1)
    sp_q, sp_k = qf.shape[1], kf.shape[1]
    grid = (b * h, sp_q // block_q, sp_k // block_k)

    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, seq_len=s_k)

    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_shape=[
            _sds((b * h, sp_q, d), q.dtype, _out_vma(q, k, v)),
            _sds((b * h, sp_q, _LANES), jnp.float32, _out_vma(q, k, v)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        # renamed TPUCompilerParams -> CompilerParams across jax releases
        compiler_params=getattr(pltpu, "CompilerParams",
                                getattr(pltpu, "TPUCompilerParams", None))(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf)

    o = o[:, :s_q].reshape(b, h, s_q, d)
    lse = lse[:, :s_q, 0].reshape(b, h, s_q)
    return o, lse


# ---------------------------------------------------------------------------
# backward: blockwise recomputation from saved lse (XLA, scan over k blocks)
# ---------------------------------------------------------------------------

def _flash_bwd(causal, sm_scale, block_q, block_k, interpret, res, g):
    q, k, v, o, lse = res
    del block_q, interpret
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    g = g.astype(jnp.float32)
    qf = q.astype(jnp.float32) * sm_scale
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)

    # delta_i = sum_d o_i * do_i  (rowwise), standard flash-bwd shortcut
    delta = jnp.sum(o.astype(jnp.float32) * g, axis=-1)          # (b,h,sq)

    nk = max(1, -(-s_k // block_k))
    pad_k = nk * block_k - s_k
    if pad_k:
        kf = jnp.pad(kf, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    kpos = jnp.arange(nk * block_k)
    qpos = jnp.arange(s_q)

    def kblock(carry, kb):
        dq_acc = carry
        ks = kb * block_k
        kblk = jax.lax.dynamic_slice_in_dim(kf, ks, block_k, axis=2)
        vblk = jax.lax.dynamic_slice_in_dim(vf, ks, block_k, axis=2)
        kp = jax.lax.dynamic_slice_in_dim(kpos, ks, block_k)
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kblk)              # (b,h,sq,bk)
        mask = (kp[None, None, None, :] < s_k)
        if causal:
            mask = jnp.logical_and(
                mask, kp[None, None, None, :] <= qpos[None, None, :, None])
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse[..., None])                          # (b,h,sq,bk)
        p = jnp.where(mask, p, 0.0)
        dv = jnp.einsum("bhqk,bhqd->bhkd", p, g)
        dp = jnp.einsum("bhqd,bhkd->bhqk", g, vblk)
        ds = p * (dp - delta[..., None])                         # (b,h,sq,bk)
        dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)               # scaled q
        dq_acc = dq_acc + jnp.einsum("bhqk,bhkd->bhqd", ds, kblk)
        return dq_acc, (dk, dv)

    # init carry derives from qf so its varying-across-mesh axes match
    # the body output under an enclosing shard_map (scan rejects a
    # non-varying init against a varying carry)
    dq, (dks, dvs) = jax.lax.scan(kblock, qf * 0.0, jnp.arange(nk))
    dk = jnp.moveaxis(dks, 0, 2).reshape(b, h, nk * block_k, d)[:, :, :s_k]
    dv = jnp.moveaxis(dvs, 0, 2).reshape(b, h, nk * block_k, d)[:, :, :s_k]
    dq = dq * sm_scale
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    o, _ = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret)
    return o


def _flash_vjp_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    o, lse = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


_flash.defvjp(_flash_vjp_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, sm_scale=None,
                    block_q=128, block_k=128, interpret=None):
    """Memory-efficient attention: ``softmax(Q K^T * scale [+ mask]) V``.

    Parameters
    ----------
    q, k, v : arrays of shape (batch, heads, seq, head_dim).
    causal : apply a lower-triangular mask.
    sm_scale : score scale; default ``1/sqrt(head_dim)``.
    block_q, block_k : VMEM tile sizes (multiples of 128 on TPU).
    interpret : force pallas interpreter mode (defaults to True off-TPU).
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        interpret = _interpret_default(q)
    block_q = min(block_q, max(8, q.shape[2]))
    block_k = min(block_k, max(8, k.shape[2]))
    return _flash(q, k, v, bool(causal), float(sm_scale),
                  int(block_q), int(block_k), bool(interpret))


# ---------------------------------------------------------------------------
# paged decode attention (serving: one query token per sequence against
# a block-table-addressed page pool — serve/decode.py's hot kernel)
# ---------------------------------------------------------------------------

def _paged_decode_xla(q, k_pages, v_pages, block_tables, lengths,
                      sm_scale):
    """Pure-lax twin of the paged kernel (the CPU tier-1 path and the
    numeric reference): block-table gather materializes each row's
    (L, kv_heads, hd) view, then standard masked GQA softmax."""
    b, kvh, g, hd = q.shape
    kc = k_pages[block_tables]           # (b, pages, page_size, kvh, hd)
    vc = v_pages[block_tables]
    L = kc.shape[1] * kc.shape[2]
    kc = kc.reshape(b, L, kvh, hd).transpose(0, 2, 1, 3)
    vc = vc.reshape(b, L, kvh, hd).transpose(0, 2, 1, 3)
    visible = jnp.arange(L)[None, :] < lengths[:, None]       # (b, L)
    sc = jnp.einsum("bkgd,bkld->bkgl", q.astype(jnp.float32),
                    kc.astype(jnp.float32)) * sm_scale
    sc = jnp.where(visible[:, None, None, :], sc, NEG_INF)
    o = jnp.einsum("bkgl,bkld->bkgd", jax.nn.softmax(sc, -1),
                   vc.astype(jnp.float32))
    return o.astype(q.dtype)


def _paged_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                  m_scr, l_scr, acc_scr, *, page_size, sm_scale):
    """Grid (b, kv_heads, pages_per_seq): the trailing page dimension
    iterates sequentially per (sequence, head), accumulating an online
    softmax in VMEM scratch exactly like the flash forward kernel —
    the block table is scalar-prefetched so each step's page DMA is
    issued from ``block_tables[b, p]`` before the body runs."""
    b_i = pl.program_id(0)
    p_i = pl.program_id(2)
    n_p = pl.num_programs(2)

    @pl.when(p_i == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    length = len_ref[b_i]
    start = p_i * page_size

    @pl.when(start < length)
    def _body():
        q = q_ref[0, 0].astype(jnp.float32) * sm_scale       # (g, hd)
        k = k_ref[0, :, 0].astype(jnp.float32)               # (ps, hd)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # (g, ps)
        kpos = start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(kpos < length, s, NEG_INF)

        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, -1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, -1, keepdims=True)
        v = v_ref[0, :, 0].astype(jnp.float32)               # (ps, hd)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # (g, hd)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(p_i == n_p - 1)
    def _fin():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def paged_decode_attention(q, k_pages, v_pages, block_tables, lengths,
                           sm_scale=None, interpret=None):
    """Decode-phase attention against a PAGED KV cache: one query token
    per sequence, keys/values gathered page-by-page via a block table.

    Parameters
    ----------
    q : (b, kv_heads, group, head_dim) — query heads grouped per shared
        K/V head (GQA layout; ``group = n_heads // kv_heads``).
    k_pages, v_pages : (num_pages, page_size, kv_heads, head_dim) —
        one layer's slice of the shared page pool.
    block_tables : (b, pages_per_seq) int32 — page ids per row, in
        position order.
    lengths : (b,) int32 — row ``r`` attends positions ``< lengths[r]``.

    Returns (b, kv_heads, group, head_dim). Forward-only (serving);
    no VJP is defined. On TPU this is a Mosaic kernel whose page DMAs
    are issued from the scalar-prefetched block table, so HBM traffic
    is exactly the live pages of each sequence; off-TPU (and under the
    interpreter inside shard_map) the pure-lax gather twin runs —
    same contract, the tier-1 path.
    """
    b, kvh, g, hd = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / (hd ** 0.5)
    block_tables = jnp.asarray(block_tables, jnp.int32)
    lengths = jnp.asarray(lengths, jnp.int32)
    if interpret is None:
        if _interpret_default(q):
            # production off-TPU path: the XLA twin, not a python-
            # interpreted per-page DMA emulation (interpret=True still
            # forces the interpreter for kernel-logic tests)
            return _paged_decode_xla(q, k_pages, v_pages, block_tables,
                                     lengths, float(sm_scale))
        interpret = False
    return _paged_decode(q, k_pages, v_pages, block_tables, lengths,
                         float(sm_scale), bool(interpret))


@functools.partial(jax.jit, static_argnames=("sm_scale", "interpret"))
def _paged_decode(q, k_pages, v_pages, block_tables, lengths, sm_scale,
                  interpret):
    b, kvh, g, hd = q.shape
    num_pages, page_size = k_pages.shape[:2]
    n_pb = block_tables.shape[1]
    grid = (b, kvh, n_pb)

    def q_map(b_i, h_i, p_i, bt, ln):
        return (b_i, h_i, 0, 0)

    def kv_map(b_i, h_i, p_i, bt, ln):
        return (bt[b_i, p_i], 0, h_i, 0)

    spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, g, hd), q_map),
            pl.BlockSpec((1, page_size, 1, hd), kv_map),
            pl.BlockSpec((1, page_size, 1, hd), kv_map),
        ],
        out_specs=pl.BlockSpec((1, 1, g, hd), q_map),
        scratch_shapes=[
            pltpu.VMEM((g, _LANES), jnp.float32),
            pltpu.VMEM((g, _LANES), jnp.float32),
            pltpu.VMEM((g, hd), jnp.float32),
        ],
    )
    kernel = functools.partial(_paged_kernel, page_size=page_size,
                               sm_scale=sm_scale)
    return pl.pallas_call(
        kernel,
        grid_spec=spec,
        out_shape=_sds((b, kvh, g, hd), q.dtype,
                       _out_vma(q, k_pages, v_pages)),
        compiler_params=getattr(pltpu, "CompilerParams",
                                getattr(pltpu, "TPUCompilerParams", None))(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(block_tables, lengths, q, k_pages, v_pages)


# ---------------------------------------------------------------------------
# paged prefill attention (serving: one batched causal forward over the
# whole prompt bucket, with the reshape-scatter page write fused into
# the kernel as a DMA epilogue — prefill's XLA boundary the forensics
# worst-fusions report ranks worst is exactly this scatter round-trip)
# ---------------------------------------------------------------------------

def _flash_prefill_xla(q, kg, vg, k_pages, v_pages, block_tables):
    """Pure-lax twin of :func:`flash_prefill_paged` — op-for-op the
    attention + page write of ``transformer._prefill_impl``'s paged
    branch (expand-KV einsum / sqrt(hd), tril mask, softmax, and the
    ``at[bt].set`` reshape-scatter), so the CPU tier-1 prefill path and
    the dense==paged bitwise contract are this exact computation."""
    b, s, nh, hd = q.shape
    kvh = kg.shape[2]
    groups = nh // kvh
    ps = k_pages.shape[1]
    n_pb = s // ps
    k = kg if groups == 1 else jnp.repeat(kg, groups, axis=2)
    v = vg if groups == 1 else jnp.repeat(vg, groups, axis=2)
    mask = jnp.tril(jnp.ones((s, s), bool))
    sc = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(hd)
    sc = jnp.where(mask[None, None], sc, NEG_INF)
    o = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(sc, -1), v)
    bt = block_tables[:, :n_pb]
    kp = k_pages.at[bt].set(
        kg.reshape(b, n_pb, ps, kvh, hd).astype(k_pages.dtype))
    vp = v_pages.at[bt].set(
        vg.reshape(b, n_pb, ps, kvh, hd).astype(v_pages.dtype))
    return o, kp, vp


def _prefill_kernel(bt_ref, q_ref, k_ref, v_ref, kg_ref, vg_ref,
                    kp_in, vp_in, o_ref, kp_out, vp_out,
                    m_scr, l_scr, acc_scr, ksem, vsem, *,
                    sm_scale, block_q, block_k, page_size, seq_len):
    """Grid (b, heads, q_blocks, k_blocks): per (batch, head, q tile)
    the trailing k dimension accumulates an online softmax in VMEM
    scratch exactly like ``_fwd_kernel``, but K/V stay in the compact
    GQA layout — grouped query heads index their shared K/V head via
    the block index map, never materialising the expanded (b, s, nh,
    hd) tensors the lax twin builds. The page write rides the same
    pass: the first (head, q-tile) visit of each k block DMAs that
    block's freshly computed K/V straight from HBM into its rows' pool
    pages (``block_k`` is a multiple of ``page_size``, so each page is
    written exactly once per layer and the separate reshape-scatter
    program — and its HBM round-trip — disappears)."""
    b_i = pl.program_id(0)
    h_i = pl.program_id(1)
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_start = qi * block_q
    k_start = ki * block_k

    @pl.when(jnp.logical_and(h_i == 0, qi == 0))
    def _write_pages():
        for j in range(block_k // page_size):
            page = bt_ref[b_i, ki * (block_k // page_size) + j]
            src = pl.ds(k_start + j * page_size, page_size)
            kcp = pltpu.make_async_copy(kg_ref.at[b_i, src],
                                        kp_out.at[page], ksem)
            vcp = pltpu.make_async_copy(vg_ref.at[b_i, src],
                                        vp_out.at[page], vsem)
            kcp.start()
            vcp.start()
            kcp.wait()
            vcp.wait()

    def _body():
        q = q_ref[0, :, 0].astype(jnp.float32) * sm_scale     # (bq, d)
        k = k_ref[0, :, 0].astype(jnp.float32)                # (bk, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)               # (bq, bk)
        kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        s = jnp.where(jnp.logical_and(kpos < seq_len, kpos <= qpos),
                      s, NEG_INF)
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, -1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, -1, keepdims=True)
        v = v_ref[0, :, 0].astype(jnp.float32)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (bq, d)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    # skip K blocks entirely above the causal diagonal (the page-write
    # epilogue above must NOT be skipped: padded-tail pages are still
    # written, exactly like the twin's scatter)
    @pl.when(k_start <= q_start + block_q - 1)
    def _():
        _body()

    @pl.when(ki == nk - 1)
    def _fin():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, :, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_q", "block_k",
                                             "interpret"))
def _flash_prefill(q, kg, vg, k_pages, v_pages, block_tables,
                   block_q, block_k, interpret):
    b, s, nh, hd = q.shape
    kvh = kg.shape[2]
    groups = nh // kvh
    ps = k_pages.shape[1]
    sm_scale = 1.0 / math.sqrt(hd)
    grid = (b, nh, s // block_q, s // block_k)

    def q_map(b_i, h_i, qi, ki, bt):
        return (b_i, qi, h_i, 0)

    def kv_map(b_i, h_i, qi, ki, bt):
        return (b_i, ki, h_i // groups, 0)

    spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, 1, hd), q_map),
            pl.BlockSpec((1, block_k, 1, hd), kv_map),
            pl.BlockSpec((1, block_k, 1, hd), kv_map),
            pl.BlockSpec(memory_space=pltpu.ANY),   # kg: page-write src
            pl.BlockSpec(memory_space=pltpu.ANY),   # vg: page-write src
            pl.BlockSpec(memory_space=pltpu.ANY),   # k_pages (aliased)
            pl.BlockSpec(memory_space=pltpu.ANY),   # v_pages (aliased)
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, 1, hd), q_map),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, hd), jnp.float32),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
    )
    kernel = functools.partial(
        _prefill_kernel, sm_scale=sm_scale, block_q=block_q,
        block_k=block_k, page_size=ps, seq_len=s)
    vma = _out_vma(q, kg, vg, k_pages, v_pages)
    return pl.pallas_call(
        kernel,
        grid_spec=spec,
        out_shape=[
            _sds((b, s, nh, hd), q.dtype, vma),
            _sds(k_pages.shape, k_pages.dtype, vma),
            _sds(v_pages.shape, v_pages.dtype, vma),
        ],
        # pool arrays alias in->out: pages no row writes keep their
        # contents, and on TPU the pool is updated in place (operand
        # order counts the scalar-prefetch arg: bt=0 ... k_pages=6)
        input_output_aliases={6: 1, 7: 2},
        compiler_params=getattr(pltpu, "CompilerParams",
                                getattr(pltpu, "TPUCompilerParams", None))(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(block_tables, q, kg, vg, kg, vg, k_pages, v_pages)


def flash_prefill_paged(q, kg, vg, k_pages, v_pages, block_tables,
                        block_q=128, block_k=128, interpret=None):
    """Prefill-phase flash attention over a paged KV pool: one batched
    causal forward per layer whose epilogue writes the prompt's K/V
    pages, replacing ``(s, s)``-score XLA attention + a separate
    reshape-scatter program.

    Parameters
    ----------
    q : (b, s, n_heads, head_dim) — prompt queries (RoPE-rotated).
    kg, vg : (b, s, kv_heads, head_dim) — compact GQA K/V; the kernel
        never materialises the ``n_heads``-expanded copies.
    k_pages, v_pages : (num_pages, page_size, kv_heads, head_dim) —
        one layer's slice of the shared pool; returned updated (the
        arrays alias in->out).
    block_tables : (b, pages_per_row) int32 — destination page ids in
        position order (``pages_per_row = s // page_size``); rows of a
        warmup batch may all point at the reserved null page 0.

    Returns ``(o, k_pages, v_pages)`` with ``o`` (b, s, n_heads,
    head_dim). Score scale is fixed at ``1/sqrt(head_dim)``. Causal
    only: position ``i`` attends ``<= i`` (ragged prompts rely on this
    plus the caller's final ``lengths-1`` logit gather, exactly like
    the XLA path). Forward-only (serving); no VJP. Off-TPU the
    pure-lax twin (the tier-1 path) runs; ``interpret=True`` forces
    the Pallas interpreter for parity tests."""
    b, s, nh, hd = q.shape
    ps = k_pages.shape[1]
    if s % ps:
        raise ValueError("prefill bucket %d is not a multiple of "
                         "page_size %d" % (s, ps))
    if s // ps > block_tables.shape[1]:
        raise ValueError("prefill bucket %d needs %d pages/row; "
                         "block table holds %d"
                         % (s, s // ps, block_tables.shape[1]))
    block_tables = jnp.asarray(block_tables, jnp.int32)[:, :s // ps]
    if interpret is None:
        if _interpret_default(q):
            return _flash_prefill_xla(q, kg, vg, k_pages, v_pages,
                                      block_tables)
        interpret = False
    # block_k must be a multiple of page_size (each page written by
    # exactly one k block) and divide s; block_q must divide s
    block_k = max(ps, (min(block_k, s) // ps) * ps)
    while s % block_k:
        block_k -= ps
    block_q = min(block_q, s)
    while s % block_q:
        block_q //= 2
    return _flash_prefill(q, kg, vg, k_pages, v_pages, block_tables,
                          int(block_q), int(block_k), bool(interpret))
