/*
 * C predict ABI for mxnet_tpu (implementation:
 * src/native/c_predict_api.cc). Capability analog of the reference's
 * include/mxnet/c_predict_api.h — the minimal inference surface
 * language bindings link against (cpp-package predictor.hpp, the
 * amalgamation build, and perl-package all consume this header's
 * contract).
 */
#ifndef MXNET_TPU_C_PREDICT_API_H_
#define MXNET_TPU_C_PREDICT_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* PredictorHandle;

const char* MXGetLastError(void);

int MXPredCreate(const char* symbol_json_str, const void* param_bytes,
                 int param_size, int dev_type, int dev_id,
                 uint32_t num_input_nodes, const char** input_keys,
                 const uint32_t* input_shape_indptr,
                 const uint32_t* input_shape_data, PredictorHandle* out);
int MXPredSetInput(PredictorHandle handle, const char* key,
                   const float* data, uint32_t size);
int MXPredForward(PredictorHandle handle);
int MXPredGetOutputShape(PredictorHandle handle, uint32_t index,
                         uint32_t* shape_data, uint32_t* shape_ndim);
int MXPredGetOutput(PredictorHandle handle, uint32_t index, float* data,
                    uint32_t size);
int MXPredFree(PredictorHandle handle);

#ifdef __cplusplus
}
#endif

#endif  /* MXNET_TPU_C_PREDICT_API_H_ */
