#!/usr/bin/env python
"""Pack an image folder / list file into RecordIO.

Reference: tools/im2rec.py (+ the C++ tools/im2rec.cc) — same CLI shape:
  python tools/im2rec.py PREFIX ROOT --list      # generate .lst
  python tools/im2rec.py PREFIX ROOT             # pack .lst -> .rec/.idx
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def list_images(root, recursive, exts):
    i = 0
    cat = {}
    for path, dirs, files in sorted(os.walk(root, followlinks=True)):
        dirs.sort()
        files.sort()
        for fname in files:
            fpath = os.path.join(path, fname)
            suffix = os.path.splitext(fname)[1].lower()
            if os.path.isfile(fpath) and suffix in exts:
                label_dir = os.path.relpath(path, root)
                if label_dir not in cat:
                    cat[label_dir] = len(cat)
                yield (i, os.path.relpath(fpath, root), cat[label_dir])
                i += 1
        if not recursive:
            break


def write_list(path_out, image_list):
    with open(path_out, "w") as fout:
        for i, (idx, fname, label) in enumerate(image_list):
            fout.write("%d\t%f\t%s\n" % (idx, label, fname))


def read_list(path_in):
    with open(path_in) as fin:
        for line in fin:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            yield (int(parts[0]),
                   [float(x) for x in parts[1:-1]], parts[-1])


def make_rec(args, path_lst):
    from mxnet_tpu import recordio, image
    prefix = os.path.splitext(path_lst)[0]
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    count = 0
    for idx, label, fname in read_list(path_lst):
        fpath = os.path.join(args.root, fname)
        with open(fpath, "rb") as f:
            buf = f.read()
        label_val = label[0] if len(label) == 1 else label
        if args.resize or args.quality != 95:
            img = image.imdecode(buf)
            if args.resize:
                img = image.resize_short(img, args.resize)
            packed = recordio.pack_img(
                (0, label_val, idx, 0), img.asnumpy(),
                quality=args.quality, img_fmt=args.encoding)
        else:
            packed = recordio.pack((0, label_val, idx, 0), buf)
        rec.write_idx(idx, packed)
        count += 1
        if count % 1000 == 0:
            print("packed %d records" % count)
    rec.close()
    print("wrote %d records to %s.rec" % (count, prefix))


def main():
    parser = argparse.ArgumentParser(
        description="Create an image list and/or RecordIO pack")
    parser.add_argument("prefix", help="prefix of output list/rec files")
    parser.add_argument("root", help="image root folder")
    parser.add_argument("--list", action="store_true",
                        help="generate the .lst file only")
    parser.add_argument("--exts", nargs="+",
                        default=[".jpeg", ".jpg", ".png"])
    parser.add_argument("--recursive", action="store_true", default=True)
    parser.add_argument("--shuffle", action="store_true", default=True)
    parser.add_argument("--train-ratio", type=float, default=1.0)
    parser.add_argument("--resize", type=int, default=0)
    parser.add_argument("--quality", type=int, default=95)
    parser.add_argument("--encoding", default=".jpg")
    args = parser.parse_args()

    if args.list:
        images = list(list_images(args.root, args.recursive, set(args.exts)))
        if args.shuffle:
            random.seed(100)
            random.shuffle(images)
        if args.train_ratio < 1.0:
            sep = int(len(images) * args.train_ratio)
            write_list(args.prefix + "_train.lst", images[:sep])
            write_list(args.prefix + "_val.lst", images[sep:])
        else:
            write_list(args.prefix + ".lst", images)
        return
    path_lst = args.prefix + ".lst"
    if not os.path.exists(path_lst):
        raise SystemExit("list file %s not found; run with --list first"
                         % path_lst)
    make_rec(args, path_lst)


if __name__ == "__main__":
    main()
