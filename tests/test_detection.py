"""Detection stack: MultiBoxTarget/MultiBoxDetection/Proposal ops,
ImageDetIter + bbox augmenters, SSD smoke training.

Reference behavior: src/operator/contrib/multibox_target.cc,
multibox_detection.cc, proposal.cc, src/io/image_det_aug_default.cc,
python/mxnet/image/detection.py.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import ndarray as nd


# ---------------------------------------------------------------------------
# MultiBoxTarget


def _mbt(anchors, labels, cls_pred, **kw):
    return nd.contrib.MultiBoxTarget(
        mx.nd.array(anchors), mx.nd.array(labels), mx.nd.array(cls_pred),
        **kw)


def test_multibox_target_perfect_match():
    # one anchor exactly over the gt box -> positive with zero offsets
    anchors = np.array([[[0.1, 0.1, 0.5, 0.5],
                         [0.6, 0.6, 0.9, 0.9]]], np.float32)
    labels = np.array([[[1.0, 0.1, 0.1, 0.5, 0.5]]], np.float32)
    cls_pred = np.zeros((1, 3, 2), np.float32)
    loc_t, loc_m, cls_t = _mbt(anchors, labels, cls_pred)
    assert loc_t.shape == (1, 8) and cls_t.shape == (1, 2)
    ct = cls_t.asnumpy()[0]
    assert ct[0] == 2.0          # gt class 1 -> target 1+1
    assert ct[1] == 0.0          # background
    lm = loc_m.asnumpy()[0]
    np.testing.assert_array_equal(lm, [1, 1, 1, 1, 0, 0, 0, 0])
    np.testing.assert_allclose(loc_t.asnumpy()[0][:4], 0.0, atol=1e-5)


def test_multibox_target_encoding_roundtrip():
    # encode then decode via MultiBoxDetection must recover the gt box
    anchors = np.array([[[0.2, 0.2, 0.6, 0.7]]], np.float32)
    gt = np.array([0.25, 0.15, 0.55, 0.66], np.float32)
    labels = np.concatenate([[3.0], gt]).reshape(1, 1, 5).astype(np.float32)
    cls_pred = np.zeros((1, 5, 1), np.float32)
    loc_t, loc_m, cls_t = _mbt(anchors, labels, cls_pred,
                               overlap_threshold=0.3)
    assert cls_t.asnumpy()[0, 0] == 4.0
    # decode: variances match defaults
    v = (0.1, 0.1, 0.2, 0.2)
    a = anchors[0, 0]
    aw, ah = a[2] - a[0], a[3] - a[1]
    ax, ay = (a[0] + a[2]) / 2, (a[1] + a[3]) / 2
    t = loc_t.asnumpy()[0]
    ox = t[0] * v[0] * aw + ax
    oy = t[1] * v[1] * ah + ay
    ow = np.exp(t[2] * v[2]) * aw / 2
    oh = np.exp(t[3] * v[3]) * ah / 2
    np.testing.assert_allclose(
        [ox - ow, oy - oh, ox + ow, oy + oh], gt, rtol=1e-4, atol=1e-5)


def test_multibox_target_bipartite_claims_best():
    # two anchors both overlap the single gt; only the better one is
    # positive via bipartite matching (threshold disabled by 0.9)
    anchors = np.array([[[0.0, 0.0, 0.5, 0.5],
                         [0.05, 0.05, 0.55, 0.55]]], np.float32)
    labels = np.array([[[0.0, 0.05, 0.05, 0.55, 0.55]]], np.float32)
    cls_pred = np.zeros((1, 2, 2), np.float32)
    _, loc_m, cls_t = _mbt(anchors, labels, cls_pred,
                           overlap_threshold=0.95)
    ct = cls_t.asnumpy()[0]
    assert ct[1] == 1.0 and ct[0] == 0.0
    np.testing.assert_array_equal(loc_m.asnumpy()[0], [0] * 4 + [1] * 4)


def test_multibox_target_negative_mining():
    # 4 anchors, 1 positive; ratio 1 -> exactly 1 negative kept, the
    # other two anchors ignored (-1)
    anchors = np.zeros((1, 4, 4), np.float32)
    anchors[0, 0] = [0.1, 0.1, 0.4, 0.4]
    anchors[0, 1] = [0.5, 0.5, 0.6, 0.6]
    anchors[0, 2] = [0.7, 0.7, 0.8, 0.8]
    anchors[0, 3] = [0.85, 0.85, 0.95, 0.95]
    labels = np.array([[[2.0, 0.1, 0.1, 0.4, 0.4]]], np.float32)
    cls_pred = np.zeros((1, 3, 4), np.float32)
    # anchor 2 least background-like -> hardest negative
    cls_pred[0, 0] = [5.0, 5.0, -5.0, 5.0]
    loc_t, loc_m, cls_t = _mbt(anchors, labels, cls_pred,
                               negative_mining_ratio=1.0,
                               negative_mining_thresh=0.5)
    ct = cls_t.asnumpy()[0]
    assert ct[0] == 3.0                     # positive, class 2 + 1
    assert ct[2] == 0.0                     # mined negative
    assert ct[1] == -1.0 and ct[3] == -1.0  # ignored


# ---------------------------------------------------------------------------
# MultiBoxDetection


def test_multibox_detection_decode_and_nms():
    anchors = np.array([[[0.1, 0.1, 0.5, 0.5],
                         [0.12, 0.12, 0.52, 0.52],
                         [0.6, 0.6, 0.9, 0.9]]], np.float32)
    # zero offsets -> boxes == anchors
    loc_pred = np.zeros((1, 12), np.float32)
    cls_prob = np.array([[[0.1, 0.2, 0.8],     # background
                          [0.8, 0.1, 0.1],     # class 0
                          [0.1, 0.7, 0.1]]], np.float32)  # class 1
    out = nd.contrib.MultiBoxDetection(
        mx.nd.array(cls_prob), mx.nd.array(loc_pred), mx.nd.array(anchors),
        nms_threshold=0.5, threshold=0.05, force_suppress=True)
    o = out.asnumpy()[0]
    assert out.shape == (1, 3, 6)
    # of the two overlapping anchors force_suppress keeps the higher;
    # the far-away third anchor survives regardless of class
    kept = o[o[:, 0] >= 0]
    assert len(kept) == 2
    assert kept[0][0] == 0.0 and abs(kept[0][1] - 0.8) < 1e-5
    np.testing.assert_allclose(kept[0][2:], anchors[0, 0], atol=1e-5)
    np.testing.assert_allclose(kept[1][2:], anchors[0, 2], atol=1e-5)


def test_multibox_detection_per_class_nms():
    # same boxes, different classes: per-class NMS keeps both
    anchors = np.array([[[0.1, 0.1, 0.5, 0.5],
                         [0.12, 0.12, 0.52, 0.52]]], np.float32)
    loc_pred = np.zeros((1, 8), np.float32)
    cls_prob = np.array([[[0.1, 0.2],
                          [0.8, 0.1],
                          [0.1, 0.7]]], np.float32)
    out = nd.contrib.MultiBoxDetection(
        mx.nd.array(cls_prob), mx.nd.array(loc_pred), mx.nd.array(anchors),
        nms_threshold=0.5, threshold=0.05)
    o = out.asnumpy()[0]
    kept = o[o[:, 0] >= 0]
    assert len(kept) == 2
    assert set(kept[:, 0]) == {0.0, 1.0}


# ---------------------------------------------------------------------------
# Proposal


def test_proposal_shapes_and_clip():
    rng = np.random.RandomState(0)
    B, A, H, W = 1, 3, 4, 5
    cls_prob = rng.uniform(0, 1, (B, 2 * A, H, W)).astype(np.float32)
    bbox_pred = (rng.randn(B, 4 * A, H, W) * 0.1).astype(np.float32)
    im_info = np.array([[64.0, 80.0, 1.0]], np.float32)
    rois = nd.contrib.Proposal(
        mx.nd.array(cls_prob), mx.nd.array(bbox_pred), mx.nd.array(im_info),
        rpn_pre_nms_top_n=50, rpn_post_nms_top_n=8, feature_stride=16,
        scales=(8,), ratios=(0.5, 1, 2), rpn_min_size=4)
    r = rois.asnumpy()
    assert r.shape == (8, 5)
    assert (r[:, 0] == 0).all()
    assert (r[:, 1] >= 0).all() and (r[:, 3] <= 79).all()
    assert (r[:, 2] >= 0).all() and (r[:, 4] <= 63).all()
    # well-formed boxes
    assert (r[:, 3] >= r[:, 1]).all() and (r[:, 4] >= r[:, 2]).all()


def test_proposal_output_score_and_order():
    rng = np.random.RandomState(1)
    B, A, H, W = 1, 1, 3, 3
    cls_prob = rng.uniform(0, 1, (B, 2 * A, H, W)).astype(np.float32)
    bbox_pred = np.zeros((B, 4 * A, H, W), np.float32)
    im_info = np.array([[48.0, 48.0, 1.0]], np.float32)
    rois, scores = nd.contrib.Proposal(
        mx.nd.array(cls_prob), mx.nd.array(bbox_pred), mx.nd.array(im_info),
        rpn_pre_nms_top_n=9, rpn_post_nms_top_n=4, feature_stride=16,
        scales=(4,), ratios=(1,), rpn_min_size=2, output_score=True,
        threshold=0.99)
    s = scores.asnumpy().ravel()
    # scores non-increasing (sorted by objectness)
    assert (np.diff(s) <= 1e-6).all()
    assert rois.shape == (4, 5) and scores.shape == (4, 1)
