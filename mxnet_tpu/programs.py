"""Unified compiled-program registry + persistent compile cache.

The stack compiles XLA programs at six independent sites — executor
forward jits, the fused train step, the serve bucket ladder, decode
prefill/slot programs, gluon CachedOp modes, and quantize calibration
executors — and before this module each kept its own dict cache, so a
freshly spawned serve replica or resumed trainer recompiled its entire
ladder from scratch. This module is the one cache they all stand
behind:

1. **Registry** — :func:`get_or_build` keyed by a stable
   :class:`ProgramKey` fingerprint (graph/symbol hash, input
   shapes+dtypes, sharding/mesh, donation layout, numerics mode, and a
   jax+library **version salt**). Within a process, two sites that
   build the same program share ONE jitted callable — a hot-swap
   replacement engine re-warms its whole bucket ladder as in-memory
   cache hits. The registry is bounded (``MXNET_PROGRAMS_MAX``, LRU)
   with eviction telemetry, and every entry records its build wall,
   compile/disk-hit counts observed inside the build callable (sites
   that return lazily-jitted callables compile at first invocation
   instead — the prewarm report and the global compile/disk-hit split
   are the cold-start measurement), and (when a site attaches one) the
   program's XLA cost-analysis record from ``health.capture_cost``.

2. **Persistent compile cache** — when ``MXNET_COMPILE_CACHE_DIR`` is
   set, JAX's persistent compilation cache is wired underneath
   (``jax_compilation_cache_dir``), so a compile in a FRESH process
   deserializes the executable from disk instead of running XLA.
   Telemetry distinguishes the two honestly: a disk load still counts
   as a compile *request* (``jit/backend_compile_total`` — every
   zero-recompile assertion keeps meaning "zero traces"), while
   ``programs/compile_total`` vs ``programs/disk_hits_total`` split
   real backend compiles from cache loads.

3. **Warm-set manifest** — each registered program appends its
   fingerprint + abstract input spec to ``<dir>/warmset.json``
   (written through :func:`checkpoint.atomic_writer`, so the file is
   never torn). :func:`prewarm` replays those specs at startup through
   per-kind replay callables, so a new replica compiles its whole
   ladder from disk before ``/healthz`` goes ready —
   ``InferenceEngine.warmup()`` and ``DecodeEngine`` warmup route
   through it. Entries whose version salt mismatches are skipped with
   a warning (never replayed as wrong traces); a corrupt or torn
   manifest degrades to a cold compile, never a crash.

4. **Donated-loop warmup rule** — :func:`warm_twice` centralizes the
   pjit sharding-provenance discipline (one executable per input
   provenance; warm on the executing thread; assert from step 2) that
   DecodeEngine's two-pass warmup discovered, so the next subsystem
   doesn't rediscover the bug.

Knobs: ``MXNET_COMPILE_CACHE_DIR``, ``MXNET_PROGRAMS_MAX`` (config.py).
Docs: docs/compile_cache.md. Bench: ``benchmark.py --job cold_start``.
"""
from __future__ import annotations

import contextlib
import hashlib
import json
import logging
import os
import threading
from collections import OrderedDict

from .base import MXNetError

__all__ = ["ProgramKey", "fingerprint", "graph_hash", "version_salt",
           "get_or_build", "attach_cost", "prewarm", "warm_twice",
           "next_instance", "ensure_persistent_cache", "cache_dir",
           "warmset_path", "load_warmset", "note_warm", "stats",
           "entries", "reset", "WARMSET_FORMAT"]

_log = logging.getLogger(__name__)

WARMSET_FORMAT = 1

_lock = threading.RLock()
_entries = OrderedDict()        # fingerprint -> _Entry (LRU order)
_build_locks = {}               # fingerprint -> Lock (never removed; tiny)
_warmset_lock = threading.Lock()
_warmset_seen = set()           # (path, fp) known recorded: skip the RMW
_active_cache_dir = [None]      # the dir jax is currently configured with
_instance_seq = [0]
_salt_cache = [None]


def _tm():
    from . import telemetry
    return telemetry


def _config(name, default=None):
    from .config import get
    return get(name, default)


# ---------------------------------------------------------------------------
# fingerprints
# ---------------------------------------------------------------------------

def version_salt():
    """Library/backend salt folded into every fingerprint: a warm-set
    manifest (or registry entry) written by a different jax/jaxlib/
    framework version or backend must never be replayed as if it named
    the same executable. Device count rides along — XLA_FLAGS device
    topology changes the compiled program."""
    if _salt_cache[0] is not None:
        return _salt_cache[0]
    from .libinfo import __version__
    parts = ["mxnet=%s" % __version__]
    try:
        import jax
        import jaxlib
        parts.append("jax=%s" % jax.__version__)
        parts.append("jaxlib=%s" % jaxlib.__version__)
        try:
            parts.append("backend=%s" % jax.default_backend())
            parts.append("devices=%d" % jax.device_count())
            # device count alone cannot distinguish 2 processes x 1
            # device from 1 process x 2 devices — same SPMD partition,
            # different runtime (cross-host collectives) — so the
            # process count is salted explicitly: a dist_tpu_sync
            # worker must never replay a single-host manifest entry as
            # if it named the same executable
            parts.append("processes=%d" % jax.process_count())
        except Exception:
            parts.append("backend=uninitialized")
    except Exception:
        parts.append("jax=unavailable")
    _salt_cache[0] = ";".join(parts)
    return _salt_cache[0]


def invalidate_version_salt():
    """Drop the memoized salt.  The elastic rescale path calls this
    after a shutdown→reinit cycle: the salt embeds ``processes=N`` and
    the device topology, both of which just changed — programs built
    for the new world must re-fingerprint (and hit the persistent
    compile cache on disk, not replay a stale executable)."""
    _salt_cache[0] = None


def graph_hash(obj):
    """Stable graph fingerprint component. Accepts a Symbol (hashes its
    json), a string (hashed as-is), or any JSON-able structure."""
    if hasattr(obj, "tojson"):
        payload = obj.tojson()
    elif isinstance(obj, str):
        payload = obj
    else:
        payload = json.dumps(obj, sort_keys=True, default=str)
    return hashlib.sha1(payload.encode()).hexdigest()[:16]


def _canonical(spec):
    return json.dumps(spec, sort_keys=True, default=str)


class ProgramKey(object):
    """Identity of one compiled program in the registry.

    ``kind``
        The jit site (``executor_forward``, ``fused_step``,
        ``serve_bucket``, ``decode_prefill``, ``decode_step``,
        ``cachedop``, ``calib_executor``, ...).
    ``graph``
        Graph/symbol hash (:func:`graph_hash`) — what is computed.
    ``spec``
        JSON-able dict of everything else that specializes the
        executable: input shapes+dtypes, sharding/mesh signature,
        donation layout, numerics mode, bucket sizes. This is also the
        abstract input spec the warm-set manifest stores for replay.
    ``instance``
        Optional per-object salt for sites whose built value captures
        live Python state (CachedOp blocks close over parameter
        identity; calibration executors hold written weights) and must
        therefore NOT be shared across instances. Instance-salted
        entries still land in the warm-set for accounting, but carry
        no cross-process identity.
    """

    __slots__ = ("kind", "graph", "spec", "instance", "_fp")

    def __init__(self, kind, graph, spec=None, instance=None):
        self.kind = str(kind)
        self.graph = str(graph)
        self.spec = spec if spec is not None else {}
        self.instance = None if instance is None else str(instance)
        self._fp = None

    @property
    def fingerprint(self):
        if self._fp is None:
            h = hashlib.sha256()
            for part in (self.kind, self.graph, _canonical(self.spec),
                         self.instance or "", version_salt()):
                h.update(part.encode())
                h.update(b"\x00")
            self._fp = h.hexdigest()[:32]
        return self._fp

    def __repr__(self):
        return "ProgramKey(%s, %s, %s)" % (self.kind, self.graph,
                                           self.fingerprint)


def fingerprint(kind, graph, spec=None, instance=None):
    """Fingerprint without constructing a key (manifest tooling)."""
    return ProgramKey(kind, graph, spec, instance).fingerprint


def next_instance(prefix):
    """Process-unique instance salt (``prefix:N``) for sites whose
    built values must not be shared across objects. Never key by
    ``id(obj)`` — CPython reuses addresses after GC."""
    with _lock:
        _instance_seq[0] += 1
        return "%s:%d" % (prefix, _instance_seq[0])


# ---------------------------------------------------------------------------
# persistent compile cache wiring
# ---------------------------------------------------------------------------

def cache_dir():
    """The configured persistent-cache directory, or None."""
    d = _config("MXNET_COMPILE_CACHE_DIR")
    return os.path.abspath(d) if d else None


def ensure_persistent_cache():
    """Point JAX's persistent compilation cache at
    ``MXNET_COMPILE_CACHE_DIR`` (idempotent; reconfigures on a dir
    change and detaches when the var is cleared). The min-compile-time
    and min-entry-size gates are zeroed so every program in a serve
    ladder is cached, not just the slow ones. Returns the active dir
    or None."""
    d = cache_dir()
    if d == _active_cache_dir[0]:
        return d
    try:
        import jax
    except Exception:
        return None
    try:
        if d is None:
            jax.config.update("jax_compilation_cache_dir", None)
        else:
            os.makedirs(d, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", d)
            for knob, val in (
                    ("jax_persistent_cache_min_compile_time_secs", 0.0),
                    ("jax_persistent_cache_min_entry_size_bytes", -1)):
                try:
                    jax.config.update(knob, val)
                except Exception:
                    pass
        try:
            # jax decides cache-or-not ONCE per task; a dir set after
            # the process's first compile must still take effect
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception:
            pass
    except Exception as e:
        _log.warning("persistent compile cache unavailable: %s", e)
        return None
    _active_cache_dir[0] = d
    if d is not None:
        tm = _tm()
        if tm._enabled:
            tm._ensure_compile_listener()
    return d


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

class _Entry(object):
    __slots__ = ("key", "value", "build_s", "compile_requests",
                 "disk_hits", "uses", "cost")

    def __init__(self, key, value, build_s, compile_requests, disk_hits):
        self.key = key
        self.value = value
        self.build_s = build_s
        self.compile_requests = compile_requests
        self.disk_hits = disk_hits
        self.uses = 1
        self.cost = None


def max_entries():
    """Registry LRU bound (``MXNET_PROGRAMS_MAX``; 0 = unbounded)."""
    try:
        return int(_config("MXNET_PROGRAMS_MAX"))
    except Exception:
        return 512


def get_or_build(key, build_fn, retain=True):
    """The one compiled-program cache API every jit site stands behind.

    Returns the registered value for ``key`` (a :class:`ProgramKey`),
    building it with ``build_fn()`` on first sight. Builds are
    serialized per fingerprint (two engines warming the same ladder
    concurrently build each program once), measured (wall, compile
    requests, persistent-cache disk hits — thread-local attribution,
    so concurrent unrelated builds don't cross-count; note the bracket
    covers ``build_fn`` only, so a site returning a lazily-jitted
    callable attributes its compile to the first invocation — the
    prewarm report — not the entry), recorded in the warm-set manifest
    when a cache dir is configured, and bounded by
    ``MXNET_PROGRAMS_MAX`` with LRU eviction telemetry.

    ``retain=False`` measures and counts the build but does NOT store
    the value: for site values that pin live state (a calibration
    executor holds the model's written weights on device) the caller's
    own cache stays the only owner, so the registry never extends
    their lifetime.
    """
    fp = key.fingerprint
    tm = _tm()
    with _lock:
        e = _entries.get(fp)
        if e is not None:
            _entries.move_to_end(fp)
            e.uses += 1
            if tm._enabled:
                tm.counter("programs/registry_hits_total",
                           "get_or_build calls served from the "
                           "compiled-program registry").inc()
            return e.value
        block = _build_locks.get(fp)
        if block is None:
            block = _build_locks[fp] = threading.Lock()
    try:
        with block:
            with _lock:
                e = _entries.get(fp)
                if e is not None:       # built while we waited
                    _entries.move_to_end(fp)
                    e.uses += 1
                    return e.value
            ensure_persistent_cache()
            if tm._enabled:
                tm._ensure_compile_listener()
            t0 = tm.monotonic()
            c0, d0 = tm.thread_compile_stats()
            value = build_fn()
            c1, d1 = tm.thread_compile_stats()
            e = _Entry(key, value, tm.monotonic() - t0, c1 - c0,
                       d1 - d0)
            evicted = 0
            if retain:
                with _lock:
                    _entries[fp] = e
                    cap = max_entries()
                    while cap > 0 and len(_entries) > cap:
                        _entries.popitem(last=False)
                        evicted += 1
            if tm._enabled:
                tm.counter("programs/registered_total",
                           "Programs built and registered in the "
                           "compiled-program registry", ("kind",)
                           ).labels(key.kind).inc()
                tm.histogram("programs/build_seconds",
                             "Wall time of one registry program build "
                             "(trace + lower + compile or disk load)"
                             ).observe(e.build_s)
                if evicted:
                    tm.counter("programs/evictions_total",
                               "Registry entries evicted past "
                               "MXNET_PROGRAMS_MAX (LRU)").inc(evicted)
            _append_warmset(key)
            return value
    finally:
        # the per-fingerprint build lock has done its job once the
        # entry exists (or the build failed): drop it so instance-
        # salted keys can't grow the lock table without bound
        with _lock:
            _build_locks.pop(fp, None)


def attach_cost(key, rec):
    """Alias a ``health.capture_cost`` record onto the registry entry
    for ``key`` (sites capture cost with live args the registry never
    sees; the alias makes ``entries()`` a one-stop program table)."""
    fp = key.fingerprint if isinstance(key, ProgramKey) else str(key)
    with _lock:
        e = _entries.get(fp)
        if e is not None:
            e.cost = rec
    return rec


def entries():
    """Snapshot of the registry: {fingerprint: row-dict}, LRU order
    (oldest first) — surfaced by ``mxnet_tpu.diagnostics()``."""
    out = OrderedDict()
    with _lock:
        rows = list(_entries.items())
    for fp, e in rows:
        row = {"kind": e.key.kind, "graph": e.key.graph,
               "build_s": round(e.build_s, 4),
               "compile_requests": e.compile_requests,
               "disk_hits": e.disk_hits, "uses": e.uses}
        if e.cost:
            row["gflops"] = round(e.cost.get("flops", 0.0) / 1e9, 3)
        out[fp] = row
    return out


def stats():
    """Registry totals for bench records / diagnostics."""
    with _lock:
        rows = list(_entries.values())
    return {"entries": len(rows),
            "build_s_total": round(sum(e.build_s for e in rows), 3),
            "compile_requests": sum(e.compile_requests for e in rows),
            "disk_hits": sum(e.disk_hits for e in rows),
            "cache_dir": _active_cache_dir[0]}


def reset():
    """Drop every registry entry (test isolation). Site-local memos
    keep already-built programs alive; the registry simply re-registers
    on next sight."""
    with _lock:
        _entries.clear()
    _warmset_seen.clear()


# ---------------------------------------------------------------------------
# warm-set manifest
# ---------------------------------------------------------------------------

def warmset_path(directory=None):
    d = directory or cache_dir()
    if d is None:
        return None
    return os.path.join(d, "warmset.json")


def load_warmset(path=None):
    """The manifest's entry dict ({fingerprint: entry}), tolerating a
    missing, torn, or corrupt file by degrading to empty — prewarm then
    falls back to a cold compile, never a crash."""
    path = path or warmset_path()
    if path is None or not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            man = json.load(f)
        ent = man.get("entries", {})
        if not isinstance(ent, dict):
            raise ValueError("entries is not a dict")
        bad = sum(1 for e in ent.values() if not isinstance(e, dict))
        if bad:
            # valid JSON, wrong shape (hand-edited / partially
            # corrupted): drop the damaged entries, keep the rest —
            # never let one bad entry crash a replica's warmup
            ent = {fp: e for fp, e in ent.items()
                   if isinstance(e, dict)}
            _log.warning("warm-set manifest %s has %d non-dict "
                         "entr%s; ignoring them", path, bad,
                         "y" if bad == 1 else "ies")
            tm = _tm()
            if tm._enabled:
                tm.counter("programs/warmset_corrupt_total",
                           "Warm-set manifests found torn/corrupt and "
                           "ignored (cold-compile fallback)").inc()
        return ent
    except (ValueError, OSError) as e:
        _log.warning("warm-set manifest %s is corrupt (%s); "
                     "falling back to cold compile", path, e)
        tm = _tm()
        if tm._enabled:
            tm.counter("programs/warmset_corrupt_total",
                       "Warm-set manifests found torn/corrupt and "
                       "ignored (cold-compile fallback)").inc()
        return {}


def _append_warmset(key):
    """Record one program's fingerprint + abstract input spec in
    ``<cache_dir>/warmset.json`` (atomic_writer: readers never see a
    torn file). No-op without a cache dir. Instance-salted keys are
    NOT recorded: their fingerprints have no cross-process identity,
    so prewarm could never replay them — they would only grow the
    manifest without bound in long-lived processes."""
    path = warmset_path()
    if path is None or key.instance is not None:
        return
    from .checkpoint import atomic_writer
    fp = key.fingerprint
    # a fingerprint this process already recorded (or found recorded)
    # skips the locked full-manifest read-modify-write: a hot-swap
    # replacement engine's re-warm would otherwise pay N manifest
    # parses per warmup for entries that are all already on disk
    if (path, fp) in _warmset_seen:
        return
    with _warmset_lock, _warmset_flock(path):
        # (re)load INSIDE both locks: _warmset_lock serializes threads,
        # the flock serializes replicas sharing one cache dir — without
        # it two concurrent warmups would each write back only their
        # own additions and the last rename would drop the other's
        ent = load_warmset(path)
        if fp in ent:
            _warmset_seen.add((path, fp))
            return
        ent[fp] = {"kind": key.kind, "graph": key.graph,
                   "spec": key.spec, "salt": version_salt()}
        try:
            with atomic_writer(path, "w") as f:
                json.dump({"format": WARMSET_FORMAT, "entries": ent},
                          f, indent=1, sort_keys=True)
                f.write("\n")
            _warmset_seen.add((path, fp))
        except OSError as e:
            _log.warning("could not write warm-set manifest %s: %s",
                         path, e)


@contextlib.contextmanager
def _warmset_flock(path):
    """Advisory cross-process lock for the manifest's
    read-modify-write (best effort: platforms without fcntl fall back
    to the in-process lock alone)."""
    try:
        import fcntl
    except ImportError:
        yield
        return
    lock_path = path + ".lock"
    try:
        fd = os.open(lock_path, os.O_CREAT | os.O_RDWR, 0o644)
    except OSError:
        yield
        return
    try:
        fcntl.flock(fd, fcntl.LOCK_EX)
        yield
    finally:
        os.close(fd)                     # close releases the flock


def note_warm(kind, graph, spec, instance=None):
    """Append a warm-set entry without registering a value — for sites
    whose per-instance objects can't be shared but whose traces should
    replay at the next replica's startup."""
    _append_warmset(ProgramKey(kind, graph, spec, instance))


# ---------------------------------------------------------------------------
# prewarm replay
# ---------------------------------------------------------------------------

def prewarm(sites, include=(), graph=None, manifest=None,
            use_manifest=True):
    """Replay compile traces so every program a replica will serve is
    built (from the persistent cache: loaded off disk) BEFORE traffic
    arrives — the sub-minute-cold-start path /healthz readiness gates
    on.

    ``sites``
        ``{kind: replay_fn}`` — each replay callable takes one spec
        dict and builds/executes that program ON THE CALLING THREAD
        (compile where you execute).
    ``include``
        ``[(kind, spec), ...]`` always replayed (an engine's configured
        ladder) whether or not the manifest mentions them.
    ``graph``
        When given, manifest entries for other graphs are ignored (a
        shared cache dir may hold several models' warm sets).
    ``manifest``
        Explicit warmset.json path (default: the active cache dir's).

    Manifest entries whose version salt mismatches are SKIPPED with a
    warning — replaying a stale trace against a different jax/backend
    would warm the wrong executables and mask real cold compiles. A
    corrupt manifest degrades to the ``include`` set. Replay failures
    of MANIFEST entries are contained per entry (warn + count), so one
    stale spec can't take down startup — but a failure replaying an
    ``include`` entry (the caller's own configured ladder) RAISES:
    reporting a replica warm with a broken ladder would let /healthz
    go ready and push the compile (or its OOM) into the serving path.
    A replay callable may return False to signal it rejected the spec
    (counted skipped, not replayed). Returns a report dict.
    """
    tm = _tm()
    ensure_persistent_cache()
    salt = version_salt()
    todo, seen = [], set()
    for kind, spec in include:
        fp = fingerprint(kind, graph or "", spec)
        if fp not in seen:
            seen.add(fp)
            todo.append((kind, spec, True))
    skipped_salt = skipped_site = skipped_graph = 0
    if use_manifest:
        for fp, ent in sorted(load_warmset(manifest).items()):
            kind = ent.get("kind")
            if ent.get("salt") != salt:
                skipped_salt += 1
                continue
            if graph is not None and ent.get("graph") != graph:
                skipped_graph += 1
                continue
            if kind not in sites:
                skipped_site += 1
                continue
            if fp in seen:
                continue
            seen.add(fp)
            todo.append((kind, ent.get("spec") or {}, False))
    if skipped_salt:
        _log.warning(
            "prewarm: skipped %d warm-set entr%s from a different "
            "library/backend version (stale salt; current: %s) — they "
            "will cold-compile on demand instead of replaying wrong "
            "traces", skipped_salt,
            "y" if skipped_salt == 1 else "ies", salt)
        if tm._enabled:
            tm.counter("programs/prewarm_skipped_total",
                       "Warm-set entries skipped at prewarm "
                       "(stale version salt or failed replay)"
                       ).inc(skipped_salt)
    t0 = tm.monotonic()
    c0, d0 = tm.thread_compile_stats()
    replayed = failed = rejected = 0
    for kind, spec, required in todo:
        fn = sites.get(kind)
        if fn is None:
            skipped_site += 1
            continue
        try:
            if fn(spec) is False:        # site rejected the spec
                rejected += 1
            else:
                replayed += 1
        except Exception as e:
            if required:
                # the caller's own configured ladder failed to warm:
                # never report this replica warm over a broken program
                raise
            failed += 1
            _log.warning("prewarm: replay of %s %s failed (%s); "
                         "continuing", kind, spec, e)
            if tm._enabled:
                tm.counter("programs/prewarm_skipped_total",
                           "Warm-set entries skipped at prewarm "
                           "(stale version salt or failed replay)"
                           ).inc()
    c1, d1 = tm.thread_compile_stats()
    report = {"replayed": replayed, "failed": failed,
              "rejected": rejected,
              "skipped_salt": skipped_salt,
              "skipped_graph": skipped_graph,
              "skipped_site": skipped_site,
              "compiles": c1 - c0, "disk_hits": d1 - d0,
              "wall_s": round(tm.monotonic() - t0, 4)}
    if tm._enabled and replayed:
        tm.counter("programs/prewarm_replayed_total",
                   "Warm-set entries replayed at prewarm "
                   "(manifest + configured ladder)").inc(replayed)
    return report


# ---------------------------------------------------------------------------
# donated-loop warmup rule
# ---------------------------------------------------------------------------

def warm_twice(fn, args, rebuild=None, passes=2):
    """Warm a donated compiled loop the way pjit requires, centralized
    so no subsystem rediscovers the rule: pjit keeps ONE executable per
    input-sharding *provenance* (a fresh ``device_put``/``jnp.zeros``
    array keys a different executable than a pjit output does), and
    steady-state traffic only ever presents pjit-output provenance. So:
    warm ON the thread that will execute (the jit cache is per
    thread-local context), run TWO passes — the second against the
    first pass's outputs — and start zero-recompile assertions from
    step 2.

    ``fn(*args)`` is called ``passes`` times. ``rebuild(out, args) ->
    args`` maps one pass's outputs into the next pass's arguments;
    donated buffers MUST come back from the output (a rebuilt fresh
    buffer would re-present the cold provenance and defeat the second
    pass). Returns the final pass's outputs.
    """
    if passes < 1:
        raise MXNetError("warm_twice needs passes >= 1")
    out = fn(*args)
    for _ in range(passes - 1):
        if rebuild is not None:
            args = rebuild(out, args)
        out = fn(*args)
    return out
