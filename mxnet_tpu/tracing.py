"""End-to-end request/step tracing: propagated span contexts.

Telemetry (telemetry.py) aggregates — it can say p99 latency doubled,
but not where THIS slow request spent its time. The profiler
(profiler.py) is a manually-armed single-process window. This module is
the third surface: an always-on, overhead-bounded span tracer in the
Dapper/OpenTelemetry mold, carrying one ``SpanContext`` (trace_id,
span_id, parent_id) across threads, queues, and the kvstore RPC hop, so
a single ``POST /predict`` or one training step yields a linked
timeline: http → queue-wait → batch → compute → slice, or
data-wait → forward-backward → optimizer → checkpoint-save.

Design points (the cost model mirrors fault.py / telemetry.py):

* **disabled path** (``MXNET_TRACING=0``): every call site checks one
  module bool — no contextvar touch, no allocation.
* **head sampling** (``MXNET_TRACE_SAMPLE``, default 1.0): the decision
  is made ONCE where a trace is born (an HTTP request, a train step);
  an unsampled root is a no-op scope and every descendant call site
  sees no active context (one contextvar read, nothing recorded).
* **implicit propagation**: :func:`start_span` inherits the
  thread-local current context (contextvars). Where work crosses a
  queue or a thread pool the producer passes ``ctx=`` explicitly
  (serve requests carry it as ``_Request.tctx``; kvstore RPCs carry it
  in the wire payload via :func:`wire_context`/:func:`from_wire`).
* **bounded memory**: finished traces land in a ring
  (``MXNET_TRACE_RING`` traces); each trace holds at most
  ``_MAX_SPANS`` spans (overflow counted, never unbounded). Slow
  traces (root over ``MXNET_TRACE_SLOW_MS``) and traces that ended in
  an error / timeout / injected fault are retained in a separate
  always-kept ring so the interesting exemplars survive traffic.
* **two exporters**: :func:`chrome_events` merges spans into the
  profiler's chrome-trace dump (one timeline with the bridged gauges),
  and :func:`traces_payload` backs the ``/traces`` HTTP endpoint on
  both the telemetry server and the serving frontend.

Span timestamps are absolute ``time.perf_counter()`` readings; the
chrome exporter rebases them onto the profiler's epoch so spans and
profiler events line up on one timeline.
"""
from __future__ import annotations

import contextvars
import os
import random as _pyrandom
import threading
import time
from collections import deque

__all__ = ["SpanContext", "Span", "start_span", "child_span",
           "record_span", "use_context", "current", "active",
           "wire_context", "from_wire", "graft", "mark_error",
           "enabled", "enable", "set_sample", "set_slow_ms",
           "set_trace_ops",
           "finished_traces", "slow_traces", "get_trace", "traces_payload",
           "traces_endpoint", "chrome_events", "reset"]

_monotonic = time.perf_counter
_PID = os.getpid()
# private RNG: ids and sampling decisions must not consume draws from
# the module-level random stream — a user's random.seed(...) run would
# otherwise diverge based on how many spans/retries happened to occur
_rng = _pyrandom.Random(os.urandom(16))
# identifies THIS process's perf_counter epoch on the wire (pid alone
# collides across hosts/containers — every container's server is pid 1)
_PROC_TOKEN = "%x-%s" % (_PID, os.urandom(4).hex())

# hard cap on spans per trace: a pathological loop (thousands of eager
# ops under one step span) degrades to a truncation count, never to
# unbounded memory
_MAX_SPANS = 512

# slow/error exemplar ring: small and separate, so ordinary traffic
# cannot evict the interesting traces
_SLOW_RING = 32


def _config(name, fallback):
    try:
        from .config import get
        v = get(name)
        return fallback if v is None else v
    except Exception:
        return fallback


_enabled = bool(_config("MXNET_TRACING", True))
_sample = float(_config("MXNET_TRACE_SAMPLE", 1.0))
_slow_ms = float(_config("MXNET_TRACE_SLOW_MS", 1000))
# per-op op.dispatch spans are opt-in: on a microsecond-scale eager op
# the span write costs more than the dispatch, so the default keeps
# sampled traces structural (queue/batch/compute/step phases) only
_trace_ops = bool(_config("MXNET_TRACE_OPS", False))

_current = contextvars.ContextVar("mxnet_trace_ctx", default=None)

_ring_lock = threading.Lock()
_ring = deque(maxlen=max(1, int(_config("MXNET_TRACE_RING", 64))))
_slow = deque(maxlen=_SLOW_RING)


def new_trace_id():
    return "%032x" % _rng.getrandbits(128)


def new_span_id():
    return "%016x" % _rng.getrandbits(64)


# ---------------------------------------------------------------------------
# trace buffer (one per sampled trace; shared by every span context of
# that trace, including contexts deserialized from the kvstore wire)
# ---------------------------------------------------------------------------

class _TraceBuf(object):
    """Collector for one trace's finished spans. ``add`` deduplicates on
    span_id — a kvstore response replayed from the server's seq-cache
    may carry span records the client already grafted; at-most-once
    applies to spans exactly like it applies to server state."""

    __slots__ = ("spans", "_seen", "error", "dropped", "_lock", "_trace")

    def __init__(self):
        self.spans = []
        self._seen = set()
        self.error = None
        self.dropped = 0
        self._lock = threading.Lock()
        self._trace = None

    def add(self, span, force=False):
        """``force`` bypasses the span cap (never the dedup): the ROOT
        span finishes last, after its children filled the buffer, and a
        capped trace without its root envelope would be 512 orphans."""
        with self._lock:
            sid = span["span_id"]
            if sid in self._seen:
                return False
            if not force and len(self.spans) >= _MAX_SPANS:
                self.dropped += 1
                return False
            self._seen.add(sid)
            self.spans.append(span)
            t = self._trace
            if t is not None:
                # the root finalized before this span landed — e.g. the
                # request timed out (504) while its batch was still
                # mid-compute and the worker records serve.* afterwards.
                # Keep attaching: the retained timeout exemplar is
                # exactly the trace that needs its phase breakdown.
                # copy-on-write — /traces may be json-serializing the
                # current spans/phases objects right now
                phases = dict(t["phases"])
                phases[span["name"]] = round(
                    phases.get(span["name"], 0.0)
                    + (span["t1"] - span["t0"]) * 1e3, 3)
                t["spans"] = t["spans"] + [span]
                t["phases"] = phases
        return True

    def extend(self, spans):
        for s in spans:
            self.add(s)


class SpanContext(object):
    """Propagation handle: identifies a position in a trace. Cheap to
    copy across threads/queues; serializable for the RPC hop."""

    __slots__ = ("trace_id", "span_id", "sampled", "buf")

    def __init__(self, trace_id, span_id, sampled, buf):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled
        self.buf = buf

    def child_of(self, span_id):
        return SpanContext(self.trace_id, span_id, self.sampled, self.buf)


def current():
    """The active :class:`SpanContext` (sampled or not), or None."""
    if not _enabled:
        return None
    return _current.get()


def active():
    """The active SAMPLED context, or None — the call-site fast path:
    one module bool and one contextvar read when nothing is recording."""
    if not _enabled:
        return None
    ctx = _current.get()
    if ctx is None or not ctx.sampled:
        return None
    return ctx


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

class Span(object):
    """A live (open) span; finished into a plain dict on scope exit."""

    __slots__ = ("name", "ctx", "parent_id", "t0", "t1", "attrs",
                 "status", "_root", "_token", "_tid")

    def __init__(self, name, ctx, parent_id, root):
        self.name = name
        self.ctx = ctx                   # context of THIS span
        self.parent_id = parent_id
        self.t0 = _monotonic()
        self.t1 = None
        self.attrs = {}
        self.status = "ok"
        self._root = root
        self._token = None
        self._tid = threading.get_ident() % 100000

    def set_attr(self, key, value):
        self.attrs[key] = value
        return self

    @property
    def trace_id(self):
        return self.ctx.trace_id

    @property
    def span_id(self):
        return self.ctx.span_id

    def _finish(self, exc=None):
        self.t1 = _monotonic()
        if exc is not None:
            self.status = "error"
            self.attrs.setdefault("error", "%s: %s"
                                  % (type(exc).__name__, exc))
            if self._root:
                # only a failure that reaches the ROOT taints the trace
                # (plus explicit mark_error calls: HTTP error replies,
                # deadline expiry, fault.inject). A child that failed
                # transiently and was retried to success — routine
                # kvstore transport noise — must not claim a slot in
                # the bounded error-exemplar ring.
                self.ctx.buf.error = self.attrs["error"]
        self.ctx.buf.add(_span_dict(self.name, self.ctx.trace_id,
                                    self.ctx.span_id, self.parent_id,
                                    self.t0, self.t1, self.attrs,
                                    self.status, self._tid),
                         force=self._root)
        if self._root:
            _finalize(self)


class _SpanScope(object):
    """Context manager around one Span: sets/restores the implicit
    context on its own thread, records the span on exit."""

    __slots__ = ("span",)

    def __init__(self, span):
        self.span = span

    def __enter__(self):
        self.span._token = _current.set(self.span.ctx)
        return self.span

    def __exit__(self, exc_type, exc, tb):
        _current.reset(self.span._token)
        self.span._finish(exc)
        return False


class _NoopSpan(object):
    """Shared no-op for the disabled / unsampled paths."""

    __slots__ = ()
    ctx = None
    trace_id = None
    span_id = None
    attrs = {}

    def set_attr(self, key, value):
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()
# public handle for call sites that branch on active() themselves to
# avoid building an attrs dict on the untraced path
NOOP = _NOOP


def _span_dict(name, trace_id, span_id, parent_id, t0, t1, attrs, status,
               tid):
    return {"name": name, "trace_id": trace_id, "span_id": span_id,
            "parent_id": parent_id, "t0": t0, "t1": t1,
            "attrs": attrs or {}, "status": status, "tid": tid}


def start_span(name, ctx=None, attrs=None, trace_id=None):
    """Open a span as a context manager.

    * With an explicit ``ctx`` (or an implicit current context), the
      span is a child in that trace — unless the context is unsampled,
      in which case this is a no-op.
    * With no context at all, this is a ROOT: the head-sampling
      decision is made here (``MXNET_TRACE_SAMPLE``). ``trace_id``
      pins the new trace's id (an accepted ``X-Request-Id``).

    Always safe to call; returns a shared no-op scope when tracing is
    disabled or the trace is unsampled.
    """
    if not _enabled:
        return _NOOP
    parent = ctx if ctx is not None else _current.get()
    if parent is None:
        if _sample <= 0.0 or (_sample < 1.0
                              and _rng.random() >= _sample):
            return _NOOP
        buf = _TraceBuf()
        span_ctx = SpanContext(trace_id or new_trace_id(), new_span_id(),
                               True, buf)
        span = Span(name, span_ctx, None, root=True)
    else:
        if not parent.sampled:
            return _NOOP
        span = Span(name, parent.child_of(new_span_id()), parent.span_id,
                    root=False)
    if attrs:
        span.attrs.update(attrs)
    return _SpanScope(span)


def child_span(name, ctx=None, attrs=None):
    """Open a span ONLY when a sampled context is already active (or is
    passed in) — never a root. This is the hook hot layers use
    (executor, kvstore, io, checkpoint): outside a traced request/step
    it costs one module bool + one contextvar read and records
    nothing."""
    if not _enabled:
        return _NOOP
    parent = ctx if ctx is not None else active()
    if parent is None:
        return _NOOP
    return start_span(name, ctx=parent, attrs=attrs)


def record_span(name, ctx, t0, t1, attrs=None, span_id=None,
                parent_id=None, status="ok"):
    """Record an already-measured interval as a span (used where the
    interval is observed after the fact — e.g. the queue-wait of a
    serve request, reconstructed at dequeue time). Returns the span id
    (reusable to parent further spans), or None when not recording."""
    if not _enabled or ctx is None or not ctx.sampled:
        return None
    sid = span_id or new_span_id()
    ctx.buf.add(_span_dict(name, ctx.trace_id, sid,
                           parent_id if parent_id is not None
                           else ctx.span_id,
                           t0, t1, attrs, status,
                           threading.get_ident() % 100000))
    return sid


class _UseCtx(object):
    """Install an explicit context as the thread's implicit one (used
    where work dequeued from another thread should adopt the request's
    context — e.g. a serve worker running the batch of a traced
    request, so nested executor spans land in that trace)."""

    __slots__ = ("_ctx", "_token")

    def __init__(self, ctx):
        self._ctx = ctx
        self._token = None

    def __enter__(self):
        if self._ctx is not None and _enabled:
            self._token = _current.set(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        if self._token is not None:
            _current.reset(self._token)
        return False


def use_context(ctx):
    """Context manager: make ``ctx`` the implicit current context on
    this thread (no-op when ``ctx`` is None or tracing is disabled)."""
    return _UseCtx(ctx)


def mark_error(reason, ctx=None):
    """Flag the (given or current) trace as errored so it is retained
    in the slow/error ring regardless of duration. Called by
    fault.inject when an armed fault fires under a sampled trace."""
    ctx = ctx if ctx is not None else active()
    if ctx is not None and ctx.sampled:
        ctx.buf.error = str(reason)


# ---------------------------------------------------------------------------
# wire propagation (kvstore RPC hop)
# ---------------------------------------------------------------------------

def wire_context(ctx=None):
    """Serializable dict for the active (or given) sampled context;
    None when nothing is recording — the RPC payload then carries no
    tracing field at all."""
    ctx = ctx if ctx is not None else active()
    if ctx is None or not ctx.sampled:
        return None
    return {"trace_id": ctx.trace_id, "span_id": ctx.span_id,
            "sampled": True}


class _SinkBuf(_TraceBuf):
    """A trace buffer that tees every accepted span into an external
    list — the server's per-RPC collector, shipped back to the client
    inside the response."""

    __slots__ = ("_sink",)

    def __init__(self, sink):
        _TraceBuf.__init__(self)
        self._sink = sink

    def add(self, span, force=False):
        if _TraceBuf.add(self, span, force=force):
            self._sink.append(span)
            return True
        return False


def from_wire(wire, sink=None):
    """Rebuild a :class:`SpanContext` from :func:`wire_context` output.
    ``sink``: a list collecting the finished span dicts (the server
    appends them to its RPC response so they surface in the client's
    trace); without one, spans land in a throwaway buffer."""
    if not wire or not wire.get("sampled"):
        return None
    buf = _TraceBuf() if sink is None else _SinkBuf(sink)
    return SpanContext(wire["trace_id"], wire["span_id"], True, buf)


def graft(spans, ctx=None, clock=None):
    """Attach remotely-recorded span dicts (an RPC response's tracing
    field) into the current trace. Deduplicated on span_id, so a
    response replayed by the server's at-most-once cache cannot
    double-count spans.

    ``clock``: ``(proc_token, server_now, client_now)`` — the sender's
    :data:`_PROC_TOKEN` plus its ``perf_counter`` reading taken as the
    response was sent, paired with the client's reading at receipt.
    Spans from a server in ANOTHER process carry that process's
    ``perf_counter`` epoch; the clock pair gives the epoch offset
    exactly (to within one response delivery delay), so the bundle is
    rebased onto the client clock with durations and relative placement
    preserved. An in-process server's token matches ours and the bundle
    is left untouched — spans recorded long before this RPC (an
    at-most-once seq-cache replay re-ships the original execution's
    spans) keep their true times."""
    ctx = ctx if ctx is not None else active()
    if ctx is None or not ctx.sampled or not spans:
        return
    if clock is not None and clock[0] != _PROC_TOKEN:
        shift = clock[2] - clock[1]
        spans = [dict(s, t0=s["t0"] + shift, t1=s["t1"] + shift)
                 for s in spans]
    ctx.buf.extend(spans)


# ---------------------------------------------------------------------------
# finished-trace rings
# ---------------------------------------------------------------------------

def _finalize(root_span):
    buf = root_span.ctx.buf
    dur_ms = (root_span.t1 - root_span.t0) * 1e3
    with buf._lock:
        spans = sorted(buf.spans, key=lambda s: s["t0"])
        phases = {}
        for s in spans:
            if s["span_id"] == root_span.ctx.span_id:
                continue
            phases[s["name"]] = phases.get(s["name"], 0.0) \
                + (s["t1"] - s["t0"]) * 1e3
        trace = {"trace_id": root_span.ctx.trace_id,
                 "root": root_span.name,
                 "duration_ms": round(dur_ms, 3),
                 "error": buf.error,
                 "spans": spans,
                 "dropped_spans": buf.dropped,
                 "phases": {k: round(v, 3) for k, v in phases.items()},
                 "wall_ts": time.time()}
        # spans recorded from now on (a worker finishing a batch whose
        # requester already timed out) land in the retained record too
        buf._trace = trace
    slow = dur_ms >= _slow_ms or buf.error is not None
    trace["slow"] = bool(slow)
    with _ring_lock:
        _ring.append(trace)
        if slow:
            _slow.append(trace)


def finished_traces(limit=None):
    """Most-recent-first list of finished sampled traces."""
    with _ring_lock:
        out = list(_ring)
    out.reverse()
    return out[:limit] if limit else out


def slow_traces(limit=None):
    """Most-recent-first list of retained slow/error exemplar traces."""
    with _ring_lock:
        out = list(_slow)
    out.reverse()
    return out[:limit] if limit else out


def get_trace(trace_id):
    """Newest trace with this id (client-supplied X-Request-Ids can
    collide; the most recent one is the one being debugged)."""
    with _ring_lock:
        candidates = list(_ring) + list(_slow)
    best = None
    for t in candidates:
        if t["trace_id"] == trace_id and \
                (best is None or t["wall_ts"] >= best["wall_ts"]):
            best = t
    return best


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

def _chrome_events_for(trace, prof_t0):
    events = []
    for s in trace["spans"]:
        args = {"trace_id": s["trace_id"], "span_id": s["span_id"]}
        if s["parent_id"]:
            args["parent_id"] = s["parent_id"]
        args.update(s["attrs"])
        events.append({
            # op.dispatch spans only: surfacing the op name keeps the
            # timeline readable; kv.* spans also carry an "op" attr but
            # must keep their span identity in the merged trace
            "name": (s["attrs"].get("op", s["name"])
                     if s["name"] == "op.dispatch" else s["name"]),
            "cat": "trace",
            "ph": "X",
            "ts": max(0.0, (s["t0"] - prof_t0) * 1e6),
            "dur": max(0.0, (s["t1"] - s["t0"]) * 1e6),
            "pid": _PID,
            "tid": s["tid"],
            "args": args})
    return events


def chrome_events():
    """Every retained trace (ring + slow exemplars, deduplicated) as
    chrome-trace complete events on the profiler's time base — merged
    into ``profiler.dump()`` so spans, per-op profiler events, and the
    bridged gauges share one timeline."""
    from . import profiler as _prof
    events, seen = [], set()
    with _ring_lock:
        traces = list(_ring) + list(_slow)
    for t in traces:
        # dedup by object identity: a slow trace also lives in the main
        # ring, but two DISTINCT traces may share a (client-supplied)
        # trace id and must both export
        if id(t) in seen:
            continue
        seen.add(id(t))
        events.extend(_chrome_events_for(t, _prof._t0))
    events.sort(key=lambda e: e["ts"])
    return events


def _trace_summary(t):
    # the root span's attrs ride the summary (e.g. train.step's
    # epoch/nbatch): the cluster observatory joins per-rank step
    # timelines on them without fetching every trace by id, and
    # wall_ts is the cross-process clock anchor that lets it stitch
    # N ranks' perf_counter timelines onto one axis
    root_attrs = {}
    for s in t["spans"]:
        if s.get("parent_id") is None and s["name"] == t["root"]:
            root_attrs = s.get("attrs") or {}
            break
    return {"trace_id": t["trace_id"], "root": t["root"],
            "duration_ms": t["duration_ms"], "error": t["error"],
            "slow": t["slow"], "spans": len(t["spans"]),
            "phases": t["phases"], "root_attrs": root_attrs,
            "wall_ts": round(t["wall_ts"], 6), "age_s": round(
                time.time() - t["wall_ts"], 1)}


def traces_payload(trace_id=None, limit=20):
    """JSON-ready payload for the ``/traces`` endpoint: recent + slow
    trace summaries (full span list per trace on ``?id=``) and the
    latency-histogram exemplars linking /metrics worst-cases to
    concrete trace ids."""
    if trace_id:
        t = get_trace(trace_id)
        if t is None:
            return None
        out = dict(t)
        out.pop("wall_ts", None)
        return out
    from . import telemetry as _tm
    return {"recent": [_trace_summary(t) for t in finished_traces(limit)],
            "slow": [_trace_summary(t) for t in slow_traces(limit)],
            "exemplars": _tm.exemplars(),
            "sample_rate": _sample,
            "slow_ms": _slow_ms,
            "enabled": _enabled}


def traces_endpoint(query=""):
    """(status_code, payload_dict) for a ``GET /traces[?id=…]``
    request — the ONE implementation behind both mounts
    (telemetry.serve and serve.serve_http), so their behavior cannot
    drift."""
    from urllib.parse import parse_qs
    tid = (parse_qs(query).get("id") or [None])[0]
    payload = traces_payload(tid)
    if payload is None:
        return 404, {"error": "unknown trace id %r" % tid}
    return 200, payload


# ---------------------------------------------------------------------------
# switches (runtime + test control)
# ---------------------------------------------------------------------------

def enabled():
    return _enabled


def enable(on=True):
    """Flip the tracer at runtime (also: ``MXNET_TRACING=0``). Returns
    the previous state. Rings are preserved."""
    global _enabled
    prev = _enabled
    _enabled = bool(on)
    return prev


def set_sample(rate):
    """Set the head-sampling probability (also: MXNET_TRACE_SAMPLE).
    Returns the previous rate."""
    global _sample
    prev = _sample
    _sample = max(0.0, min(1.0, float(rate)))
    return prev


def set_slow_ms(ms):
    """Set the slow-exemplar threshold (also: MXNET_TRACE_SLOW_MS).
    Returns the previous threshold."""
    global _slow_ms
    prev = _slow_ms
    _slow_ms = float(ms)
    return prev


def set_trace_ops(on):
    """Toggle per-op op.dispatch span recording (also: MXNET_TRACE_OPS).
    Returns the previous setting."""
    global _trace_ops
    prev = _trace_ops
    _trace_ops = bool(on)
    return prev


def reset():
    """Clear both rings (test isolation). Live spans are unaffected."""
    with _ring_lock:
        _ring.clear()
        _slow.clear()
