// C++ NDArray/autograd wrapper over the general C ABI
// (include/mxnet_tpu/c_api.h). Capability analog of the reference's
// cpp-package/include/mxnet-cpp/ndarray.h: RAII handles, typed
// imperative op invocation (see the generated op.h), autograd record/
// backward — enough surface for a C++ client to train a model.
#ifndef MXNET_TPU_CPP_NDARRAY_HPP_
#define MXNET_TPU_CPP_NDARRAY_HPP_

#include <cstdint>
#include <map>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

#include "mxnet_tpu/c_api.h"

namespace mxnet_tpu_cpp {

inline void Check(int rc) {
  if (rc != 0) throw std::runtime_error(MXGetLastError());
}

class NDArray {
 public:
  NDArray() : handle_(nullptr) {}

  NDArray(const std::vector<uint32_t>& shape, int dtype = MXTPU_FLOAT32,
          const char* dev_type = "cpu", int dev_id = 0) {
    Check(MXNDArrayCreate(shape.data(),
                          static_cast<uint32_t>(shape.size()), dtype,
                          dev_type, dev_id, &handle_));
  }

  // adopt an ABI-owned handle (strong reference transferred)
  static NDArray FromHandle(NDArrayHandle h) {
    NDArray a;
    a.handle_ = h;
    return a;
  }

  // non-owning view of a BORROWED handle (e.g. inside a monitor or
  // updater callback): reads are fine, the handle is not freed
  static NDArray Borrow(NDArrayHandle h) {
    NDArray a;
    a.handle_ = h;
    a.owns_ = false;
    return a;
  }

  NDArray(const NDArray&) = delete;
  NDArray& operator=(const NDArray&) = delete;

  NDArray(NDArray&& o) noexcept : handle_(o.handle_), owns_(o.owns_) {
    o.handle_ = nullptr;
  }
  NDArray& operator=(NDArray&& o) noexcept {
    if (this != &o) {
      Free();
      handle_ = o.handle_;
      owns_ = o.owns_;
      o.handle_ = nullptr;
    }
    return *this;
  }

  ~NDArray() { Free(); }

  NDArrayHandle handle() const { return handle_; }

  std::vector<uint32_t> Shape() const {
    uint32_t ndim = 0;
    uint32_t buf[MXTPU_MAX_NDIM] = {0};
    Check(MXNDArrayGetShape(handle_, &ndim, buf));
    return std::vector<uint32_t>(buf, buf + ndim);
  }

  size_t Size() const {
    size_t n = 1;
    for (uint32_t d : Shape()) n *= d;
    return n;
  }

  void CopyFrom(const std::vector<float>& src) {
    Check(MXNDArraySyncCopyFromCPU(handle_, src.data(),
                                   src.size() * sizeof(float)));
  }

  std::vector<float> CopyTo() const {
    std::vector<float> out(Size());
    Check(MXNDArraySyncCopyToCPU(handle_, out.data(),
                                 out.size() * sizeof(float)));
    return out;
  }

  void WaitToRead() const { Check(MXNDArrayWaitToRead(handle_)); }

  void AttachGrad() {
    NDArrayHandle h = handle_;
    Check(MXAutogradMarkVariables(1, &h));
  }

  NDArray Grad() const {
    NDArrayHandle g = nullptr;
    Check(MXAutogradGetGrad(handle_, &g));
    return FromHandle(g);
  }

  void Backward() {
    NDArrayHandle h = handle_;
    Check(MXAutogradBackward(1, &h));
  }

 private:
  void Free() {
    if (handle_ != nullptr && owns_) MXNDArrayFree(handle_);
    handle_ = nullptr;
  }
  NDArrayHandle handle_;
  bool owns_ = true;
};

// Invoke one registered operator; returns its first output.
inline NDArray Invoke(
    const std::string& op, const std::vector<const NDArray*>& inputs,
    const std::map<std::string, std::string>& attrs = {}) {
  std::vector<NDArrayHandle> ins;
  ins.reserve(inputs.size());
  for (const NDArray* a : inputs) ins.push_back(a->handle());
  std::vector<const char*> keys, vals;
  for (const auto& kv : attrs) {
    keys.push_back(kv.first.c_str());
    vals.push_back(kv.second.c_str());
  }
  int n_out = 0;
  NDArrayHandle* outs = nullptr;
  Check(MXImperativeInvoke(op.c_str(), static_cast<int>(ins.size()),
                           ins.data(), &n_out, &outs,
                           static_cast<int>(keys.size()), keys.data(),
                           vals.data()));
  NDArray first = NDArray::FromHandle(outs[0]);
  for (int i = 1; i < n_out; ++i) MXNDArrayFree(outs[i]);
  return first;
}

// In-place op (optimizer updates): outputs alias inputs; drop them.
inline void InvokeInPlace(
    const std::string& op, const std::vector<const NDArray*>& inputs,
    const std::map<std::string, std::string>& attrs = {}) {
  NDArray out = Invoke(op, inputs, attrs);
  out.WaitToRead();
}

class AutogradRecord {
 public:
  AutogradRecord() { Check(MXAutogradSetIsRecording(1, &prev_)); }
  ~AutogradRecord() { MXAutogradSetIsRecording(prev_, nullptr); }

 private:
  int prev_;
};

}  // namespace mxnet_tpu_cpp

#endif  // MXNET_TPU_CPP_NDARRAY_HPP_
