"""Weight initializers.

Reference: python/mxnet/initializer.py (728 LoC): Initializer base with
name-pattern dispatch via InitDesc attributes, a string registry, and the
standard family (Zero/One/Constant/Uniform/Normal/Orthogonal/Xavier/
MSRAPrelu/Bilinear/LSTMBias/Load/Mixed).

TPU note: initializers fill existing NDArrays host-side or via the
framework's stateless samplers; they run once at setup so they are not a
perf surface — clarity over fusion here.
"""
from __future__ import annotations

import json
import logging
import re

import numpy as np

from .base import MXNetError
from .ndarray.ndarray import NDArray, array as nd_array
from . import ndarray as nd

__all__ = ["InitDesc", "Initializer", "Zero", "One", "Constant", "Uniform",
           "Normal", "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear",
           "LSTMBias", "Load", "Mixed", "register", "create"]

_INIT_REGISTRY = {}


class InitDesc(str):
    """Name + attrs describing the parameter to initialize
    (reference: initializer.py InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


def register(klass):
    """Register an initializer under its lowercased class name."""
    name = klass.__name__.lower()
    if name in _INIT_REGISTRY:
        logging.warning("New initializer %s overrides existing %s",
                        klass.__name__, name)
    _INIT_REGISTRY[name] = klass
    return klass


# reference alias names (python/mxnet/initializer.py registers "zeros",
# "ones"; Gluon layers pass them as default bias/gamma initializers)
_ALIASES = {"zeros": "zero", "ones": "one"}


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    key = name.lower()
    key = _ALIASES.get(key, key)
    if key not in _INIT_REGISTRY:
        raise MXNetError("unknown initializer %r" % name)
    return _INIT_REGISTRY[key](**kwargs)


class Initializer(object):
    """Base initializer (reference: python/mxnet/initializer.py:91)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._verbose = False
        self._print_func = None

    def set_verbosity(self, verbose=False, print_func=None):
        self._verbose = verbose
        if print_func is None:
            def asum_stat(x):
                return str((np.abs(x.asnumpy()).mean(),))
            print_func = asum_stat
        self._print_func = print_func
        return self

    def _verbose_print(self, desc, init, arr):
        if self._verbose and self._print_func:
            logging.info("Initialized %s as %s: %s", desc, init,
                         self._print_func(arr))

    def dumps(self):
        """JSON [name, kwargs] — used to ship the initializer to KVStore
        servers (reference: initializer.py dumps)."""
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        """Initialize ``arr`` according to the parameter described by
        ``desc``; dispatches on attrs then name patterns."""
        if not isinstance(desc, InitDesc):
            desc = InitDesc(str(desc))
        if desc.global_init is None:
            desc.global_init = self
        init = desc.attrs.get("__init__", "")
        if init:
            create(init)._init_weight(desc, arr)
            self._verbose_print(desc, init, arr)
            return
        if desc.endswith("weight"):
            self._init_weight(desc, arr)
            self._verbose_print(desc, "weight", arr)
        elif desc.endswith("bias"):
            self._init_bias(desc, arr)
            self._verbose_print(desc, "bias", arr)
        elif desc.endswith("gamma"):
            self._init_gamma(desc, arr)
            self._verbose_print(desc, "gamma", arr)
        elif desc.endswith("beta"):
            self._init_beta(desc, arr)
            self._verbose_print(desc, "beta", arr)
        elif desc.endswith("running_mean") or desc.endswith("moving_mean"):
            self._init_zero(desc, arr)
        elif desc.endswith("running_var") or desc.endswith("moving_var"):
            self._init_one(desc, arr)
        elif desc.endswith("moving_inv_var"):
            self._init_zero(desc, arr)
        elif desc.endswith("moving_avg"):
            self._init_zero(desc, arr)
        elif desc.endswith("min") or desc.endswith("max"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    def _init_bilinear(self, _, arr):
        weight = np.zeros(np.prod(arr.shape), dtype="float32")
        shape = arr.shape
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(np.prod(shape)):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr._set_data(nd_array(weight.reshape(shape), ctx=arr.context,
                               dtype=arr.dtype)._data)

    def _init_zero(self, _, arr):
        arr[:] = 0.0

    def _init_one(self, _, arr):
        arr[:] = 1.0

    def _init_bias(self, _, arr):
        arr[:] = 0.0

    def _init_gamma(self, _, arr):
        arr[:] = 1.0

    def _init_beta(self, _, arr):
        arr[:] = 0.0

    def _init_weight(self, name, arr):
        raise NotImplementedError("Must override it")

    def _init_default(self, name, _):
        raise ValueError(
            "Unknown initialization pattern for %s. Default initialization "
            "is now limited to \"weight\", \"bias\", \"gamma\" (1.0), and "
            "\"beta\" (0.0). Please use mx.sym.Variable(init=mx.init.*) to "
            "set the initialization pattern" % name)

    def __eq__(self, other):
        if not isinstance(other, Initializer):
            return False
        return self._kwargs == other._kwargs and \
            type(self) is type(other)

    __hash__ = object.__hash__


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 0.0


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 1.0


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        arr[:] = self.value


@register
class Uniform(Initializer):
    """U(-scale, scale) (reference: initializer.py Uniform)."""

    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        nd.random.uniform(-self.scale, self.scale, shape=arr.shape,
                          dtype="float32", out=arr)


@register
class Normal(Initializer):
    """N(0, sigma^2) (reference: initializer.py Normal)."""

    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        nd.random.normal(0, self.sigma, shape=arr.shape, dtype="float32",
                         out=arr)


@register
class Orthogonal(Initializer):
    """Orthogonal matrix init (reference: initializer.py Orthogonal)."""

    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = nd.random.uniform(-1.0, 1.0, shape=(nout, nin)).asnumpy()
        else:
            tmp = nd.random.normal(0.0, 1.0, shape=(nout, nin)).asnumpy()
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        q = self.scale * q.reshape(arr.shape)
        arr._set_data(nd_array(q, ctx=arr.context, dtype=arr.dtype)._data)


@register
class Xavier(Initializer):
    """Xavier/Glorot (reference: initializer.py Xavier)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise ValueError(
                "Xavier initializer cannot be applied to vector %s. It "
                "requires at least 2D." % name)
        if len(shape) > 2:
            hw_scale = np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = 1.0
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise ValueError("Incorrect factor type")
        scale = np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            nd.random.uniform(-scale, scale, shape=arr.shape, out=arr)
        elif self.rnd_type == "gaussian":
            nd.random.normal(0, scale, shape=arr.shape, out=arr)
        else:
            raise ValueError("Unknown random type")


@register
class MSRAPrelu(Xavier):
    """Kaiming/MSRA init with PReLU slope correction
    (reference: initializer.py MSRAPrelu)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        self._init_bilinear(name, arr)


@register
class LSTMBias(Initializer):
    """Forget-gate bias init for LSTM (reference: initializer.py LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        arr[:] = 0.0
        num_hidden = int(arr.shape[0] / 4)
        b = arr.asnumpy().copy()        # asnumpy views can be read-only
        b[num_hidden:2 * num_hidden] = self.forget_bias
        arr._set_data(nd_array(b, ctx=arr.context, dtype=arr.dtype)._data)


@register
class FusedRNN(Initializer):
    """Initializer twin of the reference's FusedRNN (initializer.py
    FusedRNN): the reference unpacks a cuDNN-fused parameter blob; this
    build's FusedRNNCell keeps per-gate named parameters, so weights
    delegate to the wrapped initializer and LSTM biases receive the
    ``forget_bias`` on the forget-gate quarter."""

    def __init__(self, init, num_hidden, num_layers, mode,
                 bidirectional=False, forget_bias=1.0):
        if init is None:
            raise MXNetError("FusedRNN requires a wrapped initializer")
        if isinstance(init, str):
            # reference-compatible: a dumps() JSON spec
            name, kwargs = json.loads(init)
            init = create(name, **kwargs)
        super().__init__(init=init.dumps(), num_hidden=num_hidden,
                         num_layers=num_layers, mode=mode,
                         bidirectional=bidirectional,
                         forget_bias=forget_bias)
        self._init = init
        self._mode = mode
        self._forget_bias = forget_bias

    def _init_weight(self, name, arr):
        self._init(InitDesc(name), arr)

    def _init_bias(self, name, arr):
        if self._mode == "lstm" and arr.ndim == 1 \
                and arr.shape[0] % 4 == 0:
            LSTMBias(self._forget_bias)._init_weight(name, arr)
            return
        super()._init_bias(name, arr)


@register
class Load(object):
    """Init from a dict of arrays, falling back to ``default_init``
    (reference: initializer.py Load)."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            from .ndarray.utils import load
            param = load(param)
        self.param = {}
        for name, arr in param.items():
            if name.startswith("arg:") or name.startswith("aux:"):
                self.param[name[4:]] = arr
            else:
                self.param[name] = arr
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            if arr.shape != self.param[name].shape:
                raise AssertionError(
                    "Parameter %s cannot be initialized from loading. Shape "
                    "mismatch, target %s vs loaded %s"
                    % (name, str(arr.shape), str(self.param[name].shape)))
            arr._set_data(self.param[name].as_in_context(arr.context)._data)
            if self.verbose:
                logging.info("Initialized %s by loading", name)
        else:
            if self.default_init is None:
                raise AssertionError(
                    "Cannot Initialize %s. Not found in loaded param and no "
                    "default Initializer is provided." % name)
            self.default_init(name, arr)
            if self.verbose:
                logging.info("Initialized %s by default", name)


@register
class Mixed(object):
    """Dispatch to initializers by regex on the parameter name
    (reference: initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        assert len(patterns) == len(initializers)
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise ValueError(
            "Parameter name %s did not match any pattern. Consider adding a "
            "\".*\" pattern at the and with default Initializer." % name)
