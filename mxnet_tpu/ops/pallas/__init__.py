"""Pallas TPU kernels for the hot ops.

TPU-native analog of the reference's hand-written CUDA kernels
(src/operator/contrib/transformer-inl.h, src/common/rtc.cc): where XLA's
automatic fusion is not enough (attention over long sequences), we drop
to Pallas for explicit VMEM tiling and online-softmax accumulation.

Kernels degrade gracefully off-TPU: on CPU test meshes they run in
pallas interpreter mode, so the same code path is exercised everywhere.
"""
from .flash_attention import flash_attention
from .int8_matmul import int8_matmul

__all__ = ["flash_attention", "int8_matmul"]
