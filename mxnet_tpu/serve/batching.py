"""Micro-batching primitives: batch buckets and axis-0 padding.

TPUs amortize their dispatch and pipeline costs over large batches, but
XLA programs are shape-specialized: every distinct batch size is a
separate compile. Serving traffic produces arbitrary per-request row
counts, so an unconstrained shape surface means a recompile storm (the
classic TPU serving latency cliff — see docs/observability.md). The fix,
shared with XLA-for-Julia's static-shape specialization and TVM-style
ahead-of-time bucketing, is a BOUNDED set of batch buckets: requests
coalesce into one batch, the batch pads up to the nearest bucket, and
the jit cache holds at most ``len(buckets)`` forward programs no matter
what the traffic does.

Padding is along axis 0 only (the batch dimension): padded rows are
zeros, every real row's computation is independent of them for
row-parallel inference graphs, and un-padding is a mask-free slice. The
bitwise identity real-rows-of-padded-forward == unpadded-forward is
asserted by tests/test_serve.py.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError

__all__ = ["power_of_two_buckets", "parse_buckets", "validate_buckets",
           "pick_bucket", "pad_axis0", "unpad_axis0"]


def power_of_two_buckets(max_batch):
    """Power-of-two bucket ladder up to ``max_batch`` (inclusive):
    ``8 -> (1, 2, 4, 8)``. A non-power-of-two max becomes the final
    bucket (``6 -> (1, 2, 4, 6)``)."""
    max_batch = int(max_batch)
    if max_batch < 1:
        raise MXNetError("max_batch must be >= 1, got %d" % max_batch)
    buckets, b = [], 1
    while b < max_batch:
        buckets.append(b)
        b *= 2
    buckets.append(max_batch)
    return tuple(buckets)


def validate_buckets(buckets, spec=None):
    """Validate an EXPLICIT bucket ladder — strictly increasing
    positive sizes — and return it as a tuple. Unsorted, duplicate, or
    non-positive entries raise an :class:`MXNetError` naming the
    offending spec: a ladder the operator wrote down is config, and
    silently reordering/deduplicating config hides the typo it almost
    certainly is (``"16,4,8"`` meant something else)."""
    name = repr(spec) if spec is not None else repr(list(buckets))
    buckets = tuple(int(b) for b in buckets)
    if not buckets:
        raise MXNetError("bucket spec %s is empty" % name)
    for b in buckets:
        if b < 1:
            raise MXNetError("bucket spec %s: sizes must be >= 1 "
                             "(got %d)" % (name, b))
    for prev, cur in zip(buckets, buckets[1:]):
        if cur == prev:
            raise MXNetError("bucket spec %s has duplicate bucket %d"
                             % (name, cur))
        if cur < prev:
            raise MXNetError("bucket spec %s is not sorted ascending "
                             "(%d after %d)" % (name, cur, prev))
    return buckets


def parse_buckets(spec, max_batch):
    """Bucket tuple from a config spec: an explicit comma list
    (``"1,4,16"``, MXNET_SERVE_BUCKETS) or, when empty, the
    power-of-two ladder up to ``max_batch``. Explicit specs must be
    strictly increasing positive sizes (:func:`validate_buckets`)."""
    if not spec:
        return power_of_two_buckets(max_batch)
    try:
        buckets = [int(tok) for tok in str(spec).split(",") if tok.strip()]
    except ValueError:
        raise MXNetError("bad bucket spec %r (want e.g. '1,2,4,8')"
                         % (spec,))
    return validate_buckets(buckets, spec)


def pick_bucket(n, buckets):
    """Smallest bucket holding ``n`` rows. ``n`` beyond the largest
    bucket is an explicit error naming the ladder — the caller's
    admission check should have rejected it."""
    for b in buckets:
        if b >= n:
            return b
    raise MXNetError("batch of %d rows exceeds the largest bucket of "
                     "%s — split the request or raise the ladder"
                     % (n, tuple(buckets)))


def pad_axis0(arr, target):
    """Zero-pad ``arr`` along axis 0 up to ``target`` rows."""
    arr = _np.asarray(arr)
    n = arr.shape[0]
    if n == target:
        return arr
    if n > target:
        raise MXNetError("cannot pad %d rows down to %d" % (n, target))
    pad = _np.zeros((target - n,) + arr.shape[1:], dtype=arr.dtype)
    return _np.concatenate([arr, pad], axis=0)


def unpad_axis0(arr, rows):
    """Drop padding rows: a mask-free slice of the first ``rows``."""
    return arr[:rows]
