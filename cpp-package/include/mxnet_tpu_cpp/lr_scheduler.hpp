// Learning-rate schedules for the C++ training loop.
// Capability analog of the reference's cpp-package/include/mxnet-cpp/
// lr_scheduler.h (FactorScheduler with stop_factor floor); the update
// count is the optimizer step, matching python/mxnet/lr_scheduler.py.
#ifndef MXNET_TPU_CPP_LR_SCHEDULER_HPP_
#define MXNET_TPU_CPP_LR_SCHEDULER_HPP_

#include <stdexcept>
#include <vector>

namespace mxnet_tpu_cpp {

class LRScheduler {
 public:
  explicit LRScheduler(float base_lr = 0.01f) : base_lr_(base_lr) {}
  virtual ~LRScheduler() = default;
  void SetLR(float lr) { base_lr_ = lr; }
  virtual float GetLR(unsigned num_update) = 0;

 protected:
  float base_lr_;
};

class FactorScheduler : public LRScheduler {
 public:
  FactorScheduler(int step, float factor = 1.0f,
                  float stop_factor_lr = 1e-8f, float base_lr = 0.01f)
      : LRScheduler(base_lr), step_(step > 0 ? step : 0),
        factor_(factor), stop_factor_lr_(stop_factor_lr) {
    // the python reference raises for step < 1; step=0 would loop
    // forever below
    if (step < 1) throw std::invalid_argument("FactorScheduler: step >= 1");
  }

  float GetLR(unsigned num_update) override {
    while (num_update > count_ + step_) {
      count_ += step_;
      base_lr_ *= factor_;
      if (base_lr_ < stop_factor_lr_) base_lr_ = stop_factor_lr_;
    }
    return base_lr_;
  }

 private:
  unsigned step_, count_ = 0;
  float factor_, stop_factor_lr_;
};

class MultiFactorScheduler : public LRScheduler {
 public:
  MultiFactorScheduler(std::vector<unsigned> steps, float factor,
                       float base_lr = 0.01f)
      : LRScheduler(base_lr), steps_(std::move(steps)), factor_(factor) {}

  float GetLR(unsigned num_update) override {
    // strict >, matching python/mxnet lr_scheduler.py: the boundary
    // update itself still sees the pre-decay rate
    while (cur_ < steps_.size() && num_update > steps_[cur_]) {
      base_lr_ *= factor_;
      ++cur_;
    }
    return base_lr_;
  }

 private:
  std::vector<unsigned> steps_;
  size_t cur_ = 0;
  float factor_;
};

}  // namespace mxnet_tpu_cpp

#endif  // MXNET_TPU_CPP_LR_SCHEDULER_HPP_
