"""Autograd tests (mirrors reference tests/python/unittest/test_autograd.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def test_simple_grad():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 2 * x.asnumpy())


def test_chain_rule():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = nd.exp(x * 2)
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 2 * np.exp(4.0), rtol=1e-5)


def test_reuse_variable():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x + x  # dy/dx = 2x + 1
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [7.0])


def test_multiple_variables():
    a = nd.array([1.0, 2.0])
    b = nd.array([3.0, 4.0])
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        c = (a * b).sum()
    c.backward()
    np.testing.assert_allclose(a.grad.asnumpy(), b.asnumpy())
    np.testing.assert_allclose(b.grad.asnumpy(), a.asnumpy())


def test_head_gradient():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 3
    y.backward(nd.array([10.0, 100.0]))
    np.testing.assert_allclose(x.grad.asnumpy(), [30.0, 300.0])


def test_grad_req_add():
    x = nd.array([1.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = x * 2
        y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [6.0])


def test_detach_blocks_grad():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [4.0])  # only d(y_const * x)/dx


def test_stop_gradient_op():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = nd.stop_gradient(x * x) + x
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [1.0])


def test_not_recording_outside_scope():
    x = nd.array([1.0])
    x.attach_grad()
    y = x * 2  # not recorded
    assert y._ag_node is None


def test_is_recording_is_training():
    assert not autograd.is_recording()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
    with autograd.record(train_mode=False):
        assert not autograd.is_training()
    with autograd.pause():
        assert not autograd.is_recording()


def test_matrix_grad():
    x = nd.array(np.random.rand(3, 4).astype(np.float32))
    w = nd.array(np.random.rand(5, 4).astype(np.float32))
    w.attach_grad()
    with autograd.record():
        y = nd.FullyConnected(x, w, num_hidden=5, no_bias=True)
        loss = y.sum()
    loss.backward()
    # d(sum(x W^T))/dW = sum over batch of x
    expected = np.tile(x.asnumpy().sum(axis=0), (5, 1))
    np.testing.assert_allclose(w.grad.asnumpy(), expected, rtol=1e-5)


def test_softmax_output_grad():
    x = nd.array(np.random.rand(4, 3).astype(np.float32))
    label = nd.array([0, 1, 2, 1])
    x.attach_grad()
    with autograd.record():
        out = nd.SoftmaxOutput(x, label)
    out.backward()
    sm = np.exp(x.asnumpy()) / np.exp(x.asnumpy()).sum(axis=1, keepdims=True)
    oh = np.eye(3, dtype=np.float32)[[0, 1, 2, 1]]
    np.testing.assert_allclose(x.grad.asnumpy(), sm - oh, rtol=1e-4, atol=1e-6)


def test_grad_function():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x * x
    grads = autograd.grad([y], [x])
    np.testing.assert_allclose(grads[0].asnumpy(), [12.0], rtol=1e-5)


def test_numeric_gradient_check():
    """Finite-difference check (the reference's check_numeric_gradient
    pattern, python/mxnet/test_utils.py:790)."""
    x_np = np.random.rand(3, 3).astype(np.float32)
    x = nd.array(x_np)
    x.attach_grad()
    with autograd.record():
        y = (nd.tanh(x) * x).sum()
    y.backward()
    analytic = x.grad.asnumpy()
    eps = 1e-3
    numeric = np.zeros_like(x_np)
    for i in range(3):
        for j in range(3):
            xp = x_np.copy(); xp[i, j] += eps
            xm = x_np.copy(); xm[i, j] -= eps
            numeric[i, j] = ((np.tanh(xp) * xp).sum() - (np.tanh(xm) * xm).sum()) / (2 * eps)
    np.testing.assert_allclose(analytic, numeric, rtol=1e-2, atol=1e-3)


def test_autograd_function():
    import numpy as np
    from mxnet_tpu import autograd, nd
    import mxnet_tpu as mx

    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = 1 / (1 + nd.exp(-x))
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            y, = self.saved_tensors
            return dy * y * (1 - y)

    x = mx.nd.array(np.array([0.5, -1.0, 2.0]))
    x.attach_grad()
    with autograd.record():
        y = Sigmoid()(x)
    y.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    assert np.allclose(x.grad.asnumpy(), s * (1 - s), atol=1e-6)


def test_autograd_function_single_use():
    import numpy as np
    import pytest
    from mxnet_tpu import autograd
    import mxnet_tpu as mx

    class Ident(autograd.Function):
        def forward(self, x):
            return x

        def backward(self, dy):
            return dy

    f = Ident()
    x = mx.nd.array(np.ones(2))
    f(x)
    with pytest.raises(Exception):
        f(x)
