"""INT8 quantization driver: calibrate + rewrite a symbol graph.

Reference: python/mxnet/contrib/quantization.py (quantize_model: graph
pass replacing FC/conv with quantized ops + calibration collecting
layer output ranges) and src/operator/quantization/
quantize_graph_pass.cc.

TPU-native flow (int8 dots ride the MXU via XLA integer dot_general,
kernels in ops/quantization_ops.py):

1. **calibrate** — run the fp32 graph's internals on calibration
   batches, recording per-tensor ranges (``calib_mode='naive'`` =
   exact min/max; ``'entropy'`` routes to the percentile observer in
   mxnet_tpu/quantize/calibrate.py — outlier-clipped ranges at
   ``MXNET_QUANT_PERCENTILE``, the practical stand-in for the
   reference's KL calibration).
2. **rewrite** — every FullyConnected / Convolution node not excluded
   becomes ``quantize_v2(data) → quantized_op → requantize →
   dequantize`` with calibrated ranges baked into the quantize/
   requantize attrs; weights/bias quantize inline (XLA constant-folds
   them for bound executors).
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError

__all__ = ["quantize_model", "calibrate_symbol"]

_QUANTIZABLE = ("FullyConnected", "Convolution")


def _collect_ranges(symbol, arg_params, aux_params, calib_data,
                    data_names, label_names, num_calib_examples=None,
                    observer="minmax"):
    """Run internals forward over calibration batches; return
    {(node_name, out_idx): (min, max)}. One executor is bound per
    distinct batch shape and reused; ranges merge across batches
    (implementation: quantize/calibrate.py — ``observer`` picks the
    statistic, default exact min/max)."""
    from ..quantize.calibrate import collect_activation_ranges
    del label_names                      # signature parity; labels unused
    return collect_activation_ranges(
        symbol, arg_params, aux_params, calib_data,
        data_names=list(data_names), observer=observer,
        num_calib_examples=num_calib_examples)


calibrate_symbol = _collect_ranges


def _param_range(arr):
    a = arr.asnumpy() if hasattr(arr, "asnumpy") else _np.asarray(arr)
    return float(a.min()), float(a.max())


def quantize_model(sym, arg_params, aux_params=None, data_names=("data",),
                   label_names=("softmax_label",), ctx=None,
                   excluded_sym_names=(), calib_mode="naive",
                   calib_data=None, num_calib_examples=None,
                   quantized_dtype="int8", logger=None):
    """Quantize a model (reference: contrib/quantization.py
    quantize_model). Returns (qsym, arg_params, aux_params)."""
    from ..symbol import symbol as _S
    from ..ops import registry as _reg
    from ..quantize.ptq import validate_excluded_names
    if quantized_dtype not in ("int8", "auto"):
        raise MXNetError("quantized_dtype %r not supported"
                         % quantized_dtype)
    if calib_mode not in ("none", "naive", "entropy"):
        raise MXNetError(
            "calib_mode %r not supported (expected 'none', 'naive', or "
            "'entropy')" % (calib_mode,))
    # a typo'd exclusion must fail loudly, not silently quantize the
    # layer it meant to protect
    excluded = validate_excluded_names(sym, excluded_sym_names)

    stats = {}
    if calib_mode != "none":
        if calib_data is None:
            raise MXNetError(
                "calib_mode=%r needs calib_data (pass calib_mode='none' "
                "for uncalibrated dynamic ranges)" % (calib_mode,))
        # entropy -> the percentile observer (outlier-clipped ranges);
        # naive -> exact min/max
        stats = _collect_ranges(sym, arg_params, aux_params, calib_data,
                                list(data_names), list(label_names),
                                num_calib_examples,
                                observer="percentile"
                                if calib_mode == "entropy" else "minmax")

    qv2 = "_contrib_quantize_v2"
    new_of = {}        # id(old_node) -> Symbol (all outputs)

    def _sub(node, oi):
        return new_of[id(node)][oi]

    def _range_attrs(node, oi):
        k = (node.name, oi)
        if k in stats:
            return {"min_calib_range": stats[k][0],
                    "max_calib_range": stats[k][1]}
        return {}

    def _quantize_input(src_sym, range_attrs):
        q = _S._apply_op(_reg.get_op(qv2), [src_sym], dict(range_attrs),
                         None)
        return q

    for node in _S._topo(sym._entries):
        if node.is_var:
            if node.name in (arg_params or {}):
                # bake the known param shape into the rebuilt variable so
                # shape inference works on the quantized graph (deduction
                # can't see through the inserted quantize nodes)
                attrs = dict(node.attrs or {})
                attrs["__shape__"] = tuple(arg_params[node.name].shape)
                nv = _S._Node(None, node.name, attrs, is_aux=node.is_aux)
                new_of[id(node)] = _S.Symbol([(nv, 0)])
            else:
                new_of[id(node)] = _S.Symbol([(node, 0)])
            continue
        inputs_kw = {}
        for in_name, (src, oi) in zip(node.in_names or [], node.inputs):
            inputs_kw[in_name] = _sub(src, oi)
        attrs = dict(node.attrs or {})
        quantizable = node.op in _QUANTIZABLE and node.name not in excluded
        if node.op == "Convolution" and "bias" in inputs_kw \
                and not attrs.get("no_bias", False):
            quantizable = False      # biased conv stays fp32
        if quantizable:
            data_sym = inputs_kw.get("data")
            weight_sym = inputs_kw.get("weight")
            bias_sym = inputs_kw.get("bias")
            (data_src, data_oi) = node.inputs[
                (node.in_names or []).index("data")]
            qd = _quantize_input(data_sym, _range_attrs(data_src, data_oi))
            w_attrs = {}
            wname = "%s_weight" % node.name
            if wname in (arg_params or {}):
                mnw, mxw = _param_range(arg_params[wname])
                w_attrs = {"min_calib_range": mnw, "max_calib_range": mxw}
            qw = _quantize_input(weight_sym, w_attrs)
            if node.op == "FullyConnected":
                arrays = [qd[0], qw[0]]
                qname = "_contrib_quantized_fully_connected"
                if bias_sym is not None and not attrs.get("no_bias", False):
                    qb = _quantize_input(bias_sym, {})
                    arrays += [qb[0], qd[1], qd[2], qw[1], qw[2],
                               qb[1], qb[2]]
                else:
                    arrays += [qd[1], qd[2], qw[1], qw[2]]
                    attrs["no_bias"] = True
                qattrs = {k: attrs[k] for k in ("num_hidden", "no_bias",
                                                "flatten") if k in attrs}
            else:  # Convolution — bias added back in fp32 after dequant
                arrays = [qd[0], qw[0], qd[1], qd[2], qw[1], qw[2]]
                qname = "_contrib_quantized_conv"
                qattrs = {k: attrs[k] for k in ("kernel", "stride", "dilate",
                                                "pad", "num_filter",
                                                "num_group", "layout")
                          if k in attrs}
                qattrs["no_bias"] = True
            qop = _S._apply_op(_reg.get_op(qname), arrays, dict(qattrs),
                               node.name + "_quantized")
            rq = _S._apply_op(_reg.get_op("_contrib_requantize"),
                              [qop[0], qop[1], qop[2]],
                              dict(_range_attrs(node, 0)),
                              node.name + "_requantize")
            deq = _S._apply_op(_reg.get_op("_contrib_dequantize"),
                               [rq[0], rq[1], rq[2]], {},
                               node.name + "_dequantize")
            new_of[id(node)] = deq
        else:
            out = _S._apply_op(_reg.get_op(node.op), [],
                               {**attrs, **inputs_kw}, node.name)
            new_of[id(node)] = out

    entries = []
    for (node, oi) in sym._entries:
        entries.extend(new_of[id(node)][oi]._entries)
    qsym = _S.Symbol(entries)
    return qsym, arg_params, aux_params or {}
