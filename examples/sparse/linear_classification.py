"""Sparse linear classification on LibSVM data.

Capability analog of the reference's sparse linear classification
example (reference: example/sparse/linear_classification/train.py —
avazu LibSVM data, csr batches, row_sparse weight, lazy SGD through a
kvstore). TPU-native path: LibSVMIter yields CSR batches;
``sparse.dot(csr, W)`` computes on the stored nonzeros only and its
backward emits a ROW-SPARSE gradient over the touched feature columns;
the optimizer's lazy kernels update only those rows; kvstore push
aggregates the rsp gradients across device slices.

Run: python examples/sparse/linear_classification.py [--data path.libsvm]
(without --data a synthetic two-class LibSVM file is generated).
"""
import argparse
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx                                     # noqa: E402
from mxnet_tpu import autograd, nd, optimizer as opt       # noqa: E402
from mxnet_tpu.io import LibSVMIter                        # noqa: E402
from mxnet_tpu.ndarray import sparse                       # noqa: E402


def synthetic_libsvm(path, n=2048, d=10000, nnz=16, seed=0):
    """Two-class problem with a sparse planted hyperplane."""
    rng = np.random.RandomState(seed)
    w_true = np.zeros(d)
    support = rng.choice(d, 64, replace=False)
    w_true[support] = rng.randn(64)
    with open(path, "w") as f:
        for _ in range(n):
            cols = np.sort(rng.choice(d, nnz, replace=False))
            vals = rng.randn(nnz)
            y = 1 if vals @ w_true[cols] > 0 else 0
            feats = " ".join("%d:%.4f" % (c, v) for c, v in zip(cols, vals))
            f.write("%d %s\n" % (y, feats))
    return path


def train(data_path, num_features, batch_size=64, epochs=2,
          optimizer="sgd", lr=0.5, kvstore=None, log=print):
    it = LibSVMIter(data_libsvm=data_path, data_shape=(num_features,),
                    batch_size=batch_size)
    weight = nd.zeros((num_features, 1))
    bias = nd.zeros((1,))
    weight.attach_grad()
    bias.attach_grad()
    optim = opt.create(optimizer, learning_rate=lr)
    states = {0: optim.create_state(0, weight), 1: optim.create_state(1, bias)}

    kv = mx.kvstore.create(kvstore) if kvstore else None
    if kv is not None:
        kv.init(0, weight)
        kv.set_optimizer(optim)

    losses = []
    for epoch in range(epochs):
        it.reset()
        total, count = 0.0, 0
        for batch in it:
            x, y = batch.data[0], batch.label[0]
            with autograd.record():
                logits = sparse.dot(x, weight) + bias
                # logistic loss, numerically stable
                z = logits.reshape((-1,))
                loss = nd.mean(nd.relu(z) - z * y.reshape((-1,)) +
                               nd.log(1 + nd.exp(-nd.abs(z))))
            loss.backward()
            if kv is not None:
                kv.push(0, weight.grad)      # rsp grad -> lazy update
                kv.pull(0, out=weight)
            else:
                optim.update(0, weight, weight.grad, states[0])
            optim.update(1, bias, bias.grad, states[1])
            total += float(loss.asscalar())
            count += 1
        losses.append(total / max(count, 1))
        log("epoch %d: loss %.4f" % (epoch, losses[-1]))
    return losses


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None, help="libsvm file")
    ap.add_argument("--num-features", type=int, default=10000)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epoch", type=int, default=2)
    ap.add_argument("--optimizer", default="sgd",
                    choices=["sgd", "adam", "adagrad"])
    ap.add_argument("--lr", type=float, default=0.5)
    ap.add_argument("--kvstore", default=None)
    args = ap.parse_args()
    path = args.data
    if path is None:
        path = os.path.join(tempfile.gettempdir(), "synthetic.libsvm")
        synthetic_libsvm(path, d=args.num_features)
    losses = train(path, args.num_features, args.batch_size,
                   args.num_epoch, args.optimizer, args.lr, args.kvstore)
    assert losses[-1] < losses[0], "loss did not improve"


if __name__ == "__main__":
    main()
