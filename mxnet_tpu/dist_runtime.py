"""Multi-host runtime lifecycle for ``dist_tpu_sync``.

One idempotent, refcounted wrapper around the jax distributed runtime
so the kvstore (and anything else that needs the global device view)
can say "make sure the cluster runtime is up" without owning its
lifecycle:

* :func:`acquire` — initialize the runtime exactly once per process
  (explicit ``MXNET_DIST_*`` env first, standard cluster autodetection
  second), or adopt an already-initialized runtime (a launcher that
  called ``jax.distributed.initialize`` itself).
* :func:`release` — drop one reference; when the LAST holder releases
  AND this module performed the initialization, the runtime is torn
  down cleanly.  A runtime initialized by someone else is never shut
  down from here.
* :func:`reinit` — elastic shutdown→reinit cycle: tear the current
  world down (tolerating dead peers) and bring a NEW world up on a
  fresh coordinator, in the same process.  This is the primitive the
  elastic rescale path (elastic.py) is built on.

Why the explicit route builds the coordination client by hand
-------------------------------------------------------------
``jax.distributed.initialize`` wires the XLA coordination service with
defaults that are actively hostile to elastic membership (verified
empirically against jax 0.4.37 / jaxlib 0.4.36 with gloo collectives):

* the client's missed-heartbeat/error-poll handler is a hard
  ``LOG(QFATAL)`` — ~100 s after ANY peer dies, every *survivor* is
  SIGABRTed by its own runtime ("Terminating process because the JAX
  distributed service detected fatal errors");
* ``jax.distributed.shutdown()`` runs a shutdown *barrier* that blocks
  until every registered task calls in — with a dead peer it parks
  until the same watchdog kills the process;
* ``State.initialize`` refuses a second call per process, so there is
  no shutdown→reinit cycle at all.

So for the explicit ``MXNET_DIST_COORDINATOR`` route this module
constructs the service/client itself via ``xla_extension`` and
installs them into ``jax._src.distributed.global_state`` (the exact
slots jax's own initialize fills, and the place the gloo CPU backend
looks for its KV store):

* ``max_missing_heartbeats`` is set effectively infinite — death
  detection belongs to the elastic control plane (collective error /
  stale heartbeat / step watchdog), which reacts in
  ``MXNET_DIST_DEAD_S`` instead of aborting the survivor at 100 s;
* ``shutdown_timeout`` is short, so a shutdown barrier with a dead
  peer resolves in seconds (the agent "proceeds with shutdown anyway",
  which is what stops its heartbeat/error-poll threads);
* ``shutdown_on_destruction=False``, so dropping the last Python
  reference can never run a blocking barrier at an awkward time.

Teardown order matters and is load-bearing: drop the backend first
(the gloo collectives hold a reference to the client's KV store), then
destroy the CLIENT (stops its error-poll thread), and only then the
service — destroying the service while any client still polls turns
the closed socket into the QFATAL this module exists to avoid.

Configuration (config.py):

* ``MXNET_DIST_COORDINATOR`` — ``host:port`` of process 0's
  coordinator service.  Setting it (plus the two below) is the
  explicit, works-anywhere route — the CPU/gloo acceptance tests and
  the ``dist_train_sync`` bench use it, and it is the only route that
  supports :func:`reinit` (elastic rescale).
* ``MXNET_DIST_NUM_PROCESSES`` / ``MXNET_DIST_PROCESS_ID`` — world
  size and this process's rank.

Without ``MXNET_DIST_*``, :func:`env_configured` falls back to the
standard signals ``jax.distributed.initialize()`` autodetects itself
(Cloud TPU metadata, SLURM, Open MPI) so a TPU pod slice launched
through the normal tooling needs no extra variables.

On a CPU backend the gloo collectives implementation is selected
before initialization when this jax exposes the knob (the raw CPU
backend cannot run multiprocess computations) — the same live-probed
gate ``tests/test_kvstore_multiprocess.py`` uses.
"""
from __future__ import annotations

import gc
import logging
import os
import threading

from .base import MXNetError

__all__ = ["acquire", "release", "initialize", "shutdown", "teardown",
           "reinit", "is_initialized", "env_configured", "process_count",
           "process_index", "generation"]

_log = logging.getLogger(__name__)

_lock = threading.Lock()
_refs = [0]          # live acquire() holders
_owned = [False]     # did THIS module initialize the runtime?
_manual = [False]    # did we build the client/service by hand?
_generation = [0]    # completed initialize cycles (elastic member epochs)

# Coordination-service tuning for the hand-built route.  Heartbeats are
# kept alive (they double as TCP keepalive) but the miss threshold is
# effectively infinite: membership death detection is the elastic
# layer's job, not the coordination service's QFATAL.
_HB_INTERVAL_S = 10
_HB_MAX_MISSING = 1 << 20
_INIT_TIMEOUT_S = 60
_SHUTDOWN_TIMEOUT_S = 2

# standard env signals jax.distributed.initialize() can autodetect a
# cluster from without explicit arguments
_AUTO_ENV = ("SLURM_JOB_ID", "OMPI_COMM_WORLD_SIZE",
             "TPU_WORKER_HOSTNAMES", "MEGASCALE_COORDINATOR_ADDRESS",
             "COORDINATOR_ADDRESS")


def _cfg(name):
    from .config import get
    return get(name)


def _global_state():
    from jax._src import distributed as _d
    return _d.global_state


def is_initialized():
    """Whether this process already has a live distributed runtime
    (ours or anyone's)."""
    try:
        return _global_state().client is not None
    except Exception:
        return False


def env_configured():
    """Whether the environment describes a multi-process cluster this
    process could join: explicit ``MXNET_DIST_*`` settings, or one of
    the standard signals jax autodetects."""
    if _cfg("MXNET_DIST_COORDINATOR"):
        return True
    return any(os.environ.get(v) for v in _AUTO_ENV)


def _select_cpu_collectives():
    """Route multiprocess CPU computations over gloo when this jax has
    the knob; a no-op on accelerator backends and older jax (where the
    raw CPU backend simply cannot run multiprocess programs)."""
    import jax
    if os.environ.get("JAX_PLATFORMS", "").lower() != "cpu" and \
            _cfg("MXNET_TPU_PLATFORM") != "cpu":
        return
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass


def _manual_initialize(coord, num_processes, process_id):
    """Build the coordination service (rank 0) + client by hand and
    install them into jax's global state — the elastic-safe equivalent
    of ``jax.distributed.initialize`` (see module docstring)."""
    from jax._src.lib import xla_extension as xe
    st = _global_state()
    service = None
    if process_id == 0:
        bind = "[::]:" + coord.rsplit(":", 1)[1]
        service = xe.get_distributed_runtime_service(
            bind, num_processes,
            heartbeat_interval=_HB_INTERVAL_S,
            max_missing_heartbeats=_HB_MAX_MISSING)
    try:
        client = xe.get_distributed_runtime_client(
            coord, process_id,
            init_timeout=_INIT_TIMEOUT_S,
            shutdown_timeout=_SHUTDOWN_TIMEOUT_S,
            heartbeat_interval=_HB_INTERVAL_S,
            max_missing_heartbeats=_HB_MAX_MISSING,
            shutdown_on_destruction=False,
            use_compression=True)
        client.connect()
    except Exception:
        if service is not None:
            del service
            gc.collect()
        raise
    st.service = service
    st.client = client
    st.process_id = process_id
    st.num_processes = num_processes
    st.coordinator_address = coord


def _initialize_locked(coordinator=None, num_processes=None,
                       process_id=None):
    import jax
    if is_initialized():
        return False
    coord = coordinator or _cfg("MXNET_DIST_COORDINATOR")
    if num_processes is None and coord:
        num_processes = int(_cfg("MXNET_DIST_NUM_PROCESSES"))
    if process_id is None and coord:
        process_id = int(_cfg("MXNET_DIST_PROCESS_ID"))
    try:
        if coord:
            _select_cpu_collectives()
            _manual_initialize(coord, int(num_processes), int(process_id))
            # keep env/config coherent for everything that re-reads the
            # world description (kvstore sizing, respawned children)
            os.environ["MXNET_DIST_COORDINATOR"] = coord
            os.environ["MXNET_DIST_NUM_PROCESSES"] = str(int(num_processes))
            os.environ["MXNET_DIST_PROCESS_ID"] = str(int(process_id))
            _owned[0] = True
            _manual[0] = True
            _generation[0] += 1
            return True
        if any(os.environ.get(v) for v in _AUTO_ENV):
            _select_cpu_collectives()
            jax.distributed.initialize()   # standard autodetection
            _owned[0] = True
            _manual[0] = False
            _generation[0] += 1
            return True
    except MXNetError:
        raise
    except Exception as e:
        raise MXNetError(
            "distributed runtime initialization failed for the "
            "configured cluster (%s): %s" % (coord or "autodetected env", e))
    return False


def initialize(coordinator=None, num_processes=None, process_id=None):
    """Idempotent distributed-runtime bring-up.

    Returns True when THIS call initialized the runtime, False when it
    was already up or no cluster is configured.  Raises
    :class:`MXNetError` when the environment names a cluster but the
    join fails — silently training single-process after a botched
    rendezvous would corrupt the run, not degrade it."""
    with _lock:
        return _initialize_locked(coordinator, num_processes, process_id)


def _teardown_locked(graceful=True):
    """Tear down the runtime IF this module initialized it (no-op
    otherwise — never shut down a launcher-owned runtime).  Caller
    holds ``_lock``.

    Safe with dead peers: the shutdown barrier resolves within
    ``_SHUTDOWN_TIMEOUT_S`` and failure is tolerated (the coordination
    agent stops its threads either way).  The client is destroyed
    BEFORE the service — the reverse order turns the service's closed
    socket into a fatal error on the client's poll thread."""
    if not _owned[0]:
        return
    _owned[0] = False
    if not _manual[0]:
        try:
            import jax
            jax.distributed.shutdown()
        except Exception as e:       # already down / interpreter exit
            _log.debug("jax.distributed.shutdown: %s", e)
        return
    try:
        import jax
        import jax.extend.backend as _jeb
        st = _global_state()
        if st.client is not None:
            try:
                st.client.shutdown()
            except Exception as e:
                # expected with dead peers: the barrier fails after
                # _SHUTDOWN_TIMEOUT_S and the agent shuts down anyway
                _log.info("distributed client shutdown (dead peers "
                          "tolerated): %s", str(e)[:200])
        jax.clear_caches()
        _jeb.clear_backends()
        st.client = None
        st.preemption_sync_manager = None
        gc.collect()                 # stop client heartbeat/poll threads
        st.service = None
        gc.collect()                 # only now close the service socket
        st.process_id = 0
        st.num_processes = 1
        st.coordinator_address = None
    except Exception as e:
        _log.warning("distributed runtime teardown: %s", e)


def shutdown():
    with _lock:
        _teardown_locked()


def teardown(graceful=True):
    """Tear the runtime down NOW (elastic path; refcount survives so
    the holders' eventual release() calls stay balanced)."""
    with _lock:
        _teardown_locked(graceful)


def reinit(coordinator, num_processes, process_id):
    """Elastic shutdown→reinit cycle: tear down the current world
    (tolerating dead peers) and join a NEW world in-place.

    Invalidates the process-wide program-registry version salt — the
    salt embeds ``processes=N``, so programs built for the new world
    re-fingerprint (and replay from the persistent compile cache as
    disk hits rather than recompiles)."""
    with _lock:
        _teardown_locked(graceful=False)
        ok = _initialize_locked(coordinator, num_processes, process_id)
        if not ok:
            raise MXNetError("elastic reinit failed to join the new "
                             "world at %s" % coordinator)
    try:
        from . import programs
        programs.invalidate_version_salt()
    except Exception:
        pass
    return True


def acquire():
    """Refcounted ensure-initialized; pair with :func:`release`.

    Initialization is attempted whenever no runtime is live — NOT only
    on the first reference: an early holder acquired before the cluster
    env was set (e.g. ``io.dist_parts`` on a laptop) must not suppress
    a later holder's rendezvous."""
    with _lock:
        if not is_initialized():
            _initialize_locked()   # marks _owned when it performs the init
        _refs[0] += 1


def release():
    """Drop one :func:`acquire` reference; the last release shuts the
    runtime down when this module owns it."""
    with _lock:
        if _refs[0] > 0:
            _refs[0] -= 1
            if _refs[0] == 0:
                _teardown_locked()


def generation():
    """Completed initialize cycles in this process (1 after the first
    bring-up; bumps on every elastic :func:`reinit`)."""
    return _generation[0]


def process_count():
    try:
        import jax
        return int(jax.process_count())
    except Exception:
        return 1


def process_index():
    try:
        import jax
        return int(jax.process_index())
    except Exception:
        return 0
