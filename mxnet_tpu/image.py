"""Image IO + augmentation.

Reference: python/mxnet/image/image.py (imdecode/imread/imresize, crop
helpers, Augmenter pipeline, ImageIter) and the C++ decode/augment path
src/io/image_aug_default.cc.

Decoding uses OpenCV (same dependency as the reference); decoded images
are HWC **RGB** uint8 NDArrays. Augmenters run on host numpy (CPU) —
the TPU analog of the reference's CPU-side OMP decode workers — and only
final batches are shipped to the device.
"""
from __future__ import annotations

import os
import random as _pyrandom

import numpy as _np

from .base import MXNetError
from .ndarray.ndarray import NDArray, array

__all__ = ["imdecode", "imread", "imresize", "resize_short", "fixed_crop",
           "center_crop", "random_crop", "random_size_crop",
           "color_normalize", "Augmenter", "ResizeAug", "ForceResizeAug",
           "RandomCropAug", "CenterCropAug", "RandomSizedCropAug",
           "HorizontalFlipAug", "CastAug", "ColorNormalizeAug",
           "BrightnessJitterAug", "ContrastJitterAug", "SaturationJitterAug",
           "LightingAug", "ColorJitterAug", "CreateAugmenter", "ImageIter",
           "DetAugmenter", "DetBorrowAug", "DetHorizontalFlipAug",
           "DetRandomCropAug", "DetRandomPadAug", "DetForceResizeAug",
           "CreateDetAugmenter", "ImageDetIter"]


def _cv2():
    import cv2
    return cv2


def _to_np(img):
    if isinstance(img, NDArray):
        return img.asnumpy()
    return _np.asarray(img)


def _wrap_like(src, out):
    """Return ``out`` in the same container family as ``src``: NDArray in
    -> NDArray out; plain numpy passes through untouched. Keeping the
    decode/augment hot path in numpy avoids a host->device transfer per
    augmenter stage (the reference's augmenters are host-side cv::Mat for
    the same reason, src/io/image_aug_default.cc)."""
    if isinstance(src, NDArray):
        return array(_np.ascontiguousarray(out), dtype=out.dtype)
    return _np.ascontiguousarray(out)


def imdecode(buf, flag=1, to_rgb=True, out=None, to_ndarray=True):
    """Decode an image byte buffer to an HWC NDArray
    (reference: image.py imdecode → cv2.imdecode).

    ``to_ndarray=False`` returns host numpy — combined with the
    numpy-passthrough augmenters this keeps the whole decode+augment
    pipeline on the host with ZERO device round-trips per image (the
    device sees only final batches)."""
    cv2 = _cv2()
    if isinstance(buf, (bytes, bytearray)):
        buf = _np.frombuffer(buf, dtype=_np.uint8)
    img = cv2.imdecode(buf, cv2.IMREAD_COLOR if flag else
                       cv2.IMREAD_GRAYSCALE)
    if img is None:
        raise MXNetError("cannot decode image")
    if flag and to_rgb:
        img = img[..., ::-1]
    if not flag:
        img = img[..., None]
    img = _np.ascontiguousarray(img)
    if not to_ndarray:
        return img
    return array(img, dtype=_np.uint8)


def imread(filename, flag=1, to_rgb=True):
    """Reference: image.py imread."""
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag, to_rgb)


def imresize(src, w, h, interp=1):
    """Resize HWC image to (h, w) (reference: image.py imresize)."""
    cv2 = _cv2()
    img = _to_np(src)
    interp_map = {0: cv2.INTER_NEAREST, 1: cv2.INTER_LINEAR,
                  2: cv2.INTER_CUBIC, 3: cv2.INTER_AREA,
                  4: cv2.INTER_LANCZOS4}
    out = cv2.resize(img, (w, h), interpolation=interp_map.get(interp, 1))
    if out.ndim == 2:
        out = out[..., None]
    return _wrap_like(src, out)


def resize_short(src, size, interp=2):
    """Resize so the shorter edge equals ``size``
    (reference: image.py resize_short)."""
    img = _to_np(src)
    h, w = img.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    """Crop a region, optionally resize (reference: image.py fixed_crop)."""
    img = _to_np(src)
    out = img[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        return _wrap_like(src, _to_np(
            imresize(out, size[0], size[1], interp)))
    return _wrap_like(src, out)


def center_crop(src, size, interp=2):
    """Reference: image.py center_crop. Returns (img, (x0, y0, w, h))."""
    img = _to_np(src)
    h, w = img.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def random_crop(src, size, interp=2):
    """Reference: image.py random_crop."""
    img = _to_np(src)
    h, w = img.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = _pyrandom.randint(0, w - new_w)
    y0 = _pyrandom.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2):
    """Random area+aspect crop (reference: image.py random_size_crop)."""
    img = _to_np(src)
    h, w = img.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = _pyrandom.uniform(area[0], area[1]) * src_area
        log_ratio = (_np.log(ratio[0]), _np.log(ratio[1]))
        aspect = _np.exp(_pyrandom.uniform(*log_ratio))
        new_w = int(round((target_area * aspect) ** 0.5))
        new_h = int(round((target_area / aspect) ** 0.5))
        if new_w <= w and new_h <= h:
            x0 = _pyrandom.randint(0, w - new_w)
            y0 = _pyrandom.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    """Reference: image.py color_normalize."""
    img = _to_np(src).astype(_np.float32)
    mean = _np.asarray(_to_np(mean), dtype=_np.float32)
    img = img - mean
    if std is not None:
        img = img / _np.asarray(_to_np(std), dtype=_np.float32)
    return _wrap_like(src, img)


# ---------------------------------------------------------------------------
# augmenter pipeline (reference: image.py Augmenter zoo +
# src/io/image_aug_default.cc)
# ---------------------------------------------------------------------------

class Augmenter(object):
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size, self.area, self.ratio, self.interp = \
            size, area, ratio, interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            return _wrap_like(src, _to_np(src)[:, ::-1])
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean, self.std = mean, std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.brightness, self.brightness)
        return _wrap_like(src, _to_np(src).astype(_np.float32) * alpha)


class ContrastJitterAug(Augmenter):
    _coef = _np.array([[[0.299, 0.587, 0.114]]], dtype=_np.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.contrast, self.contrast)
        img = _to_np(src).astype(_np.float32)
        gray = (img * self._coef).sum() * 3.0 / img.size
        return _wrap_like(src, img * alpha + gray * (1.0 - alpha))


class SaturationJitterAug(Augmenter):
    _coef = _np.array([[[0.299, 0.587, 0.114]]], dtype=_np.float32)

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.saturation, self.saturation)
        img = _to_np(src).astype(_np.float32)
        gray = (img * self._coef).sum(axis=2, keepdims=True)
        return _wrap_like(src, img * alpha + gray * (1.0 - alpha))


class LightingAug(Augmenter):
    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = _np.asarray(eigval, dtype=_np.float32)
        self.eigvec = _np.asarray(eigvec, dtype=_np.float32)

    def __call__(self, src):
        alpha = _np.random.normal(0, self.alphastd, size=(3,))
        rgb = (self.eigvec * alpha * self.eigval).sum(axis=1)
        return _wrap_like(src, _to_np(src).astype(_np.float32) + rgb)


class ColorJitterAug(Augmenter):
    def __init__(self, brightness=0, contrast=0, saturation=0):
        super().__init__(brightness=brightness, contrast=contrast,
                         saturation=saturation)
        self.augs = []
        if brightness:
            self.augs.append(BrightnessJitterAug(brightness))
        if contrast:
            self.augs.append(ContrastJitterAug(contrast))
        if saturation:
            self.augs.append(SaturationJitterAug(saturation))

    def __call__(self, src):
        augs = list(self.augs)
        _pyrandom.shuffle(augs)
        for aug in augs:
            src = aug(src)
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, inter_method=2):
    """Build the standard augmenter list
    (reference: image.py CreateAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3. / 4., 4. / 3.), inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class NativeImageDecoder(object):
    """ctypes front for the C++ parallel decode pool
    (src/native/imagedec.cc — the analog of the reference's OMP decode
    in src/io/iter_image_recordio_2.cc:78 ParseChunk). One call decodes
    + augments a whole batch of JPEG buffers into a float32 CHW array
    on native threads with the GIL released."""

    def __init__(self, data_shape, resize=0, rand_crop=False,
                 rand_mirror=False, mean=None, std=None, num_threads=0,
                 seed=0):
        import ctypes
        from . import _native
        lib = _native.imagedec_lib()
        if lib is None:
            raise MXNetError("native image decoder unavailable "
                             "(no g++/OpenCV)")
        c, h, w = data_shape
        if c not in (1, 3):
            raise MXNetError("native decoder supports 1 or 3 channels")
        if mean is True:
            mean = _np.array([123.68, 116.28, 103.53])
        if std is True:
            std = _np.array([58.395, 57.12, 57.375])

        def fptr(v):
            if v is None:
                return None
            a = _np.ascontiguousarray(
                _np.broadcast_to(_np.asarray(_to_np(v), _np.float32)
                                 .ravel(), (3,)) if c == 3
                else _np.asarray(_to_np(v), _np.float32).ravel()[:1])
            return a, a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))

        m, s = fptr(mean), fptr(std)
        self._keep = (m, s)                    # keep buffers alive
        self._lib = lib
        self._ctypes = ctypes
        self._shape = (c, h, w)
        self._h = lib.imgdec_create(
            int(num_threads), h, w, c, int(resize), int(bool(rand_crop)),
            int(bool(rand_mirror)), m and m[1], s and s[1], int(seed))
        if not self._h:
            raise MXNetError("imgdec_create failed")

    def decode_batch(self, bufs, base=0, out=None):
        """Decode ``bufs`` (list of JPEG bytes) -> (n, c, h, w) float32.
        ``base`` keys the per-image augmentation RNG by stream position
        so results are identical for any thread count."""
        ctypes = self._ctypes
        n = len(bufs)
        c, h, w = self._shape
        if out is None:
            out = _np.empty((n, c, h, w), _np.float32)
        arr_p = (ctypes.c_char_p * n)(*bufs)
        lens = (ctypes.c_int64 * n)(*[len(b) for b in bufs])
        rc = self._lib.imgdec_decode_batch(
            self._h, n, arr_p, lens, int(base),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        if rc != 0:
            raise MXNetError("native decode failed: %s" %
                             self._lib.imgdec_last_error(self._h)
                             .decode("utf-8", "replace"))
        return out

    def close(self):
        h, self._h = self._h, None
        if h and self._lib is not None:
            self._lib.imgdec_destroy(h)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# CreateAugmenter kwargs the native decoder implements; anything else
# (rand_resize, color jitter, pca_noise, non-cubic interp) falls back
# to the Python augmenter loop
_NATIVE_AUG_KEYS = {"resize", "rand_crop", "rand_mirror", "mean", "std"}


class ImageIter(object):
    """Image data iterator over .rec packs or path lists with augmentation
    (reference: image.py ImageIter, C++ hot path
    src/io/iter_image_recordio_2.cc).

    ``preprocess_threads`` > 0 engages the native parallel decode pool
    (NativeImageDecoder) when the requested augmentations are in its
    fast path; 0 keeps the pure-Python per-image loop."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 shuffle=False, aug_list=None, imglist=None, dtype="float32",
                 num_parts=1, part_index=0, preprocess_threads=0,
                 seed=0, **kwargs):
        from .io import DataDesc
        assert path_imgrec or path_imglist or imglist is not None
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        self.dtype = dtype
        self._num_parts = int(num_parts)
        self._part_index = int(part_index)
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **kwargs)
        self._native = None
        self._stream_pos = 0                  # RNG key for native augs
        self._seed = int(seed)
        self._shuffle_epoch = -1
        if preprocess_threads and aug_list is None and dtype == "float32" \
                and all(k in _NATIVE_AUG_KEYS or not kwargs[k]
                        for k in kwargs if k != "inter_method") \
                and kwargs.get("inter_method", 2) == 2:
            try:
                self._native = NativeImageDecoder(
                    data_shape, resize=kwargs.get("resize", 0),
                    rand_crop=kwargs.get("rand_crop", False),
                    rand_mirror=kwargs.get("rand_mirror", False),
                    mean=kwargs.get("mean"), std=kwargs.get("std"),
                    num_threads=preprocess_threads, seed=seed)
            except MXNetError:
                self._native = None           # no toolchain: Python path
        self.imgrec = None
        self.imglist = None
        self.seq = None
        if path_imgrec:
            from . import recordio
            idx_path = os.path.splitext(path_imgrec)[0] + ".idx"
            if os.path.exists(idx_path):
                self.imgrec = recordio.MXIndexedRecordIO(
                    idx_path, path_imgrec, "r")
                self.seq = list(self.imgrec.keys)
            else:
                self.imgrec = recordio.MXRecordIO(path_imgrec, "r")
        elif path_imglist:
            self.imglist = {}
            with open(path_imglist) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    label = _np.array(parts[1:-1], dtype=_np.float32)
                    self.imglist[int(parts[0])] = (label, parts[-1])
            self.seq = sorted(self.imglist)
            self.path_root = path_root
        else:
            self.imglist = {i: (_np.array(lbl, dtype=_np.float32), p)
                            for i, (lbl, p) in enumerate(imglist)}
            self.seq = sorted(self.imglist)
            self.path_root = path_root
        if self._num_parts > 1:
            # distributed sharding under the shared partition contract
            # (io.shard_bounds: disjoint, exhaustive, bounds-checked;
            # reference: iter_image_recordio_2.cc num_parts/part_index)
            if self.seq is None:
                raise ValueError(
                    "num_parts>1 needs an indexed .rec (an .idx next to the "
                    ".rec) or an image list to shard")
            from .io import shard_bounds
            lo, hi = shard_bounds(len(self.seq), self._num_parts,
                                  self._part_index)
            self.seq = self.seq[lo:hi]
        self.provide_data = [DataDesc(
            "data", (batch_size,) + self.data_shape, dtype)]
        self.provide_label = [DataDesc(
            "softmax_label", (batch_size, label_width)
            if label_width > 1 else (batch_size,), dtype)]
        self.cursor = 0
        self.reset()

    def reset(self):
        self.cursor = 0
        if self.shuffle and self.seq is not None:
            # epoch shuffles come from a PRIVATE (seed, epoch)-keyed
            # stream, not the global RNG: each shard permutes its own
            # fixed slice reproducibly, and user random.seed() streams
            # never interleave with input shuffling
            from .io import mix_seed
            self._shuffle_epoch += 1
            _pyrandom.Random(mix_seed(self._seed, self._shuffle_epoch)
                             ).shuffle(self.seq)
        if self.imgrec is not None and self.seq is None:
            self.imgrec.reset()

    def next_sample(self):
        from . import recordio as rio
        if self.seq is not None and self.cursor >= len(self.seq):
            raise StopIteration
        if self.imgrec is not None:
            if self.seq is not None:
                rec = self.imgrec.read_idx(self.seq[self.cursor])
            else:
                rec = self.imgrec.read()
                if rec is None:
                    raise StopIteration
            self.cursor += 1
            header, img = rio.unpack(rec)
            return header.label, img
        label, fname = self.imglist[self.seq[self.cursor]]
        self.cursor += 1
        with open(os.path.join(self.path_root, fname), "rb") as f:
            return label, f.read()

    def next(self):
        from .io import DataBatch
        c, h, w = self.data_shape
        batch_data = _np.zeros((self.batch_size, c, h, w), dtype=self.dtype)
        batch_label = _np.zeros((self.batch_size, self.label_width),
                                dtype=_np.float32)
        i = 0
        if self._native is not None:
            bufs = []
            try:
                while i < self.batch_size:
                    label, s = self.next_sample()
                    bufs.append(bytes(s))
                    batch_label[i] = label
                    i += 1
            except StopIteration:
                if i == 0:
                    raise
            if bufs:
                self._native.decode_batch(bufs, base=self._stream_pos,
                                          out=batch_data[:len(bufs)])
                self._stream_pos += len(bufs)
        else:
            try:
                while i < self.batch_size:
                    label, s = self.next_sample()
                    img = imdecode(s, 1 if c == 3 else 0, to_ndarray=False)
                    for aug in self.auglist:
                        img = aug(img)
                    arr = _to_np(img)
                    if arr.ndim == 3:
                        arr = arr.transpose(2, 0, 1)
                    batch_data[i] = arr
                    batch_label[i] = label
                    i += 1
            except StopIteration:
                if i == 0:
                    raise
        pad = self.batch_size - i
        lbl = batch_label[:, 0] if self.label_width == 1 \
            else batch_label
        return DataBatch(data=[array(batch_data)],
                         label=[array(lbl)], pad=pad)

    def __next__(self):
        return self.next()

    def __iter__(self):
        return self


# ---------------------------------------------------------------------------
# detection augmenters + iterator (reference:
# python/mxnet/image/detection.py, src/io/image_det_aug_default.cc:1 —
# every geometric transform updates the box labels in lockstep)
# ---------------------------------------------------------------------------

class DetAugmenter(object):
    """Detection augmenter: ``(image, label) -> (image, label)`` where
    label rows are [cls, xmin, ymin, xmax, ymax] in [0,1] image coords
    (reference: detection.py DetAugmenter)."""

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Lift a color/cast-only classification augmenter into detection
    (labels pass through untouched)."""

    def __init__(self, augmenter):
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image and x-coordinates with probability p."""

    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src, label):
        if _pyrandom.random() < self.p:
            arr = _to_np(src)[:, ::-1]
            label = label.copy()
            valid = label[:, 0] >= 0
            x1 = label[:, 1].copy()
            label[:, 1] = _np.where(valid, 1.0 - label[:, 3], label[:, 1])
            label[:, 3] = _np.where(valid, 1.0 - x1, label[:, 3])
            return _wrap_like(src, arr), label
        return src, label


def _boxes_iou_cover(label, box):
    """Fraction of each gt box's area covered by crop ``box``."""
    x1 = _np.maximum(label[:, 1], box[0])
    y1 = _np.maximum(label[:, 2], box[1])
    x2 = _np.minimum(label[:, 3], box[2])
    y2 = _np.minimum(label[:, 4], box[3])
    inter = _np.maximum(x2 - x1, 0) * _np.maximum(y2 - y1, 0)
    area = _np.maximum((label[:, 3] - label[:, 1]) *
                       (label[:, 4] - label[:, 2]), 1e-12)
    return inter / area


def _update_det_labels(label, box):
    """Re-express labels in crop/pad box coords; drop boxes whose center
    leaves the region (reference: detection.py _update_labels)."""
    out = label.copy()
    bw = box[2] - box[0]
    bh = box[3] - box[1]
    cx = (label[:, 1] + label[:, 3]) / 2
    cy = (label[:, 2] + label[:, 4]) / 2
    keep = ((label[:, 0] >= 0) & (cx >= box[0]) & (cx <= box[2])
            & (cy >= box[1]) & (cy <= box[3]))
    out[:, 1] = _np.clip((label[:, 1] - box[0]) / bw, 0, 1)
    out[:, 2] = _np.clip((label[:, 2] - box[1]) / bh, 0, 1)
    out[:, 3] = _np.clip((label[:, 3] - box[0]) / bw, 0, 1)
    out[:, 4] = _np.clip((label[:, 4] - box[1]) / bh, 0, 1)
    out[~keep] = -1.0
    # compact valid rows to the front like the reference
    order = _np.argsort(~keep, kind="stable")
    return out[order]


class DetRandomCropAug(DetAugmenter):
    """IoU-constrained random crop (SSD-style; reference: detection.py
    DetRandomCropAug): sample candidate crops until one keeps at least
    ``min_object_covered`` of some object, then remap labels."""

    def __init__(self, min_object_covered=0.3, aspect_ratio_range=(0.75,
                 1.33), area_range=(0.3, 1.0), max_attempts=30, p=0.5):
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.p = p

    def __call__(self, src, label):
        if _pyrandom.random() > self.p:
            return src, label
        arr = _to_np(src)
        h, w = arr.shape[:2]
        for _ in range(self.max_attempts):
            area = _pyrandom.uniform(*self.area_range)
            ratio = _pyrandom.uniform(*self.aspect_ratio_range)
            cw = min(1.0, _np.sqrt(area * ratio))
            ch = min(1.0, _np.sqrt(area / ratio))
            cx = _pyrandom.uniform(0, 1 - cw)
            cy = _pyrandom.uniform(0, 1 - ch)
            box = (cx, cy, cx + cw, cy + ch)
            valid = label[:, 0] >= 0
            if not valid.any():
                break
            cover = _boxes_iou_cover(label[valid], box)
            if cover.max() >= self.min_object_covered:
                x0, y0 = int(cx * w), int(cy * h)
                x1, y1 = int((cx + cw) * w), int((cy + ch) * h)
                cropped = arr[y0:y1, x0:x1]
                return _wrap_like(src, cropped), _update_det_labels(label,
                                                                    box)
        return src, label


class DetRandomPadAug(DetAugmenter):
    """Zoom-out: place the image on a larger mean-filled canvas and
    shrink the boxes accordingly (reference: detection.py
    DetRandomPadAug)."""

    def __init__(self, area_range=(1.0, 3.0), aspect_ratio_range=(0.75,
                 1.33), fill=127, p=0.5):
        self.area_range = area_range
        self.aspect_ratio_range = aspect_ratio_range
        self.fill = fill
        self.p = p

    def __call__(self, src, label):
        if _pyrandom.random() > self.p:
            return src, label
        arr = _to_np(src)
        h, w = arr.shape[:2]
        area = _pyrandom.uniform(*self.area_range)
        ratio = _pyrandom.uniform(*self.aspect_ratio_range)
        nw = max(w, int(w * _np.sqrt(area * ratio)))
        nh = max(h, int(h * _np.sqrt(area / ratio)))
        x0 = _pyrandom.randint(0, nw - w)
        y0 = _pyrandom.randint(0, nh - h)
        canvas = _np.full((nh, nw) + arr.shape[2:], self.fill, arr.dtype)
        canvas[y0:y0 + h, x0:x0 + w] = arr
        # pad box in ORIGINAL normalized coords is the inverse crop
        box = (-x0 / w, -y0 / h, (nw - x0) / w, (nh - y0) / h)
        return _wrap_like(src, canvas), _update_det_labels(label, box)


class DetForceResizeAug(DetAugmenter):
    """Resize to exact (w, h); normalized labels are resize-invariant."""

    def __init__(self, size, interp=2):
        self.size = size
        self.interp = interp

    def __call__(self, src, label):
        return imresize(src, self.size[0], self.size[1],
                        self.interp), label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0,
                       min_object_covered=0.3, area_range=(0.3, 3.0),
                       aspect_ratio_range=(0.75, 1.33), **kwargs):
    """Standard detection pipeline (reference: detection.py
    CreateDetAugmenter): photometric borrow-augs + geometric det-augs +
    final force-resize to the network input."""
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(ResizeAug(resize)))
    if rand_crop > 0:
        auglist.append(DetRandomCropAug(
            min_object_covered=min_object_covered,
            aspect_ratio_range=aspect_ratio_range,
            area_range=(area_range[0], min(1.0, area_range[1])),
            p=rand_crop))
    if rand_pad > 0:
        auglist.append(DetRandomPadAug(
            area_range=(max(1.0, area_range[0]), max(1.0, area_range[1])),
            aspect_ratio_range=aspect_ratio_range, p=rand_pad))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(
            ColorJitterAug(brightness, contrast, saturation)))
    auglist.append(DetForceResizeAug((data_shape[2], data_shape[1])))
    if mean is not None:
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator: batches of (data, label (B, max_obj, 5))
    with joint image/box augmentation (reference: detection.py
    ImageDetIter over src/io/image_det_aug_default.cc).

    Accepted label layouts per image: flat [cls, x1, y1, x2, y2] * k,
    or the reference's packed header [header_width, obj_width,
    (header...), objects...].
    """

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", imglist=None,
                 shuffle=False, aug_list=None, max_objects=None,
                 dtype="float32", **kwargs):
        # iterator-level kwargs go to ImageIter (distributed sharding
        # etc.); the rest parameterize the detection augmenter pipeline
        iter_kwargs = {k: kwargs.pop(k) for k in
                       ("num_parts", "part_index") if k in kwargs}
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape, **kwargs)
        super().__init__(batch_size, data_shape, label_width=1,
                         path_imgrec=path_imgrec, path_imglist=path_imglist,
                         path_root=path_root, imglist=imglist,
                         shuffle=shuffle, aug_list=[], dtype=dtype,
                         **iter_kwargs)
        from .io import DataDesc
        self.det_auglist = aug_list
        if max_objects is None:
            max_objects = self._scan_max_objects()
        self.max_objects = int(max_objects)
        self.provide_label = [DataDesc(
            "label", (batch_size, self.max_objects, 5), dtype)]

    @staticmethod
    def _parse_det_label(raw):
        raw = _np.asarray(raw, dtype=_np.float32).ravel()
        if raw.size >= 2 and raw[0] >= 2 and raw[1] >= 5 and \
                (raw.size - raw[0]) % raw[1] == 0 and raw[0] != 5:
            hw, ow = int(raw[0]), int(raw[1])
            objs = raw[hw:].reshape(-1, ow)[:, :5]
        else:
            objs = raw.reshape(-1, 5)
        return objs

    def _scan_max_objects(self):
        if self.imglist is not None:
            return max(len(self._parse_det_label(lbl))
                       for lbl, _ in self.imglist.values()) or 1
        return 16    # unindexed .rec streams: bounded default

    def next(self):
        from .io import DataBatch
        c, h, w = self.data_shape
        data = _np.zeros((self.batch_size, c, h, w), dtype=self.dtype)
        labels = _np.full((self.batch_size, self.max_objects, 5), -1.0,
                          dtype=_np.float32)
        i = 0
        try:
            while i < self.batch_size:
                raw_label, s = self.next_sample()
                img = imdecode(s, 1 if c == 3 else 0, to_ndarray=False)
                objs = self._parse_det_label(raw_label)
                padded = _np.full((self.max_objects, 5), -1.0, _np.float32)
                padded[:len(objs)] = objs[:self.max_objects]
                for aug in self.det_auglist:
                    img, padded = aug(img, padded)
                arr = _to_np(img)
                if arr.ndim == 3:
                    arr = arr.transpose(2, 0, 1)
                data[i] = arr
                labels[i] = padded
                i += 1
        except StopIteration:
            if i == 0:
                raise
        return DataBatch(data=[array(data)], label=[array(labels)],
                        pad=self.batch_size - i)
