"""Backpressure-aware HTTP frontend for the inference engine.

Stdlib-only (http.server), like ``telemetry.serve`` — safe to run in any
deployment without adding dependencies. One threaded server mounts:

* ``POST /predict`` — JSON in, JSON out (below). Maps engine outcomes
  onto the status codes a load balancer expects: **503** on admission
  rejection (full queue / draining; ``Retry-After`` set), **504** on
  deadline expiry, **400** on malformed input.
* ``POST /generate`` — autoregressive decode through a
  :class:`~mxnet_tpu.serve.decode.DecodeEngine` (continuous batching +
  paged KV cache). Streams tokens as newline-delimited JSON chunks
  (``Transfer-Encoding: chunked``) as the scheduler produces them, or
  returns one JSON body with ``"stream": false``. Same status mapping;
  a 503 names whether the queue or the KV page pool is the saturated
  resource.
* ``GET /healthz`` — ``ok`` once every batch bucket is compiled
  (:meth:`InferenceEngine.warmup`) and the workers are live
  (:meth:`InferenceEngine.start`), **503** ``warming`` before that; a
  rollout gate that keeps compile latency out of production traffic.
* ``GET /metrics`` — the shared telemetry registry in Prometheus text
  format (same payload as ``telemetry.serve``; scrape either).
* ``GET /programs`` — the compiled-program registry listing with
  forensics availability; ``?key=<fingerprint>`` returns that
  program's per-fusion forensics summary (``forensics.py``; also
  mounted on ``telemetry.serve``).

``/predict`` request body::

    {"inputs": {"data": [[...], ...]}, "timeout_ms": 500}

or, for single-input models, the bare array ``{"data": [[...], ...]}``
/ ``[[...], ...]``. Response::

    {"outputs": [[[...], ...]], "rows": N}

``/generate`` request body::

    {"prompt": [1, 5, 9], "max_new_tokens": 32, "timeout_ms": 30000,
     "stream": true, "stop_token": 2}

Streaming response: one ``{"token": t}`` JSON line per generated token,
then ``{"done": true, "n": N}`` (or ``{"error": ..., "code": ...}`` if
the session dies mid-stream — the status line was already sent).
Non-streaming: ``{"tokens": [...], "n": N}``.

``target`` is an :class:`InferenceEngine` or a
:class:`serve.ModelRegistry` (hot-swap safe) — anything with
``submit(feed, timeout_ms)`` and ``ready`` — or None for a decode-only
frontend. ``decode`` is a :class:`~mxnet_tpu.serve.decode.DecodeEngine`
(defaults to ``target.decode_engine()`` when the target is a registry
with one attached).
"""
from __future__ import annotations

import json
import re
import threading

from ..base import MXNetError
from .. import telemetry as _tm
from .. import tracing as _tr
from .engine import DeadlineExceededError, EngineClosedError, QueueFullError

__all__ = ["serve_http", "ServeHTTPServer"]

# accepted X-Request-Id shape; anything else gets a fresh id (the
# header is echoed verbatim into responses and trace ids — never let a
# client smuggle header-splitting bytes through it)
_REQ_ID_RE = re.compile(r"^[A-Za-z0-9._\-]{1,64}$")


class ServeHTTPServer(object):
    """Handle on a running serving frontend (from :func:`serve_http`)."""

    def __init__(self, httpd, thread, target, decode=None):
        self._httpd = httpd
        self._thread = thread
        self.target = target
        self.decode = decode
        self.port = httpd.server_address[1]
        self.url = "http://%s:%d" % (httpd.server_address[0], self.port)

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    stop = close

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _parse_body(target, body):
    """(feed, timeout_ms) from a request body; raises MXNetError on
    malformed input (mapped to 400)."""
    try:
        payload = json.loads(body.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise MXNetError("request body is not valid JSON: %s" % e)
    timeout_ms = None
    if isinstance(payload, dict) and "inputs" in payload:
        timeout_ms = payload.get("timeout_ms")
        feed = payload["inputs"]
        if not isinstance(feed, dict):
            raise MXNetError('"inputs" must be an object of '
                             'name -> array')
    else:
        feed = payload                   # bare array / {input: array}
    input_names = target.engine()._input_names
    if not isinstance(feed, dict):
        if len(input_names) != 1:
            raise MXNetError("model has inputs %s; post "
                             '{"inputs": {...}}' % input_names)
        feed = {input_names[0]: feed}
    unknown = [k for k in feed if k not in input_names]
    if unknown:
        raise MXNetError("unknown inputs %s (model has %s)"
                         % (unknown, input_names))
    return feed, timeout_ms


def _parse_generate_body(body):
    """(prompt, kwargs, stream) from a /generate request body; raises
    MXNetError on malformed input (mapped to 400)."""
    try:
        payload = json.loads(body.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise MXNetError("request body is not valid JSON: %s" % e)
    if isinstance(payload, list):
        payload = {"prompt": payload}
    if not isinstance(payload, dict) or "prompt" not in payload:
        raise MXNetError('post {"prompt": [token ids], ...}')
    prompt = payload["prompt"]
    if not isinstance(prompt, list) or not prompt \
            or not all(isinstance(t, int) for t in prompt):
        raise MXNetError('"prompt" must be a non-empty list of int '
                         'token ids')
    kwargs = {}
    for key in ("max_new_tokens", "timeout_ms", "stop_token"):
        if payload.get(key) is not None:
            val = payload[key]
            if not isinstance(val, (int, float)):
                raise MXNetError('"%s" must be a number' % key)
            kwargs[key] = val
    return prompt, kwargs, bool(payload.get("stream", True))


def serve_http(target, port=0, addr="127.0.0.1", decode=None):
    """Start the serving frontend; returns a :class:`ServeHTTPServer`
    (``port=0`` picks a free port — read it from the handle)."""
    import http.server

    if decode is None and target is not None:
        getter = getattr(target, "decode_engine", None)
        if callable(getter):
            decode = getter()
    if target is None and decode is None:
        raise MXNetError("serve_http needs a predict target and/or a "
                         "decode engine")

    class _Handler(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        _rid = None
        _tsink = None                    # router-hop span collector
        _tspan = None                    # open http.request span

        def _reply(self, code, payload, ctype="application/json",
                   headers=()):
            body = (json.dumps(payload).encode() + b"\n"
                    if not isinstance(payload, bytes) else payload)
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            if self._rid is not None:
                # every outcome — 200, 503, 504, 400 — echoes the
                # request id, so a client log line links to /traces
                self.send_header("X-Request-Id", self._rid)
            if self._tsink is not None and self._tspan is not None \
                    and self._tspan.ctx is not None:
                # routed request: ship this hop's spans back in-band so
                # the router can graft them into ITS trace. The
                # http.request span is still open (it closes after the
                # reply), so synthesize it now under its real span_id —
                # the buffer dedups on span_id, suppressing the real
                # close. The clock pair lets graft() rebase our
                # perf_counter epoch onto the router's.
                sp = self._tspan
                _tr.record_span(sp.name, sp.ctx, sp.t0, _tr._monotonic(),
                                attrs=dict(sp.attrs),
                                span_id=sp.ctx.span_id,
                                parent_id=sp.parent_id)
                self.send_header("X-Trace-Spans", json.dumps(
                    {"spans": self._tsink[:64],
                     "clock": [_tr._PROC_TOKEN, _tr._monotonic()]}))
            for k, v in headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _deadline_ms(self, timeout_ms):
            """Fold the router's remaining-deadline budget
            (``X-Deadline-Ms``) into the body timeout: the replica
            must give up no later than the router will, so replica-side
            504 accounting matches the router's view instead of
            burning a worker on an answer nobody is waiting for."""
            hdr = self.headers.get("X-Deadline-Ms")
            if hdr is None:
                return timeout_ms
            try:
                # the engine reads timeout <= 0 as "no deadline"; an
                # exhausted router budget must mean "already expired"
                budget = max(1e-9, float(hdr))
            except ValueError:
                return timeout_ms
            if timeout_ms is None or float(timeout_ms) <= 0:
                return budget
            return min(float(timeout_ms), budget)

        def do_GET(self):
            self._rid = None             # keep-alive: no stale echo
            self._tsink = None
            self._tspan = None
            path, _, query = self.path.partition("?")
            if path == "/metrics":
                self._reply(200, _tm.render_prometheus().encode(),
                            ctype="text/plain; version=0.0.4; "
                                  "charset=utf-8")
            elif path == "/healthz":
                ok = ((target is None or target.ready)
                      and (decode is None or decode.ready))
                if ok:
                    self._reply(200, b"ok\n",
                                ctype="text/plain; charset=utf-8")
                else:
                    self._reply(503, b"warming\n",
                                ctype="text/plain; charset=utf-8")
            elif path == "/traces":
                code, payload = _tr.traces_endpoint(query)
                self._reply(code, payload)
            elif path == "/alerts":
                from .. import health as _hl
                code, payload = _hl.alerts_endpoint(query)
                self._reply(code, payload)
            elif path == "/programs":
                from .. import forensics as _fx
                code, payload = _fx.programs_endpoint(query)
                self._reply(code, payload)
            elif path == "/cluster":
                from .. import observatory as _ob
                code, payload = _ob.cluster_endpoint(query)
                self._reply(code, payload)
            else:
                self._reply(404, {"error": "not found"})

        def do_POST(self):
            self._rid = None             # keep-alive: no stale echo
            self._tsink = None
            self._tspan = None
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length)   # always drain: HTTP/1.1
            path = self.path.split("?")[0]
            if path == "/predict" and target is not None:
                handler = self._predict
            elif path == "/generate" and decode is not None:
                handler = self._generate
            else:
                # keep-alive reuses the socket; an unread body would be
                # parsed as the next request line
                self._reply(404, {"error": "not found"})
                return
            # accept the caller's X-Request-Id as the trace id (echoed
            # either way, sampled or not); mint one otherwise
            rid = self.headers.get("X-Request-Id", "")
            if not _REQ_ID_RE.match(rid):
                rid = _tr.new_trace_id()
            self._rid = rid
            # a routed request carries the router's forward-span wire
            # context: join THAT trace (http.request becomes a child of
            # router.forward) and tee every span of this hop into a
            # sink shipped back via the X-Trace-Spans response header
            wctx = None
            wire_hdr = self.headers.get("X-Trace-Context")
            if wire_hdr:
                try:
                    sink = []
                    wctx = _tr.from_wire(json.loads(wire_hdr), sink)
                except (ValueError, TypeError, KeyError):
                    wctx = None
                if wctx is not None:
                    self._tsink = sink
            with _tr.start_span("http.request", ctx=wctx, trace_id=rid,
                                attrs={"path": path}) as span:
                self._tspan = span
                handler(body, span)

        def _predict(self, body, span):
            try:
                feed, timeout_ms = _parse_body(target, body)
                timeout_ms = self._deadline_ms(timeout_ms)
                req = target.submit(feed, timeout_ms, ctx=span.ctx)
            except (QueueFullError, EngineClosedError) as e:
                span.set_attr("http_status", 503)
                _tr.mark_error(e, ctx=span.ctx)
                self._reply(503, {"error": str(e)},
                            headers=(("Retry-After", "1"),))
                return
            except (MXNetError, ValueError, TypeError) as e:
                # ValueError/TypeError cover np.asarray on ragged input
                # and a non-numeric timeout_ms — still a client error
                span.set_attr("http_status", 400)
                self._reply(400, {"error": str(e)})
                return

            try:
                outputs = req.result()
            except DeadlineExceededError as e:
                span.set_attr("http_status", 504)
                _tr.mark_error(e, ctx=span.ctx)
                self._reply(504, {"error": str(e)})
                return
            except EngineClosedError as e:
                span.set_attr("http_status", 503)
                _tr.mark_error(e, ctx=span.ctx)
                self._reply(503, {"error": str(e)},
                            headers=(("Retry-After", "1"),))
                return
            except MXNetError as e:
                span.set_attr("http_status", 500)
                _tr.mark_error(e, ctx=span.ctx)
                self._reply(500, {"error": str(e)})
                return
            try:
                # bare NaN/Infinity literals are invalid JSON to strict
                # (RFC 8259) parsers: surface a 500, not a 200 the
                # client cannot parse
                body = json.dumps(
                    {"outputs": [o.tolist() for o in outputs],
                     "rows": req.rows}, allow_nan=False).encode() + b"\n"
            except ValueError:
                span.set_attr("http_status", 500)
                self._reply(500, {"error": "model output contains "
                                           "non-finite values"})
                return
            span.set_attr("rows", req.rows)
            self._reply(200, body)

        def _chunk(self, obj):
            """One chunked-transfer frame holding one JSON line."""
            data = json.dumps(obj).encode() + b"\n"
            self.wfile.write(b"%X\r\n" % len(data) + data + b"\r\n")
            self.wfile.flush()

        def _generate(self, body, span):
            try:
                prompt, kwargs, stream = _parse_generate_body(body)
                budget = self._deadline_ms(kwargs.get("timeout_ms"))
                if budget is not None:
                    kwargs["timeout_ms"] = budget
                sess = decode.submit(prompt, ctx=span.ctx, **kwargs)
            except (QueueFullError, EngineClosedError) as e:
                # PagePoolExhausted subclasses QueueFullError: same 503
                # path, page-exhaustion named in the error detail
                span.set_attr("http_status", 503)
                _tr.mark_error(e, ctx=span.ctx)
                self._reply(503, {"error": str(e)},
                            headers=(("Retry-After", "1"),))
                return
            except (MXNetError, ValueError, TypeError) as e:
                span.set_attr("http_status", 400)
                self._reply(400, {"error": str(e)})
                return

            if not stream:
                try:
                    toks = sess.result()
                except DeadlineExceededError as e:
                    span.set_attr("http_status", 504)
                    _tr.mark_error(e, ctx=span.ctx)
                    self._reply(504, {"error": str(e)})
                    return
                except MXNetError as e:
                    span.set_attr("http_status", 500)
                    _tr.mark_error(e, ctx=span.ctx)
                    self._reply(500, {"error": str(e)})
                    return
                span.set_attr("tokens", len(toks))
                self._reply(200, {"tokens": toks, "n": len(toks)})
                return

            # streaming: the status line goes out before the first
            # token exists, so mid-stream failures ride an in-band
            # {"error": ...} line (the span still records the status)
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            if self._rid is not None:
                self.send_header("X-Request-Id", self._rid)
            self.end_headers()
            n = 0
            try:
                try:
                    for tok in sess.tokens():
                        self._chunk({"token": tok})
                        n += 1
                    self._chunk({"done": True, "n": n})
                except DeadlineExceededError as e:
                    span.set_attr("http_status", 504)
                    _tr.mark_error(e, ctx=span.ctx)
                    self._chunk({"error": str(e), "code": 504})
                except MXNetError as e:
                    span.set_attr("http_status", 500)
                    _tr.mark_error(e, ctx=span.ctx)
                    self._chunk({"error": str(e), "code": 500})
                self.wfile.write(b"0\r\n\r\n")
                self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                # client hung up mid-stream: cancel the session so its
                # slot and page reservation free NOW, not at deadline
                decode.cancel(sess, "client disconnected")
            span.set_attr("tokens", n)

        def log_message(self, *args):    # no stderr chatter per request
            pass

    httpd = http.server.ThreadingHTTPServer((addr, port), _Handler)
    httpd.daemon_threads = True
    thread = threading.Thread(target=httpd.serve_forever,
                              name="mxnet-serve-http", daemon=True)
    thread.start()
    # publish this mount as the process's scrapable endpoint (elastic
    # heartbeats and the cluster observatory read it)
    _tm.set_server_endpoint(addr, httpd.server_address[1])
    return ServeHTTPServer(httpd, thread, target, decode)
