"""Symbolic ResNet v1/v2 (reference: example/image-classification/symbols/
resnet.py topology; He et al. / "Identity Mappings" variant).

Built TPU-first: NCHW symbols lower through jit to XLA, which picks TPU
conv layouts itself; BatchNorm uses the framework's functional aux-state
update. The unit structure matches the reference benchmark topology so
images/sec is comparable to docs/faq/perf.md:205-214.
"""
from __future__ import annotations

from .. import symbol as sym

__all__ = ["get_symbol", "resnet50_symbol"]


def _residual_unit_v2(data, num_filter, stride, dim_match, name,
                      bottle_neck=True, bn_mom=0.9):
    """Pre-activation residual unit (resnet v2)."""
    bn1 = sym.BatchNorm(data, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                        name=name + "_bn1")
    act1 = sym.Activation(bn1, act_type="relu", name=name + "_relu1")
    if bottle_neck:
        conv1 = sym.Convolution(act1, num_filter=num_filter // 4,
                                kernel=(1, 1), stride=(1, 1), pad=(0, 0),
                                no_bias=True, name=name + "_conv1")
        bn2 = sym.BatchNorm(conv1, fix_gamma=False, eps=2e-5,
                            momentum=bn_mom, name=name + "_bn2")
        act2 = sym.Activation(bn2, act_type="relu", name=name + "_relu2")
        conv2 = sym.Convolution(act2, num_filter=num_filter // 4,
                                kernel=(3, 3), stride=stride, pad=(1, 1),
                                no_bias=True, name=name + "_conv2")
        bn3 = sym.BatchNorm(conv2, fix_gamma=False, eps=2e-5,
                            momentum=bn_mom, name=name + "_bn3")
        act3 = sym.Activation(bn3, act_type="relu", name=name + "_relu3")
        conv3 = sym.Convolution(act3, num_filter=num_filter, kernel=(1, 1),
                                stride=(1, 1), pad=(0, 0), no_bias=True,
                                name=name + "_conv3")
        body = conv3
    else:
        conv1 = sym.Convolution(act1, num_filter=num_filter, kernel=(3, 3),
                                stride=stride, pad=(1, 1), no_bias=True,
                                name=name + "_conv1")
        bn2 = sym.BatchNorm(conv1, fix_gamma=False, eps=2e-5,
                            momentum=bn_mom, name=name + "_bn2")
        act2 = sym.Activation(bn2, act_type="relu", name=name + "_relu2")
        conv2 = sym.Convolution(act2, num_filter=num_filter, kernel=(3, 3),
                                stride=(1, 1), pad=(1, 1), no_bias=True,
                                name=name + "_conv2")
        body = conv2
    if dim_match:
        shortcut = data
    else:
        shortcut = sym.Convolution(act1, num_filter=num_filter,
                                   kernel=(1, 1), stride=stride,
                                   no_bias=True, name=name + "_sc")
    return body + shortcut


def get_symbol(num_classes=1000, num_layers=50, image_shape=(3, 224, 224),
               bottle_neck=None, bn_mom=0.9):
    """Build a ResNet symbol (reference: symbols/resnet.py get_symbol).

    Supported depths: 18, 34, 50, 101, 152 (and 20/56/110 for CIFAR
    shapes)."""
    (nchannel, height, width) = image_shape
    if height <= 32:
        num_stages = 3
        if (num_layers - 2) % 9 == 0 and num_layers >= 164:
            per_unit = [(num_layers - 2) // 9]
            filter_list = [16, 64, 128, 256]
            use_bottle = True
        elif (num_layers - 2) % 6 == 0 and num_layers < 164:
            per_unit = [(num_layers - 2) // 6]
            filter_list = [16, 16, 32, 64]
            use_bottle = False
        else:
            raise ValueError("no experiments done on num_layers %d"
                             % num_layers)
        units = per_unit * num_stages
    else:
        if num_layers >= 50:
            filter_list = [64, 256, 512, 1024, 2048]
            use_bottle = True
        else:
            filter_list = [64, 64, 128, 256, 512]
            use_bottle = False
        num_stages = 4
        stage_units = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                       101: [3, 4, 23, 3], 152: [3, 8, 36, 3],
                       200: [3, 24, 36, 3]}
        if num_layers not in stage_units:
            raise ValueError("no experiments done on num_layers %d"
                             % num_layers)
        units = stage_units[num_layers]
    if bottle_neck is not None:
        use_bottle = bottle_neck

    data = sym.Variable("data")
    body = sym.BatchNorm(data, fix_gamma=True, eps=2e-5, momentum=bn_mom,
                         name="bn_data")
    if height <= 32:
        body = sym.Convolution(body, num_filter=filter_list[0],
                               kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                               no_bias=True, name="conv0")
    else:
        body = sym.Convolution(body, num_filter=filter_list[0],
                               kernel=(7, 7), stride=(2, 2), pad=(3, 3),
                               no_bias=True, name="conv0")
        body = sym.BatchNorm(body, fix_gamma=False, eps=2e-5,
                             momentum=bn_mom, name="bn0")
        body = sym.Activation(body, act_type="relu", name="relu0")
        body = sym.Pooling(body, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                           pool_type="max", name="pool0")

    for i in range(num_stages):
        stride = (1, 1) if i == 0 and height > 32 else (2, 2) if i > 0 \
            else (1, 1)
        body = _residual_unit_v2(body, filter_list[i + 1], stride, False,
                                 name="stage%d_unit1" % (i + 1),
                                 bottle_neck=use_bottle, bn_mom=bn_mom)
        for j in range(units[i] - 1):
            body = _residual_unit_v2(body, filter_list[i + 1], (1, 1), True,
                                     name="stage%d_unit%d" % (i + 1, j + 2),
                                     bottle_neck=use_bottle, bn_mom=bn_mom)

    bn1 = sym.BatchNorm(body, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                        name="bn1")
    relu1 = sym.Activation(bn1, act_type="relu", name="relu1")
    pool1 = sym.Pooling(relu1, global_pool=True, kernel=(7, 7),
                        pool_type="avg", name="pool1")
    flat = sym.Flatten(pool1)
    fc1 = sym.FullyConnected(flat, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(fc1, name="softmax")


def resnet50_symbol(num_classes=1000):
    return get_symbol(num_classes=num_classes, num_layers=50)
