"""Serving helper backing the native C predict ABI.

Reference: include/mxnet/c_predict_api.h + src/c_api/c_predict_api.cc
(MXPredCreate/SetInput/Forward/GetOutput on a symbol json + params
blob). The native layer (src/native/c_predict_api.cc) embeds CPython
and drives this module; keeping the marshalling here means the C side
is a thin, stable ABI while the compute path stays XLA.

Params blob format = mx.nd.save (zip of NPY entries, the framework's
checkpoint format); arg/aux entries use the reference's ``arg:name`` /
``aux:name`` prefixes (falling back to raw names).
"""
from __future__ import annotations

import os
import tempfile

import numpy as _np

from .base import MXNetError
from . import telemetry as _tm

__all__ = ["Predictor"]


class Predictor(object):
    """One bound inference executor (reference: c_predict_api.cc
    Predictor struct)."""

    def __init__(self, symbol_json, param_bytes, dev_type=1, dev_id=0,
                 input_shapes=None):
        from .symbol.symbol import load_json
        from .ndarray import utils as _utils
        from . import context as _ctx
        sym = load_json(symbol_json)
        fd, tmp = tempfile.mkstemp(suffix=".params")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(param_bytes)
            saved = _utils.load(tmp)
        finally:
            os.unlink(tmp)
        if not isinstance(saved, dict):
            raise MXNetError("param blob must be a named-tensor dict")
        arg_params, aux_params = {}, {}
        for k, v in saved.items():
            if k.startswith("arg:"):
                arg_params[k[4:]] = v
            elif k.startswith("aux:"):
                aux_params[k[4:]] = v
            else:
                arg_params[k] = v
        ctx = _ctx.tpu(dev_id) if dev_type == 2 else _ctx.cpu(dev_id)
        shapes = dict(input_shapes or {})
        self._sym = sym
        self._arg_params = arg_params
        self._aux_params = aux_params
        self._ctx = ctx
        self._exe = sym.simple_bind(ctx=ctx, grad_req="null", **shapes)
        for k, v in arg_params.items():
            if k in self._exe.arg_dict:
                self._exe.arg_dict[k][:] = v
        for k, v in aux_params.items():
            if k in self._exe.aux_dict:
                self._exe.aux_dict[k][:] = v
        self._input_names = list(shapes)
        self._outputs = None

    def set_input(self, key, data_bytes):
        """data_bytes: raw float32 little-endian in the bound shape."""
        if key not in self._exe.arg_dict:
            raise MXNetError("unknown input %r" % key)
        arr = self._exe.arg_dict[key]
        flat = _np.frombuffer(data_bytes, dtype="<f4")
        if flat.size != int(_np.prod(arr.shape)):
            raise MXNetError("input %r size mismatch: got %d want %d"
                             % (key, flat.size, int(_np.prod(arr.shape))))
        from .ndarray.ndarray import array
        arr[:] = array(flat.reshape(arr.shape))

    def forward(self):
        t0 = _tm.monotonic() if _tm._enabled else None
        self._outputs = self._exe.forward(is_train=False)
        if t0 is not None:
            _tm.counter("serving/requests_total",
                        "Predictor forward calls").inc()
            _tm.histogram("serving/request_seconds",
                          "Predictor forward latency (host-side)").observe(
                _tm.monotonic() - t0)

    def serve_metrics(self, port=0, addr="127.0.0.1"):
        """Start the telemetry ``/metrics`` + ``/healthz`` endpoint next
        to this predictor (inference deployments scrape it; see
        docs/observability.md). Returns the :class:`TelemetryServer`
        handle — keep a reference and ``close()`` it on shutdown."""
        from . import telemetry
        return telemetry.serve(port=port, addr=addr)

    def num_outputs(self):
        self._ensure_forward()
        return len(self._outputs)

    def get_output_shape(self, index):
        self._ensure_forward()
        return tuple(int(d) for d in self._outputs[index].shape)

    def get_output(self, index):
        """Returns raw float32 bytes of output ``index``."""
        self._ensure_forward()
        out = self._outputs[index].asnumpy().astype("<f4", copy=False)
        return out.tobytes()

    def _ensure_forward(self):
        if self._outputs is None:
            raise MXNetError("call forward() first")

    def reshape(self, input_shapes):
        """Rebind for new input shapes (reference: MXPredReshape). The
        graph program is shape-specialized by the jit cache; only the
        argument buffers are reallocated."""
        new = Predictor.__new__(Predictor)
        new._sym = self._sym
        new._arg_params = self._arg_params
        new._aux_params = self._aux_params
        new._ctx = self._ctx
        new._exe = self._sym.simple_bind(ctx=self._ctx, grad_req="null",
                                         **dict(input_shapes))
        for k, v in self._arg_params.items():
            if k in new._exe.arg_dict:
                new._exe.arg_dict[k][:] = v
        for k, v in self._aux_params.items():
            if k in new._exe.aux_dict:
                new._exe.aux_dict[k][:] = v
        new._input_names = list(input_shapes)
        new._outputs = None
        return new
