"""Custom Python operators: CustomOp / CustomOpProp / register.

Reference: python/mxnet/operator.py:76-191 (CustomOp, CustomOpProp,
register) and src/operator/custom/custom-inl.h:50-173 (the C++ bridge
that runs Python callbacks off the engine threads).

TPU-native design: two execution paths share the same user API —

* **eager** (``nd.Custom``): the op runs as a host function between
  device ops, wrapped in :class:`autograd.Function` so its
  ``backward`` joins the tape like any other op.
* **symbolic/jit** (``sym.Custom`` / hybridized graphs): the op lowers
  to ``jax.pure_callback`` (host callback inside the compiled XLA
  program — the analog of the reference's dedicated custom-op worker
  thread) with a ``jax.custom_vjp`` whose backward is itself a host
  callback into the user's ``backward``.

``req`` write modes and ``assign`` mirror the reference semantics.
"""
from __future__ import annotations

import functools

import numpy as _np

from .base import MXNetError

__all__ = ["CustomOp", "CustomOpProp", "register", "get_prop"]

_CUSTOM_REGISTRY = {}


class CustomOp(object):
    """Base class for user ops (reference: operator.py CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Write ``src`` into ``dst`` honoring the request type."""
        if req in ("null", None):
            return
        if req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] = dst + src
        else:
            raise MXNetError("unknown req %r" % req)


class CustomOpProp(object):
    """Op metadata provider (reference: operator.py CustomOpProp)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        raise NotImplementedError

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps


def register(reg_name):
    """Decorator registering a CustomOpProp subclass under ``reg_name``
    (reference: operator.py register → MXCustomOpRegister)."""
    def _wrap(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register expects a CustomOpProp subclass")
        _CUSTOM_REGISTRY[reg_name] = prop_cls
        return prop_cls
    return _wrap


def get_prop(op_type, **kwargs):
    try:
        cls = _CUSTOM_REGISTRY[op_type]
    except KeyError:
        raise MXNetError("custom op %r is not registered" % op_type) \
            from None
    return cls(**kwargs)


# ---------------------------------------------------------------------------
# eager path: nd.Custom
# ---------------------------------------------------------------------------

def custom_ndarray(*inputs, op_type=None, **kwargs):
    """Eager invocation (generated as ``nd.Custom`` in the reference)."""
    from .ndarray.ndarray import NDArray, zeros
    from . import autograd
    if op_type is None:
        raise MXNetError("nd.Custom requires op_type=")
    prop = get_prop(op_type, **kwargs)
    in_shapes = [tuple(x.shape) for x in inputs]
    _, out_shapes, _ = prop.infer_shape(list(in_shapes))
    _, out_types, _ = prop.infer_type([x.dtype for x in inputs])
    ctx = inputs[0].context if inputs else None
    op = prop.create_operator(ctx, in_shapes,
                              [x.dtype for x in inputs])
    n_out = len(out_shapes)
    # captured BEFORE Function.__call__ enters pause(): inside forward,
    # is_recording() is always False
    training = autograd.is_recording()

    class _Fn(autograd.Function):
        def forward(self, *ins):
            outs = [zeros(s, dtype=t)
                    for s, t in zip(out_shapes, out_types)]
            op.forward(is_train=training,
                       req=["write"] * n_out, in_data=list(ins),
                       out_data=outs, aux=[])
            # keep the real outputs: backward implementations read
            # out_data (e.g. sigmoid grad = g * out * (1 - out))
            self._fwd_outs = outs
            return outs[0] if n_out == 1 else tuple(outs)

        def backward(self, *out_grads):
            in_grads = [zeros(s) for s in in_shapes]
            op.backward(req=["write"] * len(inputs),
                        out_grad=list(out_grads), in_data=list(inputs),
                        out_data=self._fwd_outs, in_grad=in_grads, aux=[])
            return in_grads[0] if len(in_grads) == 1 else tuple(in_grads)

    return _Fn()(*inputs)


# ---------------------------------------------------------------------------
# jit/symbolic path: host callbacks inside the compiled program
# ---------------------------------------------------------------------------

def make_custom_jax_fn(op_type, **kwargs):
    """Build a jittable jax function for the custom op: pure_callback
    forward + custom_vjp whose backward is another host callback (the
    capability analog of custom-inl.h's async python bridge)."""
    import jax
    import jax.numpy as jnp

    prop = get_prop(op_type, **kwargs)

    def _host_forward(*arrays):
        from .ndarray.ndarray import NDArray, zeros
        ins = [NDArray(jnp.asarray(a)) for a in arrays]
        in_shapes = [tuple(a.shape) for a in arrays]
        _, out_shapes, _ = prop.infer_shape(list(in_shapes))
        _, out_types, _ = prop.infer_type([a.dtype for a in arrays])
        op = prop.create_operator(None, in_shapes,
                                  [a.dtype for a in arrays])
        outs = [zeros(s, dtype=t) for s, t in zip(out_shapes, out_types)]
        op.forward(is_train=True, req=["write"] * len(outs),
                   in_data=ins, out_data=outs, aux=[])
        return tuple(_np.asarray(o.asnumpy()) for o in outs)

    def _host_backward(n_in, *arrays_and_cts):
        from .ndarray.ndarray import NDArray, zeros
        ins = [NDArray(jnp.asarray(a)) for a in arrays_and_cts[:n_in]]
        cts = [NDArray(jnp.asarray(a)) for a in arrays_and_cts[n_in:]]
        in_shapes = [tuple(a.shape) for a in ins]
        _, out_shapes, _ = prop.infer_shape(list(in_shapes))
        op = prop.create_operator(None, in_shapes,
                                  [a.dtype for a in ins])
        outs = [zeros(s) for s in out_shapes]
        op.forward(is_train=True, req=["write"] * len(outs),
                   in_data=ins, out_data=outs, aux=[])
        grads = [zeros(s) for s in in_shapes]
        op.backward(req=["write"] * n_in, out_grad=cts, in_data=ins,
                    out_data=outs, in_grad=grads, aux=[])
        return tuple(_np.asarray(g.asnumpy()) for g in grads)

    @jax.custom_vjp
    def fn(*arrays):
        in_shapes = [tuple(a.shape) for a in arrays]
        _, out_shapes, _ = prop.infer_shape(list(in_shapes))
        _, out_types, _ = prop.infer_type(
            [_np.dtype(a.dtype) for a in arrays])
        result_shapes = tuple(
            jax.ShapeDtypeStruct(s, _np.dtype(t))
            for s, t in zip(out_shapes, out_types))
        out = jax.pure_callback(_host_forward, result_shapes, *arrays)
        return out[0] if len(out) == 1 else tuple(out)

    def fn_fwd(*arrays):
        return fn(*arrays), arrays

    def fn_bwd(arrays, cts):
        cts_t = cts if isinstance(cts, (tuple, list)) else (cts,)
        grad_shapes = tuple(jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)
                            for a in arrays)
        cb = functools.partial(_host_backward, len(arrays))
        grads = jax.pure_callback(cb, grad_shapes,
                                  *(tuple(arrays) + tuple(cts_t)))
        return tuple(grads)

    fn.defvjp(fn_fwd, fn_bwd)
    return fn


# ---------------------------------------------------------------------------
# op-registry hook: makes ``Custom`` usable from nd, symbol graphs, and
# hybridized blocks (evaluated inside jit via the callbacks above)
# ---------------------------------------------------------------------------

def _custom_op_fn(*arrays, op_type=None, **kwargs):
    """Custom python op as a graph node (reference: sym.Custom)."""
    if op_type is None:
        raise MXNetError("Custom requires op_type=")
    return make_custom_jax_fn(op_type, **kwargs)(*arrays)


def _custom_num_outputs(attrs):
    prop = get_prop(attrs["op_type"],
                    **{k: v for k, v in attrs.items() if k != "op_type"})
    return len(prop.list_outputs())


def _register_custom_opdef():
    from .ops.registry import register as _reg_op
    _reg_op("Custom", num_outputs=_custom_num_outputs,
            attr_defaults={"op_type": None})(_custom_op_fn)


_register_custom_opdef()
