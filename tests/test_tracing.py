"""End-to-end tracing (mxnet_tpu/tracing.py): span contexts propagated
serve → batch → executor → kvstore, per-step train timelines, slow
exemplars, exporters, and the docs drift check.

Acceptance (ISSUE 5): one POST /predict through a warmed engine yields
one trace with >= 5 linked spans (http → queue → batch → forward →
slice) retrievable from /traces; a kvstore push under an injected
transient fault yields one client span with two attempt children, the
second marked retried.
"""
import importlib.util
import json
import logging
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import fault
from mxnet_tpu import io
from mxnet_tpu import profiler
from mxnet_tpu import telemetry as tm
from mxnet_tpu import tracing as tr
from mxnet_tpu.module import Module
from mxnet_tpu.serve import InferenceEngine, ServeConfig, serve_http
from mxnet_tpu.serving import Predictor

FEATURE = 4
CLASSES = 3


@pytest.fixture(autouse=True)
def _clean_tracer():
    prev_on = tr.enable(True)
    prev_rate = tr.set_sample(1.0)
    prev_slow = tr.set_slow_ms(1000)
    tr.reset()
    fault.disarm()
    yield
    fault.disarm()
    tr.set_slow_ms(prev_slow)
    tr.set_sample(prev_rate)
    tr.enable(prev_on)
    tr.reset()


def _model(tmp_path, seed=0):
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=CLASSES, name="fc")
    sym = mx.sym.softmax(fc, name="prob")
    rng = np.random.RandomState(seed)
    path = str(tmp_path / "model.params")
    mx.nd.save(path, {
        "arg:fc_weight": mx.nd.array(
            rng.randn(CLASSES, FEATURE).astype(np.float32)),
        "arg:fc_bias": mx.nd.array(
            rng.randn(CLASSES).astype(np.float32))})
    with open(path, "rb") as f:
        blob = f.read()
    return sym.tojson(), blob


def _engine(tmp_path, **cfg_kw):
    sym_json, blob = _model(tmp_path)
    pred = Predictor(sym_json, blob, input_shapes={"data": (1, FEATURE)})
    kw = dict(max_batch=4, queue_depth=32, batch_wait_ms=5,
              default_timeout_ms=10000, workers=1)
    kw.update(cfg_kw)
    return InferenceEngine(pred, ServeConfig(**kw))


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, json.loads(r.read().decode()), dict(r.headers)


def _post(url, payload, headers=(), timeout=30):
    req = urllib.request.Request(
        url + "/predict", data=json.dumps(payload).encode(),
        headers=dict({"Content-Type": "application/json"}, **dict(headers)),
        method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "{}"), dict(e.headers)


def _get_trace(base_url, trace_id, tries=50):
    """Fetch one trace by id, retrying briefly: the root span finalizes
    a hair after the HTTP response is written."""
    for _ in range(tries):
        try:
            _s, body, _h = _get(base_url + "/traces?id=" + trace_id)
            return body
        except urllib.error.HTTPError:
            time.sleep(0.02)
    raise AssertionError("trace %s never appeared" % trace_id)


def _by_name(trace, name):
    return [s for s in trace["spans"] if s["name"] == name]


# ---------------------------------------------------------------------------
# acceptance: serve path
# ---------------------------------------------------------------------------

def test_predict_trace_five_linked_spans(tmp_path):
    """One POST /predict through a warmed engine = one trace with >= 5
    linked spans, retrievable from /traces by the echoed request id."""
    eng = _engine(tmp_path).start()
    eng.warmup()
    srv = serve_http(eng)
    try:
        rid = "req-abc.123"
        status, body, headers = _post(
            srv.url, {"inputs": {"data": [[0.1] * FEATURE]}},
            headers=(("X-Request-Id", rid),))
        assert status == 200
        assert headers.get("X-Request-Id") == rid
        assert body["rows"] == 1

        trace = _get_trace(srv.url, rid)
        assert trace["trace_id"] == rid
        assert trace["root"] == "http.request"
        assert len(trace["spans"]) >= 5

        root = _by_name(trace, "http.request")[0]
        queue = _by_name(trace, "serve.queue_wait")[0]
        batch = _by_name(trace, "serve.batch")[0]
        compute = _by_name(trace, "serve.compute")[0]
        sliced = _by_name(trace, "serve.slice")[0]
        # linkage: http -> queue/batch -> compute/slice
        assert root["parent_id"] is None
        assert queue["parent_id"] == root["span_id"]
        assert batch["parent_id"] == root["span_id"]
        assert compute["parent_id"] == batch["span_id"]
        assert sliced["parent_id"] == batch["span_id"]
        # the executor's own span nests under serve.compute
        fwd = _by_name(trace, "executor.forward")
        assert fwd and fwd[0]["parent_id"] == compute["span_id"]
        # listing endpoint carries the trace too
        _s, listing, _h = _get(srv.url + "/traces")
        assert any(t["trace_id"] == rid for t in listing["recent"])
    finally:
        srv.close()
        eng.close(drain=False)


def test_request_id_echoed_on_error_responses(tmp_path):
    eng = _engine(tmp_path).start()
    eng.warmup()
    srv = serve_http(eng)
    try:
        # 400: malformed feed still echoes the id
        status, _b, headers = _post(
            srv.url, {"inputs": {"nope": [[1.0]]}},
            headers=(("X-Request-Id", "bad-input-1"),))
        assert status == 400
        assert headers.get("X-Request-Id") == "bad-input-1"
        # an invalid (header-splitting) id is replaced, not echoed
        status, _b, headers = _post(
            srv.url, {"inputs": {"data": [[0.1] * FEATURE]}},
            headers=(("X-Request-Id", "x" * 200),))
        assert status == 200
        got = headers.get("X-Request-Id")
        assert got and got != "x" * 200
    finally:
        srv.close()
        eng.close(drain=False)


def test_batch_span_fans_in_n_request_parents(tmp_path):
    """N concurrent requests coalesced into one batch: each trace gets
    the SAME serve.batch span id, parented under its own root."""
    eng = _engine(tmp_path, batch_wait_ms=200)
    eng.warmup()                          # compiled, workers NOT started
    done = []

    def client(i):
        with tr.start_span("test.root") as span:
            req = eng.submit({"data": [[0.1 * i] * FEATURE]},
                             ctx=span.ctx)
            req.result()
            done.append(span.trace_id)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.3)                       # all three queued
    eng.start()
    for t in threads:
        t.join()
    eng.close(drain=True)

    assert len(done) == 3
    traces = {tid: tr.get_trace(tid) for tid in done}
    assert all(t is not None for t in traces.values())
    batch_ids = set()
    for tid, t in traces.items():
        batches = _by_name(t, "serve.batch")
        assert len(batches) == 1
        assert batches[0]["attrs"]["fanin"] == 3
        root = _by_name(t, "test.root")[0]
        assert batches[0]["parent_id"] == root["span_id"]
        batch_ids.add(batches[0]["span_id"])
    assert len(batch_ids) == 1, "batch span id must be shared"


# ---------------------------------------------------------------------------
# acceptance: kvstore path
# ---------------------------------------------------------------------------

def test_kv_push_retry_one_client_span_two_attempts():
    """A push eating one injected transient fault = ONE kv.push client
    span with TWO kv.attempt children sharing it as parent, the second
    marked retried."""
    kv = mx.kv.create("local")
    kv.init("w", mx.nd.array(np.zeros((2,), np.float32)))
    tr.reset()
    fault.arm("kv.push", step=1, kind="transient", count=1)
    with tr.start_span("test.root") as span:
        tid = span.trace_id
        kv.push("w", mx.nd.array(np.ones((2,), np.float32)))
    fault.disarm()

    t = tr.get_trace(tid)
    assert t is not None
    pushes = _by_name(t, "kv.push")
    assert len(pushes) == 1
    attempts = [s for s in _by_name(t, "kv.attempt")
                if s["parent_id"] == pushes[0]["span_id"]]
    assert len(attempts) == 2
    attempts.sort(key=lambda s: s["attrs"]["attempt"])
    assert attempts[0]["attrs"]["attempt"] == 1
    assert "retried" not in attempts[0]["attrs"]
    assert attempts[0]["status"] == "error"      # the injected fault
    assert attempts[1]["attrs"]["attempt"] == 2
    assert attempts[1]["attrs"]["retried"] is True
    assert attempts[1]["status"] == "ok"
    # a fault-injection hit always retains the trace as an exemplar
    assert any(x["trace_id"] == tid for x in tr.slow_traces())


def test_kv_server_roundtrip_context_propagation(monkeypatch):
    """Context rides the RPC payload: server handling (including the
    faulted first attempt) appears under the client's trace."""
    from mxnet_tpu.kvstore_server import KVStoreServer
    server = KVStoreServer(port=0, num_workers=1, sync_mode=True)
    server.start_background()
    monkeypatch.setenv("MXNET_TPU_PS_URI", "127.0.0.1")
    monkeypatch.setenv("MXNET_TPU_PS_PORT", str(server.port))
    monkeypatch.setenv("MXNET_KV_TIMEOUT_MS", "10000")
    try:
        kv = mx.kv.create("dist_sync")
        with tr.start_span("test.root") as span:
            tid = span.trace_id
            kv.init("w", mx.nd.array(np.zeros((3,), np.float32)))
            fault.arm("kv.server", step=1, kind="transient", count=1)
            kv.push("w", mx.nd.array(np.full((3,), 2.0, np.float32)))
            fault.disarm()
        t = tr.get_trace(tid)
        assert t is not None
        servers = [s for s in _by_name(t, "kv.server")
                   if s["attrs"].get("op") == "PUSH"]
        assert len(servers) == 2
        servers.sort(key=lambda s: s["t0"])
        assert servers[0]["status"] == "error"    # injected transient
        assert servers[1]["status"] == "ok"       # the retry
        # each server span parents to a distinct client attempt span
        attempt_ids = {s["span_id"] for s in _by_name(t, "kv.attempt")}
        assert servers[0]["parent_id"] in attempt_ids
        assert servers[1]["parent_id"] in attempt_ids
        assert servers[0]["parent_id"] != servers[1]["parent_id"]
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# sampling, rings, retention
# ---------------------------------------------------------------------------

def test_sampling_honored():
    tr.set_sample(0.0)
    with tr.start_span("test.root"):
        with tr.child_span("test.child"):
            pass
    assert tr.finished_traces() == []
    tr.set_sample(1.0)
    with tr.start_span("test.root"):
        pass
    assert len(tr.finished_traces()) == 1


def test_unsampled_context_is_noop_scope():
    tr.set_sample(0.0)
    with tr.start_span("test.root") as span:
        assert span is tr.NOOP
        assert tr.active() is None


def test_tracer_does_not_consume_global_rng():
    """Ids and sampling decisions come from a private Random instance:
    a user's random.seed(...) stream must not diverge based on how many
    spans happened to be recorded."""
    import random
    random.seed(123)
    expect = [random.random() for _ in range(5)]
    random.seed(123)
    with tr.start_span("test.root"):
        with tr.child_span("test.child"):
            pass
    assert [random.random() for _ in range(5)] == expect


def test_op_dispatch_spans_opt_in():
    """Per-op op.dispatch spans only record under MXNET_TRACE_OPS (the
    span write dominates a microsecond-scale dispatch, so the default
    keeps sampled traces structural)."""
    x = mx.nd.array(np.eye(4, dtype=np.float32))
    with tr.start_span("test.root") as span:
        tid = span.trace_id
        mx.nd.dot(x, x).wait_to_read()
    assert "op.dispatch" not in {s["name"]
                                 for s in tr.get_trace(tid)["spans"]}
    prev = tr.set_trace_ops(True)
    try:
        with tr.start_span("test.root") as span:
            tid = span.trace_id
            mx.nd.dot(x, x).wait_to_read()
    finally:
        tr.set_trace_ops(prev)
    ops = [s for s in tr.get_trace(tid)["spans"]
           if s["name"] == "op.dispatch"]
    assert ops and ops[0]["attrs"]["op"] == "dot"


def test_ring_bounded():
    cap = tr._ring.maxlen
    for _ in range(cap + 25):
        with tr.start_span("test.root"):
            pass
    assert len(tr.finished_traces()) == cap


def test_slow_and_error_exemplars_retained():
    # fast + clean: NOT retained as an exemplar
    tr.set_slow_ms(10000)
    with tr.start_span("test.root"):
        pass
    assert tr.slow_traces() == []
    # slow: retained
    tr.set_slow_ms(0)
    with tr.start_span("test.root") as span:
        slow_tid = span.trace_id
    assert any(t["trace_id"] == slow_tid for t in tr.slow_traces())
    # error: retained regardless of the threshold
    tr.set_slow_ms(10000)
    with pytest.raises(RuntimeError):
        with tr.start_span("test.root") as span:
            err_tid = span.trace_id
            raise RuntimeError("boom")
    retained = [t for t in tr.slow_traces() if t["trace_id"] == err_tid]
    assert retained and "boom" in retained[0]["error"]


def test_transient_child_error_does_not_taint_trace():
    """A child failure that never reaches the root — a transport
    attempt retried to success, without fault injection — keeps its own
    error status but does not mark the trace errored, so routine
    transient noise cannot evict real exemplars from the error ring."""
    tr.set_slow_ms(10000)
    with tr.start_span("test.root") as span:
        tid = span.trace_id
        with pytest.raises(ValueError):
            with tr.child_span("test.child"):
                raise ValueError("transient")
    t = tr.get_trace(tid)
    assert t["error"] is None
    child = [s for s in t["spans"] if s["name"] == "test.child"][0]
    assert child["status"] == "error"
    assert not any(x["trace_id"] == tid for x in tr.slow_traces())


def test_graft_clock_rebases_foreign_epoch_only():
    """graft(): a bundle from another process (foreign proc token) is
    rebased by the clock-pair offset; a same-process bundle — e.g. a
    seq-cache replay re-shipping spans recorded seconds ago — keeps its
    true times."""
    now = time.perf_counter()

    def bundle(sid):
        return [{"name": "kv.server", "trace_id": "t" * 32,
                 "span_id": sid, "parent_id": "p" * 16,
                 "t0": now - 5.0, "t1": now - 4.9, "attrs": {},
                 "status": "ok", "tid": 1}]

    with tr.start_span("graft.root") as root:
        ctx = root.ctx
        tid = ctx.trace_id
        tr.graft(bundle("a" * 16), ctx=ctx,
                 clock=(tr._PROC_TOKEN, now, now + 0.5))
        tr.graft(bundle("b" * 16), ctx=ctx,
                 clock=("other-proc", now - 100.0, now))
    t = tr.get_trace(tid)
    same = [s for s in t["spans"] if s["span_id"] == "a" * 16][0]
    foreign = [s for s in t["spans"] if s["span_id"] == "b" * 16][0]
    assert same["t0"] == pytest.approx(now - 5.0, abs=1e-9)
    assert foreign["t0"] == pytest.approx(now - 5.0 + 100.0, abs=1e-6)


def test_late_spans_attach_after_root_finalized():
    """A span recorded after the root finalized — a worker finishing a
    batch whose requester already timed out (504) — still lands in the
    retained exemplar trace, with its phase in the breakdown."""
    tr.set_slow_ms(0)
    with tr.start_span("late.root") as root:
        ctx = root.ctx
        tid = ctx.trace_id
    t = tr.get_trace(tid)
    assert all(s["name"] != "late.child" for s in t["spans"])
    t0 = time.perf_counter()
    tr.record_span("late.child", ctx, t0, t0 + 0.005)
    t2 = tr.get_trace(tid)
    late = [s for s in t2["spans"] if s["name"] == "late.child"]
    assert len(late) == 1
    assert t2["phases"].get("late.child", 0.0) >= 4.0
    # dedup still applies through the late path
    tr.record_span("late.child", ctx, t0, t0 + 0.005,
                   span_id=late[0]["span_id"])
    assert len([s for s in tr.get_trace(tid)["spans"]
                if s["name"] == "late.child"]) == 1


def test_queue_expired_request_gets_queue_wait_span(tmp_path):
    """A request that dies in the queue (504) must still show WHERE the
    time went: its retained error exemplar carries a serve.queue_wait
    span covering the whole wait."""
    from mxnet_tpu.serve.engine import _Request
    eng = _engine(tmp_path)
    with tr.start_span("test.root") as root:
        tid = root.trace_id
        req = _Request({"data": np.zeros((1, FEATURE), np.float32)}, 1,
                       tm.monotonic() - 0.5, tctx=tr.current())
        req.t_enq = tm.monotonic() - 0.6
        eng._run_batch([req])
        with pytest.raises(Exception):
            req.result()
    t = tr.get_trace(tid)
    waits = [s for s in t["spans"] if s["name"] == "serve.queue_wait"]
    assert len(waits) == 1
    assert (waits[0]["t1"] - waits[0]["t0"]) >= 0.5
    assert t["error"] is not None           # retained as a 504 exemplar


def test_disabled_is_noop():
    tr.enable(False)
    with tr.start_span("test.root") as span:
        assert span is tr.NOOP
    assert tr.current() is None
    assert tr.finished_traces() == []
    tr.enable(True)


def test_span_cap_bounds_trace_memory():
    with tr.start_span("test.root") as span:
        tid = span.trace_id
        for _ in range(tr._MAX_SPANS + 50):
            with tr.child_span("test.child"):
                pass
    t = tr.get_trace(tid)
    assert len(t["spans"]) <= tr._MAX_SPANS + 1
    assert t["dropped_spans"] >= 50
    # the root envelope survives the cap even though it finishes last —
    # a capped trace must not be a bag of orphans
    assert _by_name(t, "test.root")


# ---------------------------------------------------------------------------
# train timeline
# ---------------------------------------------------------------------------

def _mlp_sym():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc", num_hidden=8)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_train_step_timeline_and_checkpoint_spans(tmp_path):
    rng = np.random.RandomState(0)
    data = rng.rand(40, 16).astype(np.float32)
    labels = rng.randint(0, 8, size=(40,)).astype(np.float32)
    it = io.NDArrayIter(data, labels, batch_size=20)
    mod = Module(_mlp_sym(), context=mx.cpu())
    prefix = str(tmp_path / "ckpt")
    mod.fit(it, num_epoch=1, optimizer="sgd",
            optimizer_params=(("learning_rate", 0.1),),
            checkpoint_prefix=prefix)

    steps = [t for t in tr.finished_traces() if t["root"] == "train.step"]
    assert steps, "no train.step traces recorded"
    phases = steps[-1]["phases"]
    for want in ("train.forward_backward", "train.update",
                 "train.data_wait"):
        assert want in phases, (want, phases)
    ckpts = [t for t in tr.finished_traces()
             if t["root"] == "train.checkpoint"]
    assert ckpts, "no train.checkpoint trace recorded"
    assert any("ckpt.write" == s["name"] for s in ckpts[-1]["spans"])


def test_io_batch_wait_span_under_step():
    rng = np.random.RandomState(0)
    base = io.NDArrayIter(rng.rand(8, 4).astype(np.float32),
                          np.zeros(8, np.float32), batch_size=4)
    pf = io.PrefetchingIter(base)
    with tr.start_span("test.root") as span:
        tid = span.trace_id
        for _batch in pf:
            pass
    t = tr.get_trace(tid)
    assert _by_name(t, "io.batch_wait")


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

def test_chrome_export_valid_and_monotonic(tmp_path):
    with tr.start_span("test.root") as span:
        tid = span.trace_id
        with tr.child_span("test.child"):
            time.sleep(0.002)
    path = str(tmp_path / "trace.json")
    profiler.dump(finished=True, filename=path)
    with open(path) as f:
        doc = json.load(f)                # valid JSON by json.load
    spans = [e for e in doc["traceEvents"]
             if e.get("cat") == "trace"
             and e["args"].get("trace_id") == tid]
    assert len(spans) == 2
    for e in spans:
        assert e["ph"] == "X"
        assert e["ts"] >= 0 and e["dur"] >= 0
    root = next(e for e in spans if e["name"] == "test.root")
    child = next(e for e in spans if e["name"] == "test.child")
    # monotonic nesting: the child starts after its parent and ends
    # within it
    assert root["ts"] <= child["ts"]
    assert child["ts"] + child["dur"] <= root["ts"] + root["dur"] + 1.0
    assert child["args"]["parent_id"] == root["args"]["span_id"]


def test_traces_endpoint_on_telemetry_server():
    with tr.start_span("test.root") as span:
        tid = span.trace_id
    srv = tm.serve(port=0)
    try:
        _s, body, _h = _get(srv.url + "/traces")
        assert any(t["trace_id"] == tid for t in body["recent"])
        assert body["enabled"] is True
        _s, one, _h = _get(srv.url + "/traces?id=" + tid)
        assert one["trace_id"] == tid and one["spans"]
    finally:
        srv.close()


def test_histogram_exemplar_links_worst_observation():
    h = tm.histogram("test_tracing/latency_seconds", "test")
    h.observe(0.010, trace_id="aaaa")
    h.observe(0.500, trace_id="bbbb")
    h.observe(0.020, trace_id="cccc")
    ex = tm.exemplars()
    got = ex.get("test_tracing/latency_seconds")
    assert got is not None
    assert got["trace_id"] == "bbbb"
    assert got["seconds"] == 0.5


def test_histogram_exemplar_expires_when_traffic_stops():
    """A frozen exemplar must not outlive the decay window: once traced
    observations stop (sampling off, idle service), exemplar() decays
    to None instead of pointing at a long-evicted timeline."""
    h = tm.Histogram()
    h.observe(0.5, trace_id="dddd")
    assert h.exemplar()[1] == "dddd"
    h._worst_t -= tm.EXEMPLAR_WINDOW_S + 1     # age it past the window
    assert h.exemplar() is None
    h.observe(0.1, trace_id="eeee")            # fresh traffic re-arms
    assert h.exemplar()[1] == "eeee"


def test_chrome_rename_limited_to_op_dispatch():
    """Only op.dispatch events take their op attr as the event name;
    kv.* spans carry an "op" attr too but keep their span identity."""
    prev = tr.set_trace_ops(True)
    try:
        with tr.start_span("test.root"):
            with tr.child_span("kv.attempt",
                               attrs={"op": "push", "attempt": 1}):
                pass
            x = mx.nd.array(np.eye(2, dtype=np.float32))
            mx.nd.dot(x, x).wait_to_read()
    finally:
        tr.set_trace_ops(prev)
    names = {e["name"] for e in tr.chrome_events()}
    assert "kv.attempt" in names and "push" not in names
    assert "dot" in names and "op.dispatch" not in names


# ---------------------------------------------------------------------------
# log correlation
# ---------------------------------------------------------------------------

def test_log_plain_suffix_and_json_mode():
    from mxnet_tpu.log import JsonFormatter, TraceFormatter
    rec = logging.LogRecord("t", logging.INFO, __file__, 1,
                            "hello %s", ("world",), None)
    plain = TraceFormatter("%(levelname)s %(name)s: %(message)s")
    jsonf = JsonFormatter()
    # outside any context: no suffix, no trace fields
    assert "[trace=" not in plain.format(rec)
    assert "trace_id" not in json.loads(jsonf.format(rec))
    with tr.start_span("test.root") as span:
        line = plain.format(rec)
        assert "[trace=%s" % span.trace_id in line
        obj = json.loads(jsonf.format(rec))
        assert obj["trace_id"] == span.trace_id
        assert obj["span_id"] == span.span_id
        assert obj["msg"] == "hello world"
        assert obj["level"] == "INFO"


def test_get_logger_json_mode(monkeypatch, capsys):
    monkeypatch.setenv("MXNET_LOG_JSON", "1")
    from mxnet_tpu.log import get_logger
    logger = get_logger("test_tracing_json_logger", level=logging.INFO)
    with tr.start_span("test.root") as span:
        logger.info("traced message")
    err = capsys.readouterr().err.strip().splitlines()[-1]
    obj = json.loads(err)
    assert obj["msg"] == "traced message"
    assert obj["trace_id"] == span.trace_id


# ---------------------------------------------------------------------------
# diagnostics + docs drift + overhead
# ---------------------------------------------------------------------------

def test_diagnostics_slow_traces_and_serve_status(tmp_path):
    tr.set_slow_ms(0)
    with tr.start_span("test.root"):
        pass
    eng = _engine(tmp_path).start()
    eng.warmup()
    try:
        info = mx.diagnostics(as_dict=True)
        assert info["tracing_enabled"] is True
        assert info["recent_slow_traces"]
        row = info["recent_slow_traces"][0]
        assert set(row) >= {"trace_id", "root", "duration_ms", "phases"}
        assert "serve_engines" in info
        # other tests' closed-but-not-yet-GC'd engines are filtered out;
        # ours is the one ready row
        ready = [r for r in info["serve_engines"] if r["ready"]]
        assert len(ready) == 1
        eng_row = ready[0]
        assert eng_row["workers_alive"] >= 1
        assert eng_row["queue_depth"] == 0
    finally:
        eng.close(drain=False)


def test_metrics_docs_in_sync():
    """tools/check_metrics_docs.py: every registered metric/span name
    literal is documented, and nothing documented is stale."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "check_metrics_docs.py")
    spec = importlib.util.spec_from_file_location("check_metrics_docs",
                                                  path)
    modl = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(modl)
    drift = modl.check()
    assert all(not v for v in drift.values()), drift


def test_dispatch_overhead_sampling0():
    """The sampling-0 path (tracing enabled, nothing recording) stays
    close to the disabled path on the dispatch microbench. Asserted
    loosely (CI wall-clock drifts more than the effect); the banked
    trace_overhead bench job carries the production < 5% evidence."""
    x = mx.nd.array(np.random.rand(16, 16).astype("float32"))
    mx.nd.dot(x, x).wait_to_read()

    def chunk(on, iters=200):
        tr.enable(on)
        tr.set_sample(0.0)
        t0 = time.perf_counter()
        for _ in range(iters):
            mx.nd.dot(x, x)
        return time.perf_counter() - t0

    chunk(True)
    chunk(False)
    on, off = float("inf"), float("inf")
    for _ in range(6):
        on = min(on, chunk(True))
        off = min(off, chunk(False))
    tr.enable(True)
    tr.set_sample(1.0)
    assert on <= off * 1.5 + 1e-3, \
        "sampling-0 tracing overhead too high: on=%.4fs off=%.4fs" \
        % (on, off)
