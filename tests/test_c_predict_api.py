"""Native C predict ABI: build, link a C++ client, run end-to-end.

Reference: include/mxnet/c_predict_api.h (the standalone inference ABI
every foreign binding links) — validated here the way a deployment
would use it: a real C++ program compiled against
cpp-package/include/mxnet_tpu_cpp/predictor.hpp, linked to
build/native/libmxtpu_predict.so, run as a separate process.
"""
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CPP_MAIN = r"""
#include <cstdio>
#include <fstream>
#include <sstream>
#include "mxnet_tpu_cpp/predictor.hpp"

static std::string slurp(const char* path) {
  std::ifstream f(path, std::ios::binary);
  std::ostringstream ss;
  ss << f.rdbuf();
  return ss.str();
}

int main(int argc, char** argv) {
  std::string json = slurp(argv[1]);
  std::string params = slurp(argv[2]);
  std::map<std::string, std::vector<uint32_t>> shapes{{"data", {2, 4}}};
  mxnet_tpu_cpp::Predictor pred(json, params, shapes, /*dev_type=*/1);
  std::vector<float> in(8);
  for (int i = 0; i < 8; ++i) in[i] = 0.25f * i;
  pred.SetInput("data", in);
  pred.Forward();
  auto shape = pred.GetOutputShape(0);
  auto out = pred.GetOutput(0);
  printf("shape %u %u\n", shape[0], shape[1]);
  for (float v : out) printf("%.6f ", v);
  printf("\n");
  return 0;
}
"""


def _build_artifacts(tmp_path):
    # model: y = softmax(FC(x)) with fixed weights
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    sym = mx.sym.softmax(fc, name="prob")
    rng = np.random.RandomState(0)
    w = rng.randn(3, 4).astype(np.float32)
    b = rng.randn(3).astype(np.float32)
    params = {"arg:fc_weight": mx.nd.array(w), "arg:fc_bias": mx.nd.array(b)}
    json_path = os.path.join(str(tmp_path), "model.json")
    params_path = os.path.join(str(tmp_path), "model.params")
    with open(json_path, "w") as f:
        f.write(sym.tojson())
    mx.nd.save(params_path, params)
    x = np.arange(8, dtype=np.float32).reshape(2, 4) * 0.25
    logits = x @ w.T + b
    e = np.exp(logits - logits.max(axis=1, keepdims=True))
    expect = e / e.sum(axis=1, keepdims=True)
    return json_path, params_path, expect


@pytest.fixture(scope="module")
def native_lib():
    lib = os.path.join(REPO, "build", "native", "libmxtpu_predict.so")
    r = subprocess.run(["make", "-C", os.path.join(REPO, "src", "native")],
                      capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert os.path.exists(lib)
    return lib


def test_c_predict_end_to_end(tmp_path, native_lib):
    json_path, params_path, expect = _build_artifacts(tmp_path)
    main_cc = tmp_path / "main.cc"
    main_cc.write_text(_CPP_MAIN)
    exe = str(tmp_path / "predict_test")
    r = subprocess.run(
        ["g++", "-O1", "-std=c++17", str(main_cc), "-o", exe,
         "-I", os.path.join(REPO, "cpp-package", "include"),
         "-L", os.path.dirname(native_lib), "-lmxtpu_predict",
         "-Wl,-rpath," + os.path.dirname(native_lib)],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr

    env = dict(os.environ)
    site = [p for p in sys.path if p.endswith("site-packages")]
    # the embedded libpython uses its own stdlib home; the venv's
    # site-packages (jax etc.) + the repo ride in via PYTHONPATH
    env["PYTHONPATH"] = os.pathsep.join([REPO] + site +
                                        [env.get("PYTHONPATH", "")])
    env.pop("PYTHONHOME", None)
    env["MXNET_TPU_PLATFORM"] = "cpu"
    r = subprocess.run([exe, json_path, params_path], env=env,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    lines = r.stdout.strip().splitlines()
    assert lines[0].strip() == "shape 2 3"
    got = np.array([float(v) for v in lines[1].split()]).reshape(2, 3)
    np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5)


def test_perl_binding_predicts(tmp_path, native_lib):
    """perl-package proof (reference perl-package/ AI::MXNet analog):
    the XS binding over the predict ABI builds with core-Perl tooling
    only and reproduces the Python-side softmax probabilities."""
    perl = shutil.which("perl")
    if perl is None:
        pytest.skip("no perl interpreter")
    pkg = os.path.join(REPO, "perl-package", "AI-MXNetTPU")
    r = subprocess.run([perl, os.path.join(pkg, "build.pl")],
                       capture_output=True, text=True)
    if r.returncode != 0 and "ExtUtils" in (r.stderr or ""):
        pytest.skip("perl lacks ExtUtils::ParseXS: " + r.stderr[:200])
    assert r.returncode == 0, r.stdout + r.stderr

    json_path, params_path, expect = _build_artifacts(tmp_path)
    script = tmp_path / "predict.pl"
    script.write_text("""
use strict; use warnings;
use AI::MXNetTPU;
my ($json_path, $params_path) = @ARGV;
local $/;
open(my $jf, "<", $json_path) or die $!;  my $json = <$jf>;
open(my $pf, "<:raw", $params_path) or die $!;  my $params = <$pf>;
my $pred = AI::MXNetTPU::Predictor->new(
    symbol_json => $json, params => $params,
    input_name => "data", input_shape => [2, 4]);
my @out = $pred->predict(map { $_ * 0.25 } 0 .. 7);
print join(" ", map { sprintf("%.6f", $_) } @out), "\\n";
""")
    env = _perl_env()
    r = subprocess.run(
        [perl, "-I", os.path.join(pkg, "lib"),
         "-I", os.path.join(pkg, "blib", "arch"),
         str(script), json_path, params_path],
        env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    got = np.array([float(v) for v in r.stdout.split()]).reshape(2, 3)
    np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5)


def _perl_env():
    env = dict(os.environ)
    site = [p for p in sys.path if p.endswith("site-packages")]
    env["PYTHONPATH"] = os.pathsep.join([REPO] + site +
                                        [env.get("PYTHONPATH", "")])
    env.pop("PYTHONHOME", None)
    env["MXNET_TPU_PLATFORM"] = "cpu"
    return env
