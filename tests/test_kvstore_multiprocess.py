"""Multi-host kvstore allreduce: two REAL processes joined via
jax.distributed, aggregating through the device-side global-array psum
(reference analog: dist_sync push/aggregate across ps-lite workers —
tests/nightly/dist_sync_kvstore.py pattern).

The raw CPU backend cannot run multiprocess computations
("Multiprocess computations aren't implemented on the CPU backend");
jax versions that expose ``jax_cpu_collectives_implementation`` can
route them over gloo instead, which is what real multi-host CPU jobs
(and this test) use. On a jax without that knob the test skips with
the precise limitation."""
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cpu_collectives_available():
    """Whether this jax can run cross-process collectives on the CPU
    backend (gloo). Probed against the live config so the gate is
    version-accurate, not version-number guesswork."""
    import jax
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        return True
    except (AttributeError, ValueError):
        return False


_WORKER = r"""
import os, sys
import numpy as np
import jax
# raw CPU backend: "Multiprocess computations aren't implemented";
# gloo collectives are the supported multiprocess-CPU route
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(
    coordinator_address=os.environ["COORD"],
    num_processes=2, process_id=int(sys.argv[1]))
sys.path.insert(0, %r)
import mxnet_tpu as mx
from mxnet_tpu import nd

assert jax.process_count() == 2, jax.process_count()
kv = mx.kvstore.create("dist_tpu_sync")
assert kv.num_workers == 2, kv.num_workers
rank = jax.process_index()
kv.init(3, nd.zeros((4, 5)))
kv.push(3, nd.ones((4, 5)) * (rank + 1))
out = nd.zeros((4, 5))
kv.pull(3, out=out)
np.testing.assert_allclose(out.asnumpy(), 3.0)
print("rank", rank, "OK", flush=True)
""" % (REPO,)


def test_two_process_device_side_allreduce(tmp_path):
    if not _cpu_collectives_available():
        pytest.skip(
            "this jax (%s) has no jax_cpu_collectives_implementation "
            "config: multiprocess computations aren't implemented on "
            "the raw CPU backend, and there is no gloo route to gate "
            "onto" % __import__("jax").__version__)
    port = socket.socket()
    port.bind(("127.0.0.1", 0))
    coord = "127.0.0.1:%d" % port.getsockname()[1]
    port.close()
    env = dict(os.environ, JAX_PLATFORMS="cpu", COORD=coord)
    env.pop("MXNET_TPU_PS_URI", None)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    script = str(tmp_path / "worker.py")
    with open(script, "w") as f:
        f.write(_WORKER)
    procs = [subprocess.Popen([sys.executable, script, str(r)], env=env,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for r in range(2)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
        assert "OK" in out
