// C++ wrapper over the mxnet_tpu C predict ABI.
//
// Reference analog: cpp-package/include/mxnet-cpp/ (header-only C++
// frontend over the C ABI). This header wraps the predict surface
// (src/native/c_predict_api.cc) in an RAII class; link against
// build/native/libmxtpu_predict.so.

#ifndef MXNET_TPU_CPP_PREDICTOR_HPP_
#define MXNET_TPU_CPP_PREDICTOR_HPP_

#include <cstdint>
#include <map>
#include <stdexcept>
#include <string>
#include <vector>

extern "C" {
typedef void* PredictorHandle;
const char* MXGetLastError();
int MXPredCreate(const char* symbol_json, const void* param_bytes,
                 int param_size, int dev_type, int dev_id,
                 uint32_t num_input_nodes, const char** input_keys,
                 const uint32_t* input_shape_indptr,
                 const uint32_t* input_shape_data, PredictorHandle* out);
int MXPredSetInput(PredictorHandle h, const char* key, const float* data,
                   uint32_t size);
int MXPredForward(PredictorHandle h);
int MXPredGetOutputShape(PredictorHandle h, uint32_t index,
                         uint32_t* shape_data, uint32_t* shape_ndim);
int MXPredGetOutput(PredictorHandle h, uint32_t index, float* data,
                    uint32_t size);
int MXPredFree(PredictorHandle h);
}

namespace mxnet_tpu_cpp {

class Predictor {
 public:
  // dev_type: 1 = cpu, 2 = tpu (reference: c_predict_api.h dev codes).
  Predictor(const std::string& symbol_json, const std::string& param_blob,
            const std::map<std::string, std::vector<uint32_t>>& input_shapes,
            int dev_type = 1, int dev_id = 0) {
    std::vector<const char*> keys;
    std::vector<uint32_t> indptr{0};
    std::vector<uint32_t> data;
    for (const auto& kv : input_shapes) {
      keys.push_back(kv.first.c_str());
      for (uint32_t d : kv.second) data.push_back(d);
      indptr.push_back(static_cast<uint32_t>(data.size()));
    }
    if (MXPredCreate(symbol_json.c_str(), param_blob.data(),
                     static_cast<int>(param_blob.size()), dev_type, dev_id,
                     static_cast<uint32_t>(keys.size()), keys.data(),
                     indptr.data(), data.data(), &handle_) != 0) {
      throw std::runtime_error(MXGetLastError());
    }
  }

  ~Predictor() {
    if (handle_ != nullptr) MXPredFree(handle_);
  }

  Predictor(const Predictor&) = delete;
  Predictor& operator=(const Predictor&) = delete;

  void SetInput(const std::string& key, const std::vector<float>& v) {
    if (MXPredSetInput(handle_, key.c_str(), v.data(),
                       static_cast<uint32_t>(v.size())) != 0) {
      throw std::runtime_error(MXGetLastError());
    }
  }

  void Forward() {
    if (MXPredForward(handle_) != 0) {
      throw std::runtime_error(MXGetLastError());
    }
  }

  std::vector<uint32_t> GetOutputShape(uint32_t index) {
    uint32_t ndim = 0;
    if (MXPredGetOutputShape(handle_, index, nullptr, &ndim) != 0) {
      throw std::runtime_error(MXGetLastError());
    }
    std::vector<uint32_t> shape(ndim);
    MXPredGetOutputShape(handle_, index, shape.data(), &ndim);
    return shape;
  }

  std::vector<float> GetOutput(uint32_t index) {
    auto shape = GetOutputShape(index);
    uint32_t size = 1;
    for (uint32_t d : shape) size *= d;
    std::vector<float> out(size);
    if (MXPredGetOutput(handle_, index, out.data(), size) != 0) {
      throw std::runtime_error(MXGetLastError());
    }
    return out;
  }

 private:
  PredictorHandle handle_ = nullptr;
};

}  // namespace mxnet_tpu_cpp

#endif  // MXNET_TPU_CPP_PREDICTOR_HPP_
