"""Production health layer: MFU/roofline accounting, in-program
numerics sentinels, SLO burn-rate alerts, crash-safe flight recorder.

Acceptance proofs (ISSUE 12):
* a Module.fit run with MXNET_NUMERICS=step shows ZERO extra host
  dispatches per step and ZERO XLA recompiles across LR-schedule steps
  (telemetry-asserted);
* an injected NaN gradient trips the policy within one step and names
  the offending param in ``full`` mode;
* the numerics trip leaves a flight-recorder record still readable
  after the training process is SIGKILLed (rc 137, fault-harness
  subprocess);
* /alerts reports a firing serve-p99 rule under an injected
  slow-compute fault and clears after recovery;
* executor/mfu is present on /metrics after one warmed fused step.
"""
import json
import os
import struct
import subprocess
import sys
import threading
import time
import urllib.request
import zlib

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import blackbox, fault, health
from mxnet_tpu import telemetry as tm
from mxnet_tpu import tracing as trc
from mxnet_tpu.context import current_context
from mxnet_tpu.io import DataBatch, NDArrayIter
from mxnet_tpu.models import mlp
from mxnet_tpu.module import Module

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _health_isolation():
    prev_mode = health.numerics_mode()
    prev_policy = health.numerics_policy()
    yield
    health.set_numerics(prev_mode)
    health.set_numerics_policy(prev_policy)
    health.reset()
    blackbox.reset()
    fault.disarm()


def _mlp_module(batch=16, seed=0):
    mod = Module(mlp(), context=current_context())
    mod.bind(data_shapes=[("data", (batch, 784))],
             label_shapes=[("softmax_label", (batch,))])
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05,
                                         "momentum": 0.9})
    rng = np.random.RandomState(seed)
    db = DataBatch(
        data=[mx.nd.array(rng.randn(batch, 784).astype(np.float32))],
        label=[mx.nd.array(rng.randint(0, 10, (batch,))
                           .astype(np.float32))])
    return mod, db


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_flight_recorder_roundtrip_and_cli(tmp_path):
    path = str(tmp_path / "flight.bin")
    blackbox.configure(path)
    blackbox.record_event("checkpoint", file="ck-0001.params",
                          seconds=0.012)
    blackbox.record_event("swap", quantized=True)
    events, torn = blackbox.read_events(path)
    assert torn == 0
    names = [e["event"] for e in events]
    assert names == ["start", "checkpoint", "swap"]
    assert events[1]["file"] == "ck-0001.params"
    assert all(e["pid"] == os.getpid() for e in events)
    assert blackbox.records_written() == 3
    # the post-mortem CLI reads the same ring from a fresh process
    proc = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.blackbox", path, "--json"],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(l) for l in proc.stdout.splitlines()
             if l.startswith("{")]
    assert [l["event"] for l in lines] == names


def test_flight_recorder_unknown_event_raises(tmp_path):
    blackbox.configure(str(tmp_path / "f.bin"))
    with pytest.raises(mx.base.MXNetError, match="unknown flight"):
        blackbox.record_event("zap_not_registered")


def test_flight_recorder_disabled_is_noop(tmp_path):
    blackbox.configure(None)
    assert blackbox.record_event("checkpoint", file="x") is False


def test_flight_recorder_torn_tail_tolerated(tmp_path):
    """A SIGKILL can land mid-frame: every frame before the tear must
    still read, and the reader must report the abandoned bytes."""
    path = str(tmp_path / "flight.bin")
    blackbox.configure(path)
    for i in range(5):
        blackbox.record_event("checkpoint", file="ck-%d" % i)
    blackbox.configure(None)             # close the fd
    with open(path, "r+b") as f:
        f.seek(0, os.SEEK_END)
        f.truncate(f.tell() - 7)         # tear the last frame
    events, torn = blackbox.read_events(path)
    assert torn > 0
    assert [e["event"] for e in events] == \
        ["start"] + ["checkpoint"] * 4   # last record lost, rest intact


def test_flight_recorder_corrupt_frame_stops_segment(tmp_path):
    """A flipped byte mid-ring fails that frame's CRC; the reader
    keeps everything before it rather than trusting garbage."""
    path = str(tmp_path / "flight.bin")
    blackbox.configure(path)
    for i in range(4):
        blackbox.record_event("checkpoint", file="ck-%d" % i)
    blackbox.configure(None)
    with open(path, "rb") as f:
        blob = f.read()
    # find the 3rd frame boundary and corrupt its payload
    hdr = struct.Struct("<4sII")
    off = 0
    for _ in range(2):
        _m, length, _c = hdr.unpack_from(blob, off)
        off += hdr.size + length
    blob = bytearray(blob)
    blob[off + hdr.size + 2] ^= 0xFF
    with open(path, "wb") as f:
        f.write(bytes(blob))
    events, torn = blackbox.read_events(path)
    assert [e["event"] for e in events] == ["start", "checkpoint"]
    assert torn > 0


def test_flight_recorder_rotation_bounds_disk(tmp_path):
    path = str(tmp_path / "flight.bin")
    blackbox.configure(path, limit_mb=0.01)   # 5 KB per segment
    for i in range(400):
        blackbox.record_event("checkpoint", file="ck-%06d" % i)
    size = os.path.getsize(path) if os.path.exists(path) else 0
    size1 = os.path.getsize(path + ".1") if os.path.exists(path + ".1") \
        else 0
    assert size + size1 <= 2 * 5000 + 4096    # bounded footprint
    events, torn = blackbox.read_events(path)
    assert torn == 0
    # the NEWEST record always survives rotation
    assert events[-1]["file"] == "ck-000399"


# ---------------------------------------------------------------------------
# pillar 1: MFU / roofline
# ---------------------------------------------------------------------------

def test_mfu_gauges_after_one_warmed_fused_step():
    """Acceptance: executor/mfu present on /metrics after one warmed
    fused step (plus the captured program's flops are real)."""
    mod, db = _mlp_module()
    for _ in range(3):                   # build + warm + one interval
        mod.forward_backward(db)
        mod.update()
    rec = mod._exec.fused_cost()
    if rec is None:
        pytest.skip("backend returned no cost analysis (documented "
                    "n/a fallback: gauges absent)")
    assert rec["flops"] > 0 and rec["bytes"] > 0
    prom = tm.render_prometheus()
    assert "mxnet_executor_mfu " in prom
    assert "mxnet_executor_hbm_bw_util " in prom
    summary = health.mfu_summary()
    assert summary["programs"]
    assert summary["executor_mfu"] > 0


def test_capture_cost_unknown_kind_raises():
    with pytest.raises(mx.base.MXNetError, match="unknown cost kind"):
        health.capture_cost("nope", "k", None, ())


def test_serve_bucket_mfu_under_traffic():
    from mxnet_tpu.serve import InferenceEngine, ServeConfig
    from mxnet_tpu.serving import Predictor
    from mxnet_tpu.benchmark import _serve_mlp_symbol
    import tempfile
    sym, params = _serve_mlp_symbol(32, 32, 8)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "p.params")
        mx.nd.save(path, params)
        with open(path, "rb") as f:
            blob = f.read()
    pred = Predictor(sym.tojson(), blob, dev_type=1,
                     input_shapes={"data": (1, 32)})
    eng = InferenceEngine(pred, ServeConfig(max_batch=4, workers=1,
                                            batch_wait_ms=0))
    eng.start().warmup()
    try:
        eng.predict({"data": np.zeros((1, 32), np.float32)})
        if eng._bucket_cost.get(1) is None:
            pytest.skip("no cost analysis on this backend")
        prom = tm.render_prometheus()
        assert 'mxnet_serving_mfu{bucket="1"}' in prom
    finally:
        eng.close(drain=False)


def test_concurrent_engines_price_batches_with_own_costs():
    """Two live engines (the shadow-A/B / swap-drain shape) must not
    share one global bucket cost record: each prices its batches with
    ITS program's FLOPs."""
    from mxnet_tpu.serve import InferenceEngine, ServeConfig
    from mxnet_tpu.serving import Predictor
    from mxnet_tpu.benchmark import _serve_mlp_symbol
    import tempfile
    engines = []
    try:
        for hidden in (16, 64):          # different-size models
            sym, params = _serve_mlp_symbol(16, hidden, 4)
            with tempfile.TemporaryDirectory() as d:
                path = os.path.join(d, "p.params")
                mx.nd.save(path, params)
                with open(path, "rb") as f:
                    blob = f.read()
            pred = Predictor(sym.tojson(), blob, dev_type=1,
                             input_shapes={"data": (1, 16)})
            eng = InferenceEngine(pred, ServeConfig(max_batch=2,
                                                    workers=1,
                                                    batch_wait_ms=0))
            eng.start().warmup()
            eng.predict({"data": np.zeros((1, 16), np.float32)})
            engines.append(eng)
        a, b = engines[0]._bucket_cost.get(1), \
            engines[1]._bucket_cost.get(1)
        if a is None or b is None:
            pytest.skip("no cost analysis on this backend")
        # distinct programs, distinct records — the bigger model costs
        # more flops, and neither engine clobbered the other
        assert a["flops"] != b["flops"]
    finally:
        for eng in engines:
            eng.close(drain=False)


def test_single_event_fires_events_mode_rule():
    """A counter-delta rule in events mode fires on ONE event and
    clears once the short window drains — burn-fraction dilution
    across quiet evaluator ticks must not swallow a numerics trip."""
    box = {"v": None}
    rule = health._Rule("unit_ev", lambda: box["v"], threshold=0.0,
                        cmp=">", short_s=2.0, long_s=6.0, burn=0.5,
                        description="", mode="events")
    t = 100.0
    for i in range(5):                   # long quiet steady state
        box["v"] = 0.0
        state, _ = rule.evaluate(t + i)
        assert state == "ok"
    box["v"] = 1.0                       # ONE event
    state, trans = rule.evaluate(t + 5)
    assert state == "firing" and trans
    box["v"] = 0.0
    state, _ = rule.evaluate(t + 6)      # still inside short window
    assert state == "firing"
    state, trans = rule.evaluate(t + 9)  # short window drained
    assert state == "ok" and trans
    # the default delta rules run in events mode
    for name in ("numerics", "kv_giveups", "worker_restart_burn"):
        health.rules()                   # install defaults
        assert health._rules[name].mode == "events"


# ---------------------------------------------------------------------------
# pillar 2: numerics sentinels
# ---------------------------------------------------------------------------

def test_acceptance_step_mode_zero_dispatch_zero_recompile():
    """Acceptance: a Module.fit run with MXNET_NUMERICS=step on the
    fused-step probe shows zero extra host dispatches per step and
    zero XLA recompiles across LR-schedule steps — telemetry-asserted.
    The LR scheduler changes the learning rate EVERY step, so a
    sentinel that baked scalars into the program would recompile."""
    health.set_numerics("step")
    batch, nbatch = 16, 8
    rng = np.random.RandomState(0)
    X = rng.randn(batch * nbatch, 784).astype(np.float32)
    y = rng.randint(0, 10, (batch * nbatch,)).astype(np.float32)

    def make_it():
        return NDArrayIter(X, y, batch_size=batch)

    mod = Module(mlp(), context=current_context())
    sched = mx.lr_scheduler.FactorScheduler(step=1, factor=0.95)
    opt_params = {"learning_rate": 0.05, "momentum": 0.9,
                  "lr_scheduler": sched}

    def fit_epoch():
        mod.fit(make_it(), num_epoch=1, optimizer="sgd",
                optimizer_params=opt_params,
                initializer=mx.init.Uniform(0.1))

    def measured_epoch():
        snap0 = tm.snapshot()
        fit_epoch()
        snap1 = tm.snapshot()
        return {k: snap1[k] - snap0[k]
                for k in ("op_dispatch_total", "backend_compile_total",
                          "fused_step_total", "fused_step_compiles")}

    # baseline: sentinels OFF, warm then measure one epoch
    health.set_numerics("off")
    fit_epoch()
    base = measured_epoch()
    # sentinels ON: the mode is a build-time knob, so one warm epoch
    # re-specializes the program; the epoch after must be identical
    health.set_numerics("step")
    fit_epoch()
    delta = measured_epoch()
    assert delta["fused_step_total"] == nbatch
    # ZERO extra host dispatches per step vs the sentinel-off baseline
    # (the only per-step dispatch is the one fused_train_step; the
    # epoch-boundary param-sync copies are identical in both modes)
    assert delta["op_dispatch_total"] == base["op_dispatch_total"]
    # and ZERO recompiles though the LR changed every step
    assert delta["backend_compile_total"] == 0
    assert delta["fused_step_compiles"] == 0
    # the sentinel actually ran: gauges are live
    assert tm.REGISTRY._families.get("health/grad_norm") is not None


def test_nan_trips_within_one_step():
    health.set_numerics("step")
    health.set_numerics_policy("raise")
    mod, db = _mlp_module()
    for _ in range(2):
        mod.forward_backward(db)
        mod.update()
    mod._exec.flush_numerics()           # healthy so far
    bad = DataBatch(
        data=[mx.nd.array(np.full((16, 784), np.nan, np.float32))],
        label=db.label)
    trips0 = health.numerics_trips()
    mod.forward_backward(bad)
    mod.update()                         # verdict is read one step
    with pytest.raises(health.NumericsError) as ei:
        mod._exec.flush_numerics()       # ...deferred: within one step
    assert "nonfinite" in str(ei.value)
    assert health.numerics_trips() == trips0 + 1
    assert ei.value.report["nonfinite"] > 0


def test_full_mode_names_offending_param():
    health.set_numerics("full")
    health.set_numerics_policy("raise")
    mod, db = _mlp_module()
    mod.forward_backward(db)
    mod.update()
    bad = DataBatch(
        data=[mx.nd.array(np.full((16, 784), np.nan, np.float32))],
        label=db.label)
    mod.forward_backward(bad)
    mod.update()
    with pytest.raises(health.NumericsError) as ei:
        mod._exec.flush_numerics()
    msg = str(ei.value)
    assert "worst param" in msg
    assert any(p in msg for p in mod._param_names)
    per_param = ei.value.report["per_param"]
    assert set(per_param) == set(mod._param_names)
    assert sum(v["nonfinite"] for v in per_param.values()) > 0


def test_warn_policy_continues_training():
    health.set_numerics("step")
    health.set_numerics_policy("warn")
    mod, db = _mlp_module()
    mod.forward_backward(db)
    mod.update()
    bad = DataBatch(
        data=[mx.nd.array(np.full((16, 784), np.nan, np.float32))],
        label=db.label)
    trips0 = health.numerics_trips()
    mod.forward_backward(bad)
    mod.update()
    mod._exec.flush_numerics()           # warn: no raise
    assert health.numerics_trips() == trips0 + 1


def test_checkpoint_and_raise_saves_forensic_checkpoint(tmp_path):
    health.set_numerics("step")
    health.set_numerics_policy("checkpoint-and-raise")
    batch, nbatch = 16, 4
    rng = np.random.RandomState(0)
    X = rng.randn(batch * nbatch, 784).astype(np.float32)
    X[batch:2 * batch] = np.nan          # NaN batch mid-epoch
    y = rng.randint(0, 10, (batch * nbatch,)).astype(np.float32)
    prefix = str(tmp_path / "ck")
    mod = Module(mlp(), context=current_context())
    with pytest.raises(health.NumericsError):
        mod.fit(NDArrayIter(X, y, batch_size=batch), num_epoch=2,
                optimizer="sgd",
                optimizer_params={"learning_rate": 0.05},
                initializer=mx.init.Uniform(0.1),
                checkpoint_prefix=prefix)
    forensic = [f for f in os.listdir(str(tmp_path))
                if f.startswith("ck.numerics") and
                f.endswith(".params")]
    assert forensic, os.listdir(str(tmp_path))
    # the recovery chain under the PLAIN prefix is untouched by the
    # forensic save (nothing valid yet, and nothing clobbered)
    from mxnet_tpu.checkpoint import load_latest_valid
    assert load_latest_valid(prefix) is None


def test_grad_spike_trips():
    health.set_numerics("step")
    health.set_numerics_policy("raise")
    prev = health.set_spike_factor(3.0)
    try:
        mod, db = _mlp_module()
        for _ in range(4):               # establish the EMA
            mod.forward_backward(db)
            mod.update()
        mod._exec.flush_numerics()
        big = DataBatch(
            data=[mx.nd.array(np.full((16, 784), 1e4, np.float32))],
            label=db.label)
        mod.forward_backward(big)
        mod.update()
        with pytest.raises(health.NumericsError, match="grad_spike"):
            mod._exec.flush_numerics()
    finally:
        health.set_spike_factor(prev)


def test_acceptance_sigkill_leaves_readable_flight_record(tmp_path):
    """Acceptance: train with MXNET_NUMERICS=step and the flight
    recorder on, trip a NaN sentinel (policy warn → recorded, training
    continues), then SIGKILL the process via an armed crash fault two
    steps later (rc 137). The numerics_trip AND the fault's own record
    must both read back from the ring post-mortem."""
    rec_path = str(tmp_path / "flight.bin")
    script = tmp_path / "train.py"
    script.write_text(
        "import numpy as np\n"
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu.io import NDArrayIter\n"
        "from mxnet_tpu.models import mlp\n"
        "from mxnet_tpu.module import Module\n"
        "from mxnet_tpu.context import current_context\n"
        "batch = 16\n"
        "rng = np.random.RandomState(0)\n"
        "X = rng.randn(batch * 8, 784).astype(np.float32)\n"
        "X[batch:2*batch] = np.nan\n"   # trips at step 2
        "y = rng.randint(0, 10, (batch * 8,)).astype(np.float32)\n"
        "mod = Module(mlp(), context=current_context())\n"
        "mod.fit(NDArrayIter(X, y, batch_size=batch), num_epoch=2,\n"
        "        optimizer='sgd',\n"
        "        optimizer_params={'learning_rate': 0.05},\n"
        "        initializer=mx.init.Uniform(0.1))\n"
        "raise SystemExit(0)\n")
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               MXNET_NUMERICS="step",
               MXNET_NUMERICS_POLICY="warn",
               MXNET_FLIGHT_RECORDER=rec_path,
               MXNET_FAULT_INJECT="engine.step:5:crash",
               PYTHONPATH=REPO_ROOT + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    proc = subprocess.run([sys.executable, str(script)], env=env,
                          cwd=REPO_ROOT, capture_output=True,
                          timeout=300)
    assert proc.returncode == 137, proc.stderr.decode()[-2000:]
    events, _torn = blackbox.read_events(rec_path)
    names = [e["event"] for e in events]
    assert "numerics_trip" in names      # survived the SIGKILL
    trip = events[names.index("numerics_trip")]
    assert trip["kind"] == "nonfinite"
    # the crash fault wrote its own record before os._exit: the ring
    # names its killer
    assert names[-1] == "fault"
    assert events[-1]["point"] == "engine.step"
    assert events[-1]["kind"] == "crash"
    # and the reader CLI agrees from a fresh process
    proc = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.blackbox", rec_path],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0
    assert "numerics_trip" in proc.stdout


# ---------------------------------------------------------------------------
# pillar 3: SLO engine
# ---------------------------------------------------------------------------

def test_default_rules_registered():
    names = health.rules()
    for n in ("serve_p99", "decode_itl_p99", "queue_depth",
              "worker_restart_burn", "kv_giveups", "numerics"):
        assert n in names


def test_watch_validation():
    with pytest.raises(mx.base.MXNetError, match="exactly one"):
        health.watch("bad_rule")
    with pytest.raises(mx.base.MXNetError, match="exactly one"):
        health.watch("bad_rule", gauge="a/b", counter_delta="c/d")


def test_multiwindow_burn_rate_semantics():
    """A one-sample blip cannot fire; a sustained violation fires once
    both windows burn; recovery clears when the short window drops."""
    box = {"v": 0.0}
    rule = health._Rule("unit", lambda: box["v"], threshold=1.0,
                        cmp=">", short_s=2.0, long_s=6.0, burn=0.5,
                        description="")
    t = 100.0
    # one blip inside an otherwise-clean history: no fire
    for i in range(6):
        box["v"] = 5.0 if i == 2 else 0.0
        state, trans = rule.evaluate(t + i)
        assert state == "ok"
    # sustained violation: fires (both windows past burn)
    t += 10
    fired = False
    for i in range(8):
        box["v"] = 5.0
        state, trans = rule.evaluate(t + i)
        fired = fired or state == "firing"
    assert fired
    # recovery: clean short window clears it
    t += 20
    for i in range(6):
        box["v"] = 0.0
        state, _ = rule.evaluate(t + i)
    assert state == "ok"


def test_acceptance_alerts_fire_and_clear_under_slow_compute():
    """Acceptance: /alerts reports a firing serve-p99 rule under an
    injected slow-compute fault and clears after recovery — through a
    real InferenceEngine and the HTTP endpoint."""
    from mxnet_tpu.serve import InferenceEngine, ServeConfig
    from mxnet_tpu.serving import Predictor
    from mxnet_tpu.benchmark import _serve_mlp_symbol
    import tempfile
    sym, params = _serve_mlp_symbol(32, 32, 8)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "p.params")
        mx.nd.save(path, params)
        with open(path, "rb") as f:
            blob = f.read()
    pred = Predictor(sym.tojson(), blob, dev_type=1,
                     input_shapes={"data": (1, 32)})
    eng = InferenceEngine(pred, ServeConfig(max_batch=4, workers=1,
                                            batch_wait_ms=0,
                                            default_timeout_ms=20000))
    eng.start().warmup()
    health.set_interval(0.05)
    # the default serve_p99 rule with test-speed windows/threshold
    health.watch("serve_p99", histogram_p99="serving/request_seconds",
                 threshold=0.040, short_s=0.5, long_s=1.0, burn=0.5,
                 description="test serve p99")
    srv = tm.serve()
    feed = {"data": np.zeros((1, 32), np.float32)}

    def alerts():
        with urllib.request.urlopen(srv.url + "/alerts",
                                    timeout=5) as r:
            return json.loads(r.read())

    try:
        # slow-compute fault: every worker iteration eats a 70 ms
        # delay, pushing request p99 far past the 40 ms threshold
        fault.arm("serve.worker", step=1, kind="delay", count=10 ** 6,
                  delay_ms=70)
        deadline = time.time() + 20
        firing = []
        while time.time() < deadline:
            eng.predict(feed)
            firing = alerts()["firing"]
            if "serve_p99" in firing:
                break
        assert "serve_p99" in firing, alerts()
        # recovery: disarm, keep traffic flowing so fresh (fast)
        # samples land in the short window
        fault.disarm("serve.worker")
        deadline = time.time() + 20
        while time.time() < deadline:
            eng.predict(feed)
            firing = alerts()["firing"]
            if "serve_p99" not in firing:
                break
            time.sleep(0.02)
        assert "serve_p99" not in firing, alerts()
        body = alerts()
        row = [r for r in body["rules"] if r["name"] == "serve_p99"][0]
        assert row["state"] == "ok"
        assert body["evaluator_alive"]
        # transitions were recorded: counter + flight-style history
        fam = tm.REGISTRY._families.get("health/alert_transitions_total")
        states = {lv for lv, _c in fam.series()}
        assert ("serve_p99", "firing") in states
        assert ("serve_p99", "ok") in states
    finally:
        fault.disarm()
        srv.close()
        eng.close(drain=False)


def test_alerts_endpoint_on_serve_http():
    """The serving frontend mounts the SAME /alerts implementation."""
    from mxnet_tpu.serve import InferenceEngine, ServeConfig, serve_http
    from mxnet_tpu.serving import Predictor
    from mxnet_tpu.benchmark import _serve_mlp_symbol
    import tempfile
    sym, params = _serve_mlp_symbol(16, 16, 4)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "p.params")
        mx.nd.save(path, params)
        with open(path, "rb") as f:
            blob = f.read()
    pred = Predictor(sym.tojson(), blob, dev_type=1,
                     input_shapes={"data": (1, 16)})
    eng = InferenceEngine(pred, ServeConfig(max_batch=2, workers=1))
    eng.start().warmup()
    srv = serve_http(eng)
    try:
        with urllib.request.urlopen(srv.url + "/alerts", timeout=5) as r:
            body = json.loads(r.read())
        assert "rules" in body and "firing" in body
        assert any(r["name"] == "serve_p99" for r in body["rules"])
    finally:
        srv.close()
        eng.close(drain=False)


def test_snapshot_and_diagnostics_carry_health_fields():
    snap = tm.snapshot()
    assert "alerts_firing" in snap
    assert "numerics_trips" in snap
    assert "flight_records" in snap
    info = tm.diagnostics(as_dict=True)
    assert "health" in info
    assert "mfu" in info["health"]
    assert "alerts_firing" in info["health"]


# ---------------------------------------------------------------------------
# satellite: registry/trace-ring vs SLO evaluator concurrency
# ---------------------------------------------------------------------------

def test_concurrent_writers_vs_slo_reader():
    """Telemetry writers + trace-ring writers hammering while the SLO
    evaluator and the scrape path read: no torn snapshots (counter
    totals add up exactly), no deadlock, p99 evaluation keeps
    working."""
    c = tm.counter("serving/requests_total", "x")
    h = tm.histogram("serving/request_seconds", "x")
    health.set_interval(0.02)
    health.watch("conc_unit", histogram_p99="serving/request_seconds",
                 threshold=1e9, short_s=0.5, long_s=1.0, burn=0.5,
                 description="concurrency probe")
    n_threads, per_thread = 8, 400
    stop = threading.Event()
    errs = []

    def writer(i):
        try:
            for k in range(per_thread):
                c.inc()
                h.observe(1e-4 * (k % 7), trace_id="t%d" % i)
                with trc.start_span("train.step",
                                    attrs={"epoch": 0, "nbatch": k}):
                    pass
        except Exception as e:           # pragma: no cover
            errs.append(e)

    def reader():
        try:
            while not stop.is_set():
                tm.REGISTRY.render_prometheus()
                tm.snapshot()
                health.evaluate_once()
                trc.finished_traces(limit=5)
        except Exception as e:           # pragma: no cover
            errs.append(e)

    c0 = c.value
    rt = threading.Thread(target=reader)
    rt.start()
    ts = [threading.Thread(target=writer, args=(i,))
          for i in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    stop.set()
    rt.join(timeout=10)
    assert not errs, errs
    assert c.value - c0 == n_threads * per_thread   # no lost bumps
    assert h._default().count >= n_threads * per_thread


def test_exemplar_expiry_still_enforced(monkeypatch):
    """The worst-recent exemplar decays: after the window a stale
    exemplar reads as None instead of pointing at an evicted
    timeline (PR 5 contract, re-asserted under the new reader)."""
    h = tm.Histogram()
    h.observe(0.5, trace_id="abc")
    assert h.exemplar()[1] == "abc"
    monkeypatch.setattr(tm, "EXEMPLAR_WINDOW_S", 0.0)
    time.sleep(0.01)
    assert h.exemplar() is None
    assert h.exemplar() is None          # stays cleared


# ---------------------------------------------------------------------------
# satellite: bench wiring
# ---------------------------------------------------------------------------

def test_mfu_divergence_warning_unit():
    from mxnet_tpu import benchmark as B
    extra = {"mfu_est": 0.10, "mfu_measured": 0.25}
    B._note_mfu_divergence(extra)
    assert "mfu_divergence_warning" in extra
    assert extra["mfu_measured_vs_est"] == 2.5
    ok = {"mfu_est": 0.10, "mfu_measured": 0.11}
    B._note_mfu_divergence(ok)
    assert "mfu_divergence_warning" not in ok


def test_health_overhead_job_registered():
    from mxnet_tpu import benchmark as B
    assert "health_overhead" in B.JOBS
    assert "health_overhead" in B.JOB_PRIORITY


def test_docs_drift_check_covers_events_and_rules():
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    try:
        import check_metrics_docs as chk
    finally:
        sys.path.pop(0)
    _m, _s, events, rules, endpoints = chk.collect_code_names()
    assert set(blackbox.EVENTS) <= events
    assert {"serve_p99", "numerics", "kv_giveups",
            "mfu_divergence"} <= rules
    assert {"/metrics", "/alerts", "/programs"} <= endpoints
    drift = chk.check()
    assert not any(drift.values()), drift
