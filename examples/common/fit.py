"""Training-fit plumbing for the image-classification CLIs.

Reference analog: example/image-classification/common/fit.py:83-90 —
network/kv-store flag wiring into Module.fit with lr scheduling,
checkpoint callbacks, and Speedometer logging. TPU-native notes:
``--tpus 0,1,...`` (alias ``--gpus``) builds a data-parallel context
list (one mesh-sharded program, see mxnet_tpu/module/module.py
_install_dp_mesh); ``--kv-store dist_tpu_sync`` selects the allreduce
distributed mode.
"""
from __future__ import annotations

import logging
import math
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import mxnet_tpu as mx  # noqa: E402


def get_epoch_size(args, kv):
    nworker = kv.num_workers if kv else 1
    return math.ceil(int(args.num_examples / nworker) / args.batch_size)


def _get_lr_scheduler(args, kv):
    if not getattr(args, "lr_factor", None) or args.lr_factor >= 1:
        return (args.lr, None)
    epoch_size = get_epoch_size(args, kv)
    begin_epoch = args.load_epoch or 0
    step_epochs = [int(l) for l in args.lr_step_epochs.split(",")]
    lr = args.lr
    for s in step_epochs:
        if begin_epoch >= s:
            lr *= args.lr_factor
    if lr != args.lr:
        logging.info("Adjusted learning rate to %e for epoch %d",
                     lr, begin_epoch)
    steps = [epoch_size * (x - begin_epoch)
             for x in step_epochs if x - begin_epoch > 0]
    if not steps:
        return (lr, None)
    return (lr, mx.lr_scheduler.MultiFactorScheduler(
        step=steps, factor=args.lr_factor, base_lr=args.lr))


def _load_model(args, rank=0):
    if getattr(args, "load_epoch", None) is None:
        return (None, None, None)
    assert args.model_prefix is not None
    return mx.model.load_checkpoint(args.model_prefix, args.load_epoch)


def _save_model(args, rank=0):
    if args.model_prefix is None:
        return None
    dst_dir = os.path.dirname(args.model_prefix)
    if dst_dir and not os.path.isdir(dst_dir):
        os.makedirs(dst_dir)
    prefix = args.model_prefix if rank == 0 else "%s-%d" % (
        args.model_prefix, rank)
    return mx.callback.do_checkpoint(prefix, period=args.save_period)


def add_fit_args(parser):
    train = parser.add_argument_group("Training")
    train.add_argument("--network", type=str, default="resnet",
                       help="the network to train")
    train.add_argument("--num-layers", type=int, default=50)
    train.add_argument("--tpus", "--gpus", dest="tpus", type=str,
                       default=None,
                       help="comma list of device ids for data parallelism, "
                            "e.g. 0,1,2,3; empty means one device")
    train.add_argument("--kv-store", type=str, default="device",
                       help="local | device | dist_tpu_sync | dist_sync | "
                            "dist_async")
    train.add_argument("--num-epochs", type=int, default=90)
    train.add_argument("--lr", type=float, default=0.1)
    train.add_argument("--lr-factor", type=float, default=0.1)
    train.add_argument("--lr-step-epochs", type=str, default="30,60,80")
    train.add_argument("--optimizer", type=str, default="sgd")
    train.add_argument("--mom", type=float, default=0.9)
    train.add_argument("--wd", type=float, default=1e-4)
    train.add_argument("--batch-size", type=int, default=128)
    train.add_argument("--disp-batches", type=int, default=20)
    train.add_argument("--model-prefix", type=str, default=None)
    train.add_argument("--save-period", type=int, default=1)
    train.add_argument("--load-epoch", type=int, default=None)
    train.add_argument("--max-batches", type=int, default=None,
                       help="stop every epoch after this many batches "
                            "(smoke tests / benchmarking)")
    train.add_argument("--monitor", type=int, default=0)
    return train


def fit(args, network, data_loader):
    """Train ``network`` with the flags in ``args``
    (reference: common/fit.py fit)."""
    kv = None
    if "dist" in args.kv_store:
        kv = mx.kvstore.create(args.kv_store)
    head = "%(asctime)-15s Node[" + str(kv.rank if kv else 0) + "] %(message)s"
    logging.basicConfig(level=logging.INFO, format=head)
    logging.info("start with arguments %s", args)

    epoch_size = get_epoch_size(args, kv)
    train, val = data_loader(args, kv)

    if args.tpus:
        devs = [mx.tpu(int(i)) for i in args.tpus.split(",")]
    else:
        devs = mx.tpu(0) if mx.num_tpus() > 0 else mx.cpu()

    lr, lr_scheduler = _get_lr_scheduler(args, kv)
    sym, arg_params, aux_params = _load_model(args, kv.rank if kv else 0)
    if sym is None:
        sym = network

    mod = mx.module.Module(symbol=sym, context=devs)

    optimizer_params = {
        "learning_rate": lr,
        "wd": args.wd,
        "lr_scheduler": lr_scheduler,
    }
    if args.optimizer in ("sgd", "nag", "signum"):
        optimizer_params["momentum"] = args.mom

    checkpoint = _save_model(args, kv.rank if kv else 0)
    batch_end_cbs = [mx.callback.Speedometer(args.batch_size,
                                             args.disp_batches)]

    eval_metrics = ["accuracy"]
    if args.num_classes >= 5:
        eval_metrics.append(mx.metric.create("top_k_accuracy", top_k=5))

    monitor = mx.monitor.Monitor(1, pattern=".*") if args.monitor else None

    if args.max_batches:
        train = mx.io.ResizeIter(train, args.max_batches)

    mod.fit(train,
            begin_epoch=args.load_epoch or 0,
            num_epoch=args.num_epochs,
            eval_data=val,
            eval_metric=eval_metrics,
            kvstore=kv if kv else args.kv_store,
            optimizer=args.optimizer,
            optimizer_params=optimizer_params,
            initializer=mx.init.Xavier(rnd_type="gaussian",
                                       factor_type="in", magnitude=2),
            arg_params=arg_params,
            aux_params=aux_params,
            batch_end_callback=batch_end_cbs,
            epoch_end_callback=checkpoint,
            allow_missing=True,
            monitor=monitor)
    return mod
