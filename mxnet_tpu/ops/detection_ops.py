"""Detection operators: SSD target/decode + RPN proposals.

Reference: src/operator/contrib/multibox_target.cc,
multibox_detection.cc, proposal.cc. The reference implementations are
sequential per-anchor CPU/CUDA loops; here every stage is a vectorized,
statically-shaped masked computation (argmax matching, rank-based
top-k, fori_loop NMS over a dense IoU matrix) so the whole pipeline
jits and vmaps over the batch — no host round-trips inside training.

All three ops are non-differentiable label/post-processing stages, as
in the reference (their backward passes are zeros).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register
from .contrib_ops import _iou_matrix

_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# MultiBoxTarget (reference: src/operator/contrib/multibox_target.cc:58-280)
# ---------------------------------------------------------------------------

def _encode_loc(anchors, gt, variances):
    """Center-offset box encoding (reference multibox_target.cc:32-55):
    ((gx-ax)/aw/vx, (gy-ay)/ah/vy, log(gw/aw)/vw, log(gh/ah)/vh)."""
    vx, vy, vw, vh = variances
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = (anchors[:, 0] + anchors[:, 2]) * 0.5
    ay = (anchors[:, 1] + anchors[:, 3]) * 0.5
    gw = gt[:, 2] - gt[:, 0]
    gh = gt[:, 3] - gt[:, 1]
    gx = (gt[:, 0] + gt[:, 2]) * 0.5
    gy = (gt[:, 1] + gt[:, 3]) * 0.5
    aw = jnp.maximum(aw, 1e-12)
    ah = jnp.maximum(ah, 1e-12)
    safe = (gw > 0) & (gh > 0)
    gw = jnp.where(safe, gw, 1.0)
    gh = jnp.where(safe, gh, 1.0)
    return jnp.stack([
        (gx - ax) / aw / vx,
        (gy - ay) / ah / vy,
        jnp.log(gw / aw) / vw,
        jnp.log(gh / ah) / vh,
    ], axis=1)


def _multibox_target_one(anchors, labels, cls_pred, overlap_threshold,
                         ignore_label, negative_mining_ratio,
                         negative_mining_thresh, minimum_negative_samples,
                         variances):
    """One batch element. anchors (N,4), labels (M,W>=5) rows
    [cls, xmin, ymin, xmax, ymax, ...] padded with cls<0, cls_pred (C,N).
    """
    N = anchors.shape[0]
    M = labels.shape[0]
    gt_valid = labels[:, 0] >= 0                               # (M,)
    iou = _iou_matrix(anchors, labels[:, 1:5])                 # (N, M)
    iou = jnp.where(gt_valid[None, :], iou, -1.0)

    # stage 1 — greedy bipartite: repeatedly take the globally best
    # (anchor, gt) pair so every gt gets its best unclaimed anchor
    # (reference multibox_target.cc:100-147)
    def bip_step(state, _):
        a_used, g_used, m_gt, m_iou = state
        masked = jnp.where(a_used[:, None] | g_used[None, :], -1.0, iou)
        flat = jnp.argmax(masked)
        ai, gi = flat // M, flat % M
        ok = masked[ai, gi] > 1e-12
        a_used = a_used.at[ai].set(a_used[ai] | ok)
        g_used = g_used.at[gi].set(g_used[gi] | ok)
        m_gt = m_gt.at[ai].set(jnp.where(ok, gi, m_gt[ai]))
        m_iou = m_iou.at[ai].set(jnp.where(ok, masked[ai, gi], m_iou[ai]))
        return (a_used, g_used, m_gt, m_iou), None

    init = (jnp.zeros(N, bool), jnp.zeros(M, bool),
            jnp.zeros(N, jnp.int32), jnp.full(N, -1.0))
    (a_used, _, m_gt, m_iou), _ = lax.scan(bip_step, init, None, length=M)

    # stage 2 — per-anchor threshold matching for the rest
    # (reference multibox_target.cc:150-179)
    best_gt = jnp.argmax(iou, axis=1).astype(jnp.int32)
    best_iou = jnp.max(iou, axis=1)
    m_gt = jnp.where(a_used, m_gt, best_gt)
    m_iou = jnp.where(a_used, m_iou, best_iou)
    thr_pos = (~a_used) & (best_iou > overlap_threshold) \
        if overlap_threshold > 0 else jnp.zeros(N, bool)
    positive = a_used | thr_pos

    if negative_mining_ratio > 0:
        # hard negative mining: unmatched anchors below the mining IoU
        # threshold, ranked by background confidence ascending (least
        # background-like first — reference multibox_target.cc:181-240)
        num_pos = jnp.sum(positive)
        num_neg = jnp.minimum(
            (num_pos * negative_mining_ratio).astype(jnp.int32),
            N - num_pos)
        num_neg = jnp.maximum(num_neg, int(minimum_negative_samples))
        bg_prob = jax.nn.softmax(cls_pred, axis=0)[0]          # (N,)
        cand = (~positive) & (m_iou < negative_mining_thresh)
        key = jnp.where(cand, bg_prob, jnp.inf)
        order = jnp.argsort(key)
        rank = jnp.zeros(N, jnp.int32).at[order].set(jnp.arange(N,
                                                     dtype=jnp.int32))
        negative = cand & (rank < num_neg)
    else:
        negative = ~positive

    cls_target = jnp.where(
        positive, labels[m_gt, 0] + 1.0,
        jnp.where(negative, 0.0, float(ignore_label)))
    loc = _encode_loc(anchors, labels[m_gt, 1:5], variances)   # (N, 4)
    loc_target = jnp.where(positive[:, None], loc, 0.0).reshape(-1)
    loc_mask = jnp.where(positive[:, None],
                         jnp.ones((N, 4)), 0.0).reshape(-1)
    return loc_target, loc_mask, cls_target


@register("_contrib_MultiBoxTarget", num_outputs=3, differentiable=False,
          attr_defaults={"overlap_threshold": 0.5, "ignore_label": -1.0,
                         "negative_mining_ratio": -1.0,
                         "negative_mining_thresh": 0.5,
                         "minimum_negative_samples": 0,
                         "variances": (0.1, 0.1, 0.2, 0.2)})
def _multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                     ignore_label=-1.0, negative_mining_ratio=-1.0,
                     negative_mining_thresh=0.5,
                     minimum_negative_samples=0,
                     variances=(0.1, 0.1, 0.2, 0.2), **_ig):
    """SSD training-target assignment (reference: multibox_target.cc).

    anchor (1, N, 4) corner boxes; label (B, M, 5+) rows
    [cls, xmin, ymin, xmax, ymax] padded with cls=-1; cls_pred (B, C, N)
    raw class scores (used only for hard negative mining).
    Returns (loc_target (B, 4N), loc_mask (B, 4N), cls_target (B, N)).
    """
    anchors = anchor.reshape(-1, 4)
    fn = lambda lab, cp: _multibox_target_one(
        anchors, lab, cp, float(overlap_threshold), float(ignore_label),
        float(negative_mining_ratio), float(negative_mining_thresh),
        int(minimum_negative_samples), tuple(variances))
    return jax.vmap(fn)(label, cls_pred)


# ---------------------------------------------------------------------------
# MultiBoxDetection (reference: src/operator/contrib/multibox_detection.cc)
# ---------------------------------------------------------------------------

def _decode_loc(anchors, loc, variances, clip):
    """Inverse of _encode_loc (reference multibox_detection.cc:46-72)."""
    vx, vy, vw, vh = variances
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = (anchors[:, 0] + anchors[:, 2]) * 0.5
    ay = (anchors[:, 1] + anchors[:, 3]) * 0.5
    ox = loc[:, 0] * vx * aw + ax
    oy = loc[:, 1] * vy * ah + ay
    ow = jnp.exp(loc[:, 2] * vw) * aw * 0.5
    oh = jnp.exp(loc[:, 3] * vh) * ah * 0.5
    out = jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=1)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


def _multibox_detection_one(cls_prob, loc_pred, anchors, threshold, clip,
                            background_id, nms_threshold, force_suppress,
                            nms_topk, variances):
    C, N = cls_prob.shape
    # best non-background class per anchor
    fg = jnp.where(jnp.arange(C)[:, None] == background_id,
                   -jnp.inf, cls_prob)                          # (C, N)
    cid = jnp.argmax(fg, axis=0)                                # (N,)
    score = jnp.max(fg, axis=0)
    keep_cls = score >= threshold
    boxes = _decode_loc(anchors, loc_pred.reshape(-1, 4), variances, clip)

    # class ids are re-based so background is dropped: classes after
    # background shift down by one (reference stores id-1 with bg=0)
    out_id = jnp.where(cid > background_id, cid - 1, cid).astype(
        cls_prob.dtype)
    out_id = jnp.where(keep_cls, out_id, -1.0)

    # greedy NMS over score-descending order (reference: multibox NMS
    # with per-class suppression unless force_suppress). Slice to the
    # top-K candidates FIRST so the IoU matrix is K*K, not N*N — with
    # SSD300's 8732 anchors that is the difference between ~300 MB and
    # a few MB per batch element.
    order = jnp.argsort(-jnp.where(keep_cls, score, -jnp.inf))
    K = min(N, nms_topk) if nms_topk > 0 else N
    order = order[:K]
    sid = out_id[order]
    sscore = jnp.where(keep_cls, score, -1.0)[order]
    sbox = boxes[order]
    valid0 = sid >= 0
    iou = _iou_matrix(sbox, sbox)
    same = jnp.ones((K, K), bool) if force_suppress \
        else sid[:, None] == sid[None, :]

    def body(i, keep):
        sup = (iou[i] > nms_threshold) & same[i] & keep[i] \
            & (jnp.arange(K) > i)
        return jnp.where(sup, False, keep)

    keep = lax.fori_loop(0, K, body, valid0)
    rows = jnp.concatenate([
        jnp.where(keep, sid, -1.0)[:, None],
        sscore[:, None], sbox], axis=1)                         # (K, 6)
    # compact: surviving detections first, suppressed rows become -1
    # (the reference writes valid entries to the front of the output)
    comp = jnp.argsort(~keep, stable=True)
    rows = jnp.where(keep[comp, None], rows[comp],
                     jnp.full((1, 6), -1.0, rows.dtype))
    if K < N:
        rows = jnp.concatenate(
            [rows, jnp.full((N - K, 6), -1.0, rows.dtype)])
    return rows


@register("_contrib_MultiBoxDetection", differentiable=False,
          attr_defaults={"clip": True, "threshold": 0.01,
                         "background_id": 0, "nms_threshold": 0.5,
                         "force_suppress": False, "nms_topk": -1,
                         "variances": (0.1, 0.1, 0.2, 0.2)})
def _multibox_detection(cls_prob, loc_pred, anchor, clip=True,
                        threshold=0.01, background_id=0, nms_threshold=0.5,
                        force_suppress=False, nms_topk=-1,
                        variances=(0.1, 0.1, 0.2, 0.2), **_ig):
    """SSD inference decode + NMS (reference: multibox_detection.cc).

    cls_prob (B, C, N) softmax class probabilities, loc_pred (B, 4N),
    anchor (1, N, 4). Returns (B, N, 6) rows
    [class_id, score, xmin, ymin, xmax, ymax], -1 padded.
    """
    anchors = anchor.reshape(-1, 4)
    fn = lambda cp, lp: _multibox_detection_one(
        cp, lp, anchors, float(threshold), bool(clip), int(background_id),
        float(nms_threshold), bool(force_suppress), int(nms_topk),
        tuple(variances))
    return jax.vmap(fn)(cls_prob, loc_pred)


# ---------------------------------------------------------------------------
# Proposal (reference: src/operator/contrib/proposal.cc) — RPN stage of
# Faster R-CNN: anchors + deltas -> clipped, filtered, NMS'd ROIs
# ---------------------------------------------------------------------------

def _base_anchors(base_size, scales, ratios):
    """Faster-RCNN base anchors around a base_size window at the origin:
    ratio enumeration then scale enumeration (reference
    proposal-inl.h GenerateAnchors semantics)."""
    import numpy as np
    base = np.array([0, 0, base_size - 1, base_size - 1], dtype=np.float64)
    w = base[2] - base[0] + 1
    h = base[3] - base[1] + 1
    cx = base[0] + 0.5 * (w - 1)
    cy = base[1] + 0.5 * (h - 1)
    out = []
    size = w * h
    for r in ratios:
        ws = np.round(np.sqrt(size / r))
        hs = np.round(ws * r)
        for s in scales:
            wss, hss = ws * s, hs * s
            out.append([cx - 0.5 * (wss - 1), cy - 0.5 * (hss - 1),
                        cx + 0.5 * (wss - 1), cy + 0.5 * (hss - 1)])
    return np.array(out, dtype=np.float32)                      # (A, 4)


def _proposal_one(fg_scores, deltas, im_info, anchors_hw, pre_n, post_n,
                  nms_thresh, min_size, iou_loss):
    """fg_scores (A, H, W); deltas (A, 4, H, W); anchors_hw (A, H, W, 4)."""
    A, H, W = fg_scores.shape
    n = A * H * W
    boxes = anchors_hw.reshape(n, 4)
    d = jnp.transpose(deltas, (0, 2, 3, 1)).reshape(n, 4)
    scores = fg_scores.reshape(n)
    im_h, im_w, im_scale = im_info[0], im_info[1], im_info[2]

    if iou_loss:
        # iou_loss decode: deltas are direct corner offsets
        # (reference proposal.cc BBoxTransformInv2)
        pred = boxes + d
    else:
        bw = boxes[:, 2] - boxes[:, 0] + 1.0
        bh = boxes[:, 3] - boxes[:, 1] + 1.0
        cx = boxes[:, 0] + 0.5 * (bw - 1.0)
        cy = boxes[:, 1] + 0.5 * (bh - 1.0)
        pcx = d[:, 0] * bw + cx
        pcy = d[:, 1] * bh + cy
        pw = jnp.exp(d[:, 2]) * bw
        ph = jnp.exp(d[:, 3]) * bh
        pred = jnp.stack([pcx - 0.5 * (pw - 1.0), pcy - 0.5 * (ph - 1.0),
                          pcx + 0.5 * (pw - 1.0), pcy + 0.5 * (ph - 1.0)],
                         axis=1)
    pred = jnp.stack([
        jnp.clip(pred[:, 0], 0.0, im_w - 1.0),
        jnp.clip(pred[:, 1], 0.0, im_h - 1.0),
        jnp.clip(pred[:, 2], 0.0, im_w - 1.0),
        jnp.clip(pred[:, 3], 0.0, im_h - 1.0)], axis=1)

    # drop proposals smaller than min_size (scaled to the input image);
    # the reference expands them and flags score=-1 — same net effect
    ms = min_size * im_scale
    pw = pred[:, 2] - pred[:, 0] + 1.0
    ph = pred[:, 3] - pred[:, 1] + 1.0
    ok = (pw >= ms) & (ph >= ms)
    scores = jnp.where(ok, scores, -1.0)

    # slice to pre_nms_top_n BEFORE the IoU matrix: with a 50x38 RPN
    # map and 12 anchors (n~23k) the full n*n matrix would be ~2 GB;
    # pre_n*pre_n (default 6000) is what the reference computes too
    pre = min(n, pre_n)
    order = jnp.argsort(-scores)[:pre]
    sbox = pred[order]
    sscore = scores[order]
    valid0 = sscore > -1.0
    iou = _iou_matrix(sbox, sbox)

    def body(i, keep):
        sup = (iou[i] > nms_thresh) & keep[i] & (jnp.arange(pre) > i)
        return jnp.where(sup, False, keep)

    keep = lax.fori_loop(0, pre, body, valid0)

    # take the first post_n surviving proposals; when fewer survive,
    # pad by repeating survivors (the reference pads cyclically)
    comp = jnp.argsort(~keep, stable=True)                      # kept first
    nk = jnp.maximum(jnp.sum(keep), 1)
    idx = comp[jnp.arange(post_n) % nk]
    return sbox[idx], sscore[idx]


@register("_contrib_Proposal",
          num_outputs=lambda attrs: 2 if attrs.get("output_score") else 1,
          differentiable=False,
          attr_defaults={"rpn_pre_nms_top_n": 6000,
                         "rpn_post_nms_top_n": 300, "threshold": 0.7,
                         "rpn_min_size": 16, "scales": (4, 8, 16, 32),
                         "ratios": (0.5, 1, 2), "feature_stride": 16,
                         "output_score": False, "iou_loss": False})
def _proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
              rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
              scales=(4, 8, 16, 32), ratios=(0.5, 1, 2), feature_stride=16,
              output_score=False, iou_loss=False, **_ig):
    """RPN proposal generation (reference: contrib/proposal.cc).

    cls_prob (B, 2A, H, W) background/foreground scores; bbox_pred
    (B, 4A, H, W); im_info (B, 3) rows [height, width, scale].
    Returns rois (B*post_n, 5) [batch_idx, x1, y1, x2, y2] (+ scores
    (B*post_n, 1) when output_score).
    """
    B, twoA, H, W = cls_prob.shape
    A = twoA // 2
    if A != len(scales) * len(ratios):
        from ..base import MXNetError
        raise MXNetError(
            "Proposal: cls_prob has %d anchors/position but "
            "len(scales)*len(ratios)=%d" % (A, len(scales) * len(ratios)))
    base = jnp.asarray(_base_anchors(feature_stride, scales, ratios))
    sx = jnp.arange(W, dtype=jnp.float32) * feature_stride
    sy = jnp.arange(H, dtype=jnp.float32) * feature_stride
    shift = jnp.stack([
        jnp.broadcast_to(sx[None, :], (H, W)),
        jnp.broadcast_to(sy[:, None], (H, W)),
        jnp.broadcast_to(sx[None, :], (H, W)),
        jnp.broadcast_to(sy[:, None], (H, W))], axis=-1)        # (H, W, 4)
    anchors_hw = base[:, None, None, :] + shift[None]           # (A,H,W,4)

    fg = cls_prob[:, A:, :, :]                                  # (B, A, H, W)
    deltas = bbox_pred.reshape(B, A, 4, H, W)
    fn = lambda s, dl, info: _proposal_one(
        s, dl, info, anchors_hw, int(rpn_pre_nms_top_n),
        int(rpn_post_nms_top_n), float(threshold), float(rpn_min_size),
        bool(iou_loss))
    boxes, scores = jax.vmap(fn)(fg, deltas, im_info)           # (B,post,4)
    bidx = jnp.repeat(jnp.arange(B, dtype=boxes.dtype),
                      int(rpn_post_nms_top_n))[:, None]
    rois = jnp.concatenate([bidx, boxes.reshape(-1, 4)], axis=1)
    if output_score:
        return rois, scores.reshape(-1, 1)
    return rois
