"""Serving fleet tier (ISSUE 18): prefix-affinity router + SLO-driven
replica autoscaler.

Unit layers run against fake in-process HTTP backends (no jax in the
loop) so router policy — consistent-hash affinity, yield-to-load,
least-outstanding, ejection + retry — is asserted cheaply; the
acceptance test drives a REAL fleet of serve_http worker subprocesses
through a load ramp, a SIGKILL under traffic, and a drain-retirement,
asserting replica count tracks load, only in-flight requests can be
lost, zero XLA compiles happen after warmup on every replica
(including the warmset-spawned mid-ramp one), and the flight recorder
tells the story post-mortem.
"""
import http.client
import http.server
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import blackbox as bb
from mxnet_tpu import fault
from mxnet_tpu import health
from mxnet_tpu import telemetry as tm
from mxnet_tpu import tracing as tr
from mxnet_tpu.base import MXNetError
from mxnet_tpu.checkpoint import ProcessSupervisor, TrainingSupervisor
from mxnet_tpu.serve import (Fleet, ModelRegistry, NoLiveReplicaError,
                             Router, serve_http, serve_router)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _post(url, path, payload, timeout=30, headers=()):
    req = urllib.request.Request(
        url + path, data=json.dumps(payload).encode(),
        headers=dict({"Content-Type": "application/json"}, **dict(headers)),
        method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "{}"), dict(e.headers)


def _get(url, path, timeout=10):
    with urllib.request.urlopen(url + path, timeout=timeout) as r:
        return r.status, r.read()


class _EchoHandler(http.server.BaseHTTPRequestHandler):
    """Fake replica: echoes the propagation headers back as JSON."""
    protocol_version = "HTTP/1.1"

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        self.rfile.read(n)
        hold = getattr(self.server, "hold_s", 0.0)
        if hold:
            time.sleep(hold)
        out = json.dumps(
            {"port": self.server.server_address[1],
             "rid": self.headers.get("X-Request-Id"),
             "deadline_ms": self.headers.get("X-Deadline-Ms"),
             "trace_ctx": self.headers.get("X-Trace-Context")}
        ).encode() + b"\n"
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(out)))
        self.end_headers()
        self.wfile.write(out)

    def log_message(self, *args):
        pass


def _fake_backend():
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _EchoHandler)
    srv.daemon_threads = True
    threading.Thread(target=lambda: srv.serve_forever(poll_interval=0.05),
                     daemon=True).start()
    return srv


@pytest.fixture
def two_backends():
    b1, b2 = _fake_backend(), _fake_backend()
    yield b1, b2
    for b in (b1, b2):
        b.shutdown()
        b.server_close()


# ---------------------------------------------------------------------------
# satellite 1: ProcessSupervisor extraction
# ---------------------------------------------------------------------------


def test_process_supervisor_triage_policy():
    """Preemption-grade exits always relaunch and reset the budget;
    genuine failures burn it; the relaunch metric keeps its labels."""
    tm.reset()
    ps = ProcessSupervisor(max_failures=2, relaunch_delay_s=0)
    assert ps.triage(-9) == ("preempt", True)       # signal death
    assert ps.triage(137) == ("preempt", True)      # 128+SIGKILL
    assert ps.triage(143) == ("preempt", True)      # 128+SIGTERM
    assert ps.failures == 0
    assert ps.triage(1) == ("failure", True)
    assert ps.triage(137) == ("preempt", True)      # resets the count
    assert ps.failures == 0
    assert ps.triage(1) == ("failure", True)
    assert ps.triage(2) == ("failure", False)       # budget exhausted
    text = tm.render_prometheus()
    assert 'mxnet_supervisor_relaunches_total{reason="preempt"} 4' in text
    # two failure relaunches; the exhausted decision does NOT count
    assert 'mxnet_supervisor_relaunches_total{reason="failure"} 2' in text


def test_process_supervisor_note_success_resets_budget():
    ps = ProcessSupervisor(max_failures=2, relaunch_delay_s=0)
    assert ps.triage(1) == ("failure", True)
    ps.note_success()
    assert ps.failures == 0
    assert ps.triage(1) == ("failure", True)        # budget is fresh


def test_training_supervisor_delegates_behavior_identical(tmp_path):
    """Regression: the old entry point still returns 0 on clean exit
    and the last rc after max_failures genuine failures, and still
    reads MXNET_SUPERVISOR_MAX_FAILURES by default."""
    assert TrainingSupervisor._PREEMPT_RCS == frozenset((137, 143))
    assert TrainingSupervisor.is_preemption_rc(-15)
    assert not TrainingSupervisor.is_preemption_rc(7)
    runs = tmp_path / "runs.txt"
    script = tmp_path / "job.py"
    script.write_text(
        "import sys\n"
        "with open(%r, 'a') as f: f.write('x')\n"
        "sys.exit(7)\n" % str(runs))
    rc = TrainingSupervisor.supervise(
        [sys.executable, str(script)], max_failures=2,
        relaunch_delay_s=0)
    assert rc == 7
    assert runs.read_text() == "xx"                 # ran exactly twice
    script.write_text("raise SystemExit(0)\n")
    assert TrainingSupervisor.supervise(
        [sys.executable, str(script)], max_failures=1,
        relaunch_delay_s=0) == 0


# ---------------------------------------------------------------------------
# satellite 3: machine-readable /alerts
# ---------------------------------------------------------------------------


def _check_alerts_payloads(url):
    status, body = _get(url, "/alerts")
    human = json.loads(body)
    assert status == 200
    # the default (human/dashboard) payload is unchanged
    assert set(human) == {"rules", "firing", "interval_s",
                          "evaluator_alive"}
    assert all("description" in r for r in human["rules"])
    status, body = _get(url, "/alerts?format=json")
    machine = json.loads(body)
    assert status == 200
    assert machine["format"] == "json"
    assert isinstance(machine["firing"], list)
    by_name = {r["rule"]: r for r in machine["rules"]}
    assert "serve_p99" in by_name
    row = by_name["serve_p99"]
    assert row["state"] in ("ok", "firing")
    assert len(row["windows"]) == 2
    assert all({"window_s", "burn_frac"} <= set(w)
               for w in row["windows"])


def test_alerts_format_json_telemetry_mount():
    health.reset()
    srv = tm.serve(port=0)
    try:
        _check_alerts_payloads("http://127.0.0.1:%d" % srv.port)
    finally:
        srv.close()
        health.reset()


# ---------------------------------------------------------------------------
# router policy units (fake backends; no jax in the loop)
# ---------------------------------------------------------------------------


def test_affinity_key_prefix_head():
    r = Router(prefix_tokens=4, affinity_slack=2)
    body = json.dumps({"prompt": [1, 2, 3, 4, 5, 6]}).encode()
    assert r.affinity_key("/generate", body) == "1,2,3,4"
    # same head, different tail -> same key (one prefix family)
    body2 = json.dumps({"prompt": [1, 2, 3, 4, 99, 98]}).encode()
    assert r.affinity_key("/generate", body2) == "1,2,3,4"
    assert r.affinity_key("/predict", body) is None
    assert r.affinity_key("/generate", b"not json") is None
    assert r.affinity_key("/generate", json.dumps([7, 8]).encode()) \
        == "7,8"


def test_affinity_pins_and_yields_to_load():
    tm.reset()
    r = Router(prefix_tokens=4, affinity_slack=2)
    r.add("a", "127.0.0.1", 1001)
    r.add("b", "127.0.0.1", 1002)
    key = r.affinity_key("/generate",
                         json.dumps({"prompt": [5, 5, 5, 5, 1]}).encode())
    rep, hit = r.pick(key)
    assert hit
    pinned = rep.name
    r._release(rep)
    # stable: the same key pins the same replica across picks
    for _ in range(3):
        rep, hit = r.pick(key)
        assert (rep.name, hit) == (pinned, True)
        r._release(rep)
    # saturate the pinned replica past the slack: affinity yields
    with r._lock:
        r._replicas[pinned].outstanding = 5
    rep, hit = r.pick(key)
    assert rep.name != pinned and not hit
    text = tm.render_prometheus()
    assert "mxnet_router_affinity_yields_total 1" in text
    assert "mxnet_router_affinity_hits_total 4" in text


def test_least_outstanding_pick():
    r = Router()
    r.add("a", "127.0.0.1", 1001)
    r.add("b", "127.0.0.1", 1002)
    with r._lock:
        r._replicas["a"].outstanding = 3
    rep, hit = r.pick()
    assert (rep.name, hit) == ("b", False)
    with pytest.raises(NoLiveReplicaError):
        r.pick(exclude={"a", "b"})


def test_router_ejects_dead_replica_and_retries(two_backends):
    b1, b2 = two_backends
    r = Router(forward_retries=2)
    r.add("a", "127.0.0.1", b1.server_address[1])
    r.add("b", "127.0.0.1", b2.server_address[1])
    with serve_router(r, port=0) as front:
        b1.shutdown()
        b1.server_close()
        live_port = b2.server_address[1]
        for _ in range(4):
            status, out, _ = _post(front.url, "/predict", {"inputs": 1})
            assert status == 200 and out["port"] == live_port
        snap = {x["name"]: x for x in r.replicas()}
        assert not snap["a"]["healthy"] and snap["b"]["healthy"]
        # everything dead -> 503 with Retry-After, not a hang
        r.eject("b")
        status, out, headers = _post(front.url, "/predict", {"inputs": 1})
        assert status == 503 and "Retry-After" in headers


def test_router_forward_fault_point(two_backends):
    """An armed router.forward fault looks exactly like a vanished
    replica: eject + retry onto the next one, request still succeeds."""
    b1, b2 = two_backends
    tm.reset()
    r = Router(forward_retries=2)
    r.add("a", "127.0.0.1", b1.server_address[1])
    r.add("b", "127.0.0.1", b2.server_address[1])
    with serve_router(r, port=0) as front:
        with fault.arming("router.forward", step=1, kind="raise"):
            status, out, _ = _post(front.url, "/predict", {"inputs": 1})
        assert status == 200
        assert fault.hits("router.forward") >= 1
        assert sum(1 for x in r.replicas() if x["healthy"]) == 1
    text = tm.render_prometheus()
    assert "mxnet_router_forward_retries_total 1" in text


def test_router_deadline_expiry_and_propagation(two_backends):
    b1, _ = two_backends
    r = Router()
    r.add("a", "127.0.0.1", b1.server_address[1])
    with serve_router(r, port=0) as front:
        # a microscopic budget dies in the router with a 504
        status, out, _ = _post(front.url, "/predict",
                               {"inputs": 1, "timeout_ms": 1e-6})
        assert status == 504
        # a real budget is forwarded as the REMAINING deadline
        status, out, headers = _post(
            front.url, "/predict", {"inputs": 1, "timeout_ms": 5000},
            headers={"X-Request-Id": "fleet-rid-1"})
        assert status == 200
        assert out["rid"] == "fleet-rid-1"
        assert headers["X-Request-Id"] == "fleet-rid-1"
        assert 0 < float(out["deadline_ms"]) <= 5000
        wire = json.loads(out["trace_ctx"])
        assert wire["trace_id"] == "fleet-rid-1" and wire["sampled"]


# ---------------------------------------------------------------------------
# satellite 2 + 3: end-to-end against a REAL replica (in-process)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def real_replica(tmp_path_factory):
    """One warmed serve_http replica over a tiny FC+softmax model."""
    tmp = tmp_path_factory.mktemp("fleet_model")
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    sym = mx.sym.softmax(fc, name="prob")
    rng = np.random.RandomState(0)
    path = str(tmp / "m.params")
    mx.nd.save(path, {
        "arg:fc_weight": mx.nd.array(rng.randn(3, 4).astype(np.float32)),
        "arg:fc_bias": mx.nd.array(rng.randn(3).astype(np.float32))})
    with open(path, "rb") as f:
        blob = f.read()
    reg = ModelRegistry(sym.tojson(), blob, input_shapes={"data": (1, 4)})
    reg.warmup()
    srv = serve_http(reg, port=0)
    yield srv
    srv.close()
    reg.close()
    health.reset()


def test_alerts_format_json_serve_mount(real_replica):
    _check_alerts_payloads(real_replica.url)


def test_end_to_end_trace_links_router_and_replica_spans(real_replica):
    """One trace on the ROUTER's /traces holds the whole story:
    router.request -> router.forward -> the replica's http.request and
    its serve.* children, clock-rebased into the router's timeline."""
    r = Router()
    r.add("a", "127.0.0.1", real_replica.port)
    rid = "fleet-e2e-trace-1"
    with serve_router(r, port=0) as front:
        status, out, _ = _post(
            front.url, "/predict",
            {"inputs": {"data": [[1.0, 2.0, 3.0, 4.0]]},
             "timeout_ms": 20000},
            headers={"X-Request-Id": rid})
        assert status == 200 and out["rows"] == 1
        code, body = _get(front.url, "/traces?trace_id=" + rid)
        assert code == 200
    trace = tr.get_trace(rid)
    assert trace is not None
    spans = {s["name"]: s for s in trace["spans"]}
    assert {"router.request", "router.forward",
            "http.request"} <= set(spans)
    root = spans["router.request"]
    fwd = spans["router.forward"]
    rep = spans["http.request"]
    assert fwd["parent_id"] == root["span_id"]
    assert rep["parent_id"] == fwd["span_id"]          # cross-process link
    assert root["t0"] <= fwd["t0"] <= rep["t0"]        # rebased clock nests
    assert "serve.compute" in trace["phases"]          # replica internals


def test_replica_honors_router_deadline_header(real_replica):
    """X-Deadline-Ms caps the replica-side budget even when the body
    asks for more — replica 504 accounting matches the router's view."""
    conn = http.client.HTTPConnection("127.0.0.1", real_replica.port,
                                      timeout=30)
    try:
        body = json.dumps({"inputs": {"data": [[1, 2, 3, 4]]},
                           "timeout_ms": 60000}).encode()
        conn.request("POST", "/predict", body,
                     {"Content-Type": "application/json",
                      "X-Deadline-Ms": "0.0"})
        resp = conn.getresponse()
        out = json.loads(resp.read())
        assert resp.status == 504, out
    finally:
        conn.close()


# ---------------------------------------------------------------------------
# autoscaler hysteresis (no subprocesses: stubbed spawn/retire)
# ---------------------------------------------------------------------------


def test_autoscaler_hysteresis(tmp_path, monkeypatch):
    from mxnet_tpu.serve.fleet import _Replica

    class _FakeProc(object):
        pid = 0

    sigs = {"rows": []}
    fleet = Fleet({"builder": "x:y"}, str(tmp_path / "wd"),
                  min_replicas=1, max_replicas=3, scale_up_s=10.0,
                  scale_down_s=30.0, cooldown_s=15.0,
                  signals_fn=lambda: sigs["rows"])
    actions = []
    monkeypatch.setattr(fleet, "_spawn",
                        lambda reason: actions.append(("up", reason)))
    monkeypatch.setattr(
        fleet, "_retire",
        lambda name, reason: actions.append(("down", name, reason)))
    # seed two fake live replicas so scale-down has a "newest" to pick
    for name, spawned in (("r1", 1.0), ("r2", 2.0)):
        rep = _Replica(name, _FakeProc(), None)
        rep.spawned_t = spawned
        fleet._replicas[name] = rep
    hot = [{"name": "r1", "firing": ["serve_p99"], "queue_depth": 0.0}]
    idle = [{"name": "r1", "firing": [], "queue_depth": 0.0}]
    busy_q = [{"name": "r1", "firing": [], "queue_depth": 9.0}]

    # a burn blip shorter than the hold window never scales
    sigs["rows"] = hot
    assert fleet._autoscale(now=0.0) is None
    sigs["rows"] = idle
    assert fleet._autoscale(now=5.0) is None
    assert fleet.target == 1 and not actions

    # sustained burn scales up once the hold window elapses
    sigs["rows"] = hot
    assert fleet._autoscale(now=10.0) is None
    assert fleet._autoscale(now=21.0) == "up"
    assert fleet.target == 2 and actions[-1][0] == "up"
    assert "burn" in actions[-1][1]

    # cooldown gates an immediate second decision, even under burn
    assert fleet._autoscale(now=22.0) is None
    sigs["rows"] = idle
    assert fleet._autoscale(now=30.0) is None          # hot streak resets

    # queue growth alone (no burn rule firing) also counts as hot
    sigs["rows"] = busy_q
    assert fleet._autoscale(now=40.0) is None
    assert fleet._autoscale(now=51.0) == "up"
    assert fleet.target == 3

    # slack must be sustained for the LONGER window to scale down,
    # and it retires the NEWEST replica
    sigs["rows"] = idle
    assert fleet._autoscale(now=70.0) is None
    assert fleet._autoscale(now=90.0) is None          # 20s < 30s hold
    assert fleet._autoscale(now=100.5) == "down"
    assert fleet.target == 2 and actions[-1] == ("down", "r2", "slack")

    # never below min_replicas
    fleet.target = 1
    sigs["rows"] = idle
    fleet._cold_since = None
    fleet._last_scale = None
    assert fleet._autoscale(now=200.0) is None
    assert fleet._autoscale(now=231.0) is None
    assert fleet.target == 1

    # training-side rules must not scale the serving fleet
    sigs["rows"] = [{"name": "r1", "firing": ["mfu_divergence"],
                     "queue_depth": 0.0}]
    fleet.target = 1
    fleet._last_scale = None
    assert fleet._autoscale(now=300.0) is None
    assert fleet._autoscale(now=311.0) is None
    assert fleet.target == 1


# ---------------------------------------------------------------------------
# subprocess fleet: chaos + acceptance
# ---------------------------------------------------------------------------

_BUILDER_SRC = """\
import os
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu.serve import ModelRegistry

def build(spec):
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    sym = mx.sym.softmax(fc, name="prob")
    rng = np.random.RandomState(0)
    path = os.path.join(spec["workdir"], "m-%d.params" % os.getpid())
    mx.nd.save(path, {
        "arg:fc_weight": mx.nd.array(rng.randn(3, 4).astype(np.float32)),
        "arg:fc_bias": mx.nd.array(rng.randn(3).astype(np.float32))})
    with open(path, "rb") as f:
        blob = f.read()
    reg = ModelRegistry(sym.tojson(), blob, input_shapes={"data": (1, 4)})
    reg.warmup()
    return reg
"""


def _write_spec(tmp_path, extra_env=None):
    (tmp_path / "fleet_test_builder.py").write_text(_BUILDER_SRC)
    env = {"JAX_PLATFORMS": "cpu"}
    env.update(extra_env or {})
    return {"builder": "fleet_test_builder:build",
            "pythonpath": [str(tmp_path), REPO_ROOT],
            "workdir": str(tmp_path),
            "env": env}


def _scrape_counter(port, prom_name):
    """Unlabelled counter value from a replica's /metrics, or 0.0."""
    _, body = _get("http://127.0.0.1:%d" % port, "/metrics")
    for line in body.decode().splitlines():
        if line.startswith(prom_name + " "):
            return float(line.split()[-1])
    return 0.0


@pytest.mark.slow
def test_worker_fault_point_flight_recorder_names_killer(tmp_path):
    """A fleet.replica crash fault SIGKILLs the worker mid-serve; its
    own flight ring's last fault record names the killer, and the exit
    code triages as preemption-grade."""
    spec = _write_spec(tmp_path)
    spec_path = tmp_path / "spec.json"
    spec_path.write_text(json.dumps(spec))
    ready = tmp_path / "w.ready.json"
    ring = str(tmp_path / "w.flight.bin")
    env = dict(os.environ)
    env.update(spec["env"])
    env["PYTHONPATH"] = os.pathsep.join([str(tmp_path), REPO_ROOT])
    env["MXNET_FAULT_INJECT"] = "fleet.replica:3:crash"
    env["MXNET_FLIGHT_RECORDER"] = ring
    proc = subprocess.Popen(
        [sys.executable, "-m", "mxnet_tpu.serve.fleet", "--worker",
         "--spec", str(spec_path), "--ready-file", str(ready),
         "--name", "chaos"],
        env=env, cwd=str(tmp_path),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert rc == 137
    assert ProcessSupervisor.is_preemption_rc(rc)
    assert ready.exists()                      # it WAS serving first
    events, _torn = bb.read_events(ring)
    faults = [e for e in events if e["event"] == "fault"]
    assert faults and faults[-1]["point"] == "fleet.replica"
    assert faults[-1]["kind"] == "crash"


@pytest.mark.slow
def test_fleet_acceptance_ramp_kill_drain(tmp_path):
    """The tentpole, end to end on real subprocesses: load ramp scales
    1->2 (the mid-ramp replica spawning warm off the shared warmset
    manifest), a SIGKILL under traffic loses only in-flight requests
    and the fleet re-converges with zero operator action, slack drains
    a replica with zero in-flight lost, zero XLA compiles happen after
    warmup on every replica, and the parent flight ring tells the
    story (replica_death -> scale_up)."""
    cache = tmp_path / "cache"
    cache.mkdir()
    bb.reset()
    bb.configure(str(tmp_path / "parent.flight.bin"))
    spec = _write_spec(
        tmp_path, {"MXNET_COMPILE_CACHE_DIR": str(cache)})
    sigs = {"rows": []}
    fleet = Fleet(spec, str(tmp_path / "wd"), min_replicas=1,
                  max_replicas=2, interval_s=0.15, scale_up_s=0.4,
                  scale_down_s=0.8, cooldown_s=0.6,
                  spawn_timeout_s=120, drain_timeout_s=30,
                  signals_fn=lambda: sigs["rows"])
    results = []
    stop = threading.Event()

    def _traffic():
        while not stop.is_set():
            t0 = time.perf_counter()
            try:
                status, _, _ = _post(
                    front.url, "/predict",
                    {"inputs": {"data": [[1.0, 2.0, 3.0, 4.0]]},
                     "timeout_ms": 20000}, timeout=30)
            except (OSError, urllib.error.URLError):
                status = -1
            results.append((status, time.perf_counter() - t0))
            time.sleep(0.02)

    try:
        fleet.start()
        front = serve_router(fleet.router, port=0)
        baselines = {}

        def _bank_baselines():
            for rep in fleet.status()["replicas"]:
                if rep["port"] and rep["name"] not in baselines:
                    baselines[rep["name"]] = (
                        rep["port"],
                        _scrape_counter(
                            rep["port"],
                            "mxnet_jit_backend_compile_total"))

        _bank_baselines()
        threads = [threading.Thread(target=_traffic, daemon=True)
                   for _ in range(2)]
        for t in threads:
            t.start()

        # ---- ramp: sustained burn scales 1 -> 2, warm off the manifest
        assert (cache / "warmset.json").exists()   # replica 1 wrote it
        sigs["rows"] = [{"name": "r1", "firing": ["serve_p99"],
                         "queue_depth": 0.0}]
        deadline = time.time() + 60
        while time.time() < deadline and fleet.live_count() < 2:
            time.sleep(0.1)
        st = fleet.status()
        assert st["live"] == 2 and fleet.target == 2, st
        mid_ramp = [r for r in st["replicas"] if r["name"] != "r1"][0]
        assert mid_ramp["warm"], st                # manifest was present
        _bank_baselines()
        sigs["rows"] = []                          # hold (hysteresis)

        # ---- SIGKILL the oldest replica under live traffic
        victim = next(r for r in st["replicas"] if r["name"] == "r1")
        os.kill(victim["pid"], signal.SIGKILL)
        deadline = time.time() + 60
        while time.time() < deadline:
            st = fleet.status()
            names = {r["name"] for r in st["replicas"]}
            if st["live"] == 2 and "r1" not in names \
                    and all(r["spawn_s"] for r in st["replicas"]):
                break
            time.sleep(0.1)
        st = fleet.status()
        assert st["live"] == 2 and st["degraded"] is None, st
        _bank_baselines()
        time.sleep(0.5)                            # traffic on new fleet

        # ---- slack: sustained cold drains back to min (hysteresis
        # already held the fleet at 2 while signals were empty-hot-less)
        sigs["rows"] = [{"name": "x", "firing": [], "queue_depth": 0.0}]
        deadline = time.time() + 60
        while time.time() < deadline and (
                fleet.live_count() > 1
                or len(fleet.status()["replicas"]) > 1):
            time.sleep(0.1)
        st = fleet.status()
        assert fleet.live_count() == 1 and fleet.target == 1
        assert len(st["replicas"]) == 1, st    # drained one is GONE
        time.sleep(0.3)
        stop.set()
        for t in threads:
            t.join(timeout=10)

        # ---- only in-flight requests may be lost: a SIGKILL can fail
        # the requests the dead replica was holding (bounded by the
        # router's view of its outstanding count, itself bounded by
        # the 2 client threads), never the rest of the stream
        failures = [s for s, _ in results if s not in (200, 503)]
        assert len(results) > 50
        assert len(failures) <= 2, failures
        ok_lat = sorted(lat for s, lat in results if s == 200)
        assert ok_lat, results
        p99 = ok_lat[min(len(ok_lat) - 1, int(0.99 * len(ok_lat)))]
        assert p99 < 5.0, p99                      # tiny model, huge slack

        # ---- zero XLA compiles after warmup on EVERY replica that is
        # still up, including the warmset-spawned mid-ramp one
        for name, (port, base) in baselines.items():
            if name not in {r["name"] for r in
                            fleet.status()["replicas"]}:
                continue                           # killed/retired
            now_count = _scrape_counter(
                port, "mxnet_jit_backend_compile_total")
            assert now_count == base, (name, base, now_count)
            # and the warm replica really did ride the disk cache
            if name != "r1":
                assert _scrape_counter(
                    port, "mxnet_programs_disk_hits_total") > 0

        # ---- the flight ring tells the story post-mortem
        events, _torn = bb.read_events()
        kinds = [e["event"] for e in events]
        assert "scale_up" in kinds and "scale_down" in kinds \
            and "replica_death" in kinds
        death = next(e for e in events if e["event"] == "replica_death")
        assert death["replica"] == "r1" and death["reason"] == "preempt" \
            and death["respawn"]
        # the respawn scale_up comes AFTER the death record
        i_death = kinds.index("replica_death")
        assert "scale_up" in kinds[i_death:]
        retired = next(e for e in events if e["event"] == "scale_down")
        assert retired["reason"] == "slack"
        front.close()
    finally:
        stop.set()
        fleet.close()
        bb.reset()
