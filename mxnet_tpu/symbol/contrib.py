"""sym.contrib namespace (reference: python/mxnet/symbol/contrib.py) —
the ``_contrib_*`` ops under their public names, mirroring nd.contrib.
"""
from __future__ import annotations

from .register import populate_prefixed, prefixed_getattr

__all__ = populate_prefixed(__name__, "_contrib_")
__getattr__ = prefixed_getattr("_contrib_")


# ---------------------------------------------------------------------------
# symbolic control flow (reference: python/mxnet/symbol/contrib.py:215+
# foreach / while_loop / cond over nnvm subgraphs; src/operator/
# control_flow.cc). TPU-native lowering: the traced body is serialized
# into the node's attrs and evaluated under lax.scan / lax.cond at
# graph-eval time — one compiled step reused across iterations.
# ---------------------------------------------------------------------------

def _as_list(x):
    if isinstance(x, (list, tuple)):
        return list(x), True
    return [x], False


def _group(outs):
    from .symbol import Group
    return Group(outs)


# unique per-trace placeholder prefix: nested control flow must never
# reuse an enclosing trace's bound names, or the inner free-input scan
# would silently capture the wrong variable
_trace_counter = [0]


def _fresh_prefix(kind):
    _trace_counter[0] += 1
    return "_cf%d_%s_" % (_trace_counter[0], kind)


def _trace_mark():
    from .symbol import _node_serial
    return _node_serial[0]


def _extract_body(out_syms, bound_names, mark, pre):
    """Close a traced body into a standalone subgraph.

    Nodes created BEFORE the trace (serial <= mark) are closed-over
    OUTER computations: each such entry is cut into a placeholder
    variable and the original symbol becomes an extra input — computed
    ONCE in the enclosing graph (the reference wires captured outputs
    as subgraph data inputs the same way; re-inlining would re-execute
    them per iteration and fork their RNG). Free VARIABLES keep their
    identity (shared with the enclosing graph). Returns
    (sub, free_names, free_syms, aux_names): free/aux names in the
    order the op will receive them as inputs."""
    from .symbol import Group, Symbol, _Node, _topo
    cut = {}          # (id(node), oi) -> (placeholder_node, 0)
    cloned = {}       # id(node) -> cloned _Node
    captures = []     # (name, Symbol of the outer entry)

    def walk(src, oi):
        if src.is_var:
            return (src, oi)
        if src.serial <= mark:
            key = (id(src), oi)
            if key not in cut:
                nm = "%scap%d" % (pre, len(captures))
                cut[key] = (_Node(None, nm), 0)
                captures.append((nm, Symbol([(src, oi)])))
            return cut[key]
        if id(src) not in cloned:
            new_inputs = [walk(s, o) for (s, o) in src.inputs]
            cloned[id(src)] = _Node(src.op, src.name, src.attrs,
                                    new_inputs, src.is_aux, src.in_names)
        return (cloned[id(src)], oi)

    entries = []
    for s in out_syms:
        assert len(s._entries) == 1
        entries.append(walk(*s._entries[0]))
    sub = Group([Symbol([e]) for e in entries])

    cap_map = dict(captures)
    frees, syms, aux_names, seen = [], [], [], set()
    for node in _topo(sub._entries):
        if node.is_var and node.name not in bound_names \
                and node.name not in seen:
            seen.add(node.name)
            frees.append(node.name)
            cap = cap_map.get(node.name)
            syms.append(Symbol([(node, 0)]) if cap is None else cap)
            if node.is_aux:
                aux_names.append(node.name)
    return sub, frees, syms, tuple(aux_names)


def _register_cf_ops():
    from ..ops.registry import register, get_op

    try:
        get_op("_sym_foreach")
        return
    except Exception:
        pass

    def _foreach_fn(key, data, *rest, graph_json=None, data_name="",
                    state_names=(), free_names=(), aux_names=(),
                    n_outputs=1, train_mode=False, **_ig):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from .symbol import load_json
        from .symbol import _graph_eval_fn
        n_states = len(state_names)
        states = rest[:n_states]
        frees = dict(zip(free_names, rest[n_states:]))
        fn = _graph_eval_fn(load_json(graph_json),
                            is_train=bool(train_mode))
        aux0 = tuple(frees[n] for n in aux_names)

        def step(carry, xt):
            st, aux, i = carry
            env = dict(frees)
            env[data_name] = xt
            env.update(zip(state_names, st))
            env.update(zip(aux_names, aux))   # carried stats win
            k = None if key is None else jax.random.fold_in(key, i)
            outs, new_aux = fn(env, k)
            aux_next = tuple(new_aux.get(n, a)
                             for n, a in zip(aux_names, aux))
            return ((tuple(outs[n_outputs:]), aux_next, i + 1),
                    tuple(outs[:n_outputs]))

        (final_states, final_aux, _), ys = lax.scan(
            step, (tuple(states), aux0, jnp.int32(0)), data)
        result = tuple(ys) + tuple(final_states) + tuple(final_aux)
        return result if len(result) > 1 else result[0]

    register("_sym_foreach", needs_rng=True,
             num_outputs=lambda a: (int(a.get("n_outputs", 1)) +
                                    len(a.get("state_names", ())) +
                                    len(a.get("aux_names", ()))),
             attr_defaults={"graph_json": None, "data_name": "",
                            "state_names": (), "free_names": (),
                            "aux_names": (), "n_outputs": 1,
                            "train_mode": False})(
                 _foreach_fn)

    def _while_fn(key, *rest, cond_json=None, body_json=None,
                  state_names=(), cond_free_names=(), body_free_names=(),
                  aux_names=(), n_outputs=1, max_iterations=0,
                  train_mode=False, **_ig):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from .symbol import load_json, _graph_eval_fn
        n_states = len(state_names)
        states = tuple(rest[:n_states])
        frees = rest[n_states:]
        cf = dict(zip(cond_free_names, frees[:len(cond_free_names)]))
        bf = dict(zip(body_free_names, frees[len(cond_free_names):]))
        cond_fn = _graph_eval_fn(load_json(cond_json), is_train=False)
        body_fn = _graph_eval_fn(load_json(body_json),
                                 is_train=bool(train_mode))
        aux0 = tuple(bf[n] for n in aux_names)

        def pred(st):
            env = dict(zip(state_names, st))
            env.update(cf)
            (p,), _ = cond_fn(env, None)
            return p.reshape(()).astype(bool)

        def step(carry, i):
            st, aux, active = carry
            env = dict(bf)
            env.update(zip(state_names, st))
            env.update(zip(aux_names, aux))
            k = None if key is None else jax.random.fold_in(key, i)
            outs, new_aux = body_fn(env, k)
            new_st = tuple(
                jnp.where(active, n, o) for n, o in
                zip(outs[n_outputs:], st))
            aux_next = tuple(
                jnp.where(active, new_aux.get(n, a), a)
                for n, a in zip(aux_names, aux))
            ys = tuple(jnp.where(active, o, jnp.zeros_like(o))
                       for o in outs[:n_outputs])
            nxt_active = jnp.logical_and(active, pred(new_st))
            return (new_st, aux_next, nxt_active), ys

        active0 = pred(states)
        (final, final_aux, _a), ys = lax.scan(
            step, (states, aux0, active0),
            jnp.arange(int(max_iterations)))
        result = tuple(ys) + tuple(final) + tuple(final_aux)
        return result if len(result) > 1 else result[0]

    register("_sym_while_loop", needs_rng=True,
             num_outputs=lambda a: (int(a.get("n_outputs", 1)) +
                                    len(a.get("state_names", ())) +
                                    len(a.get("aux_names", ()))),
             attr_defaults={"cond_json": None, "body_json": None,
                            "state_names": (), "cond_free_names": (),
                            "body_free_names": (), "aux_names": (),
                            "n_outputs": 1, "max_iterations": 0,
                            "train_mode": False})(
                 _while_fn)

    def _cond_fn(key, *rest, pred_json=None, then_json=None,
                 else_json=None, input_names=(), pred_free_names=(),
                 then_free_names=(), else_free_names=(), aux_names=(),
                 n_outputs=1, train_mode=False, **_ig):
        import jax
        from jax import lax
        from .symbol import load_json, _graph_eval_fn
        n_in = len(input_names)
        ins = dict(zip(input_names, rest[:n_in]))
        frees = rest[n_in:]
        np_, nt = len(pred_free_names), len(then_free_names)
        pf = dict(zip(pred_free_names, frees[:np_]))
        tf = dict(zip(then_free_names, frees[np_:np_ + nt]))
        ef = dict(zip(else_free_names, frees[np_ + nt:]))
        pred_fn = _graph_eval_fn(load_json(pred_json), is_train=False)
        then_fn = _graph_eval_fn(load_json(then_json),
                                 is_train=bool(train_mode))
        else_fn = _graph_eval_fn(load_json(else_json),
                                 is_train=bool(train_mode))
        env_p = dict(ins)
        env_p.update(pf)
        (p,), _ = pred_fn(env_p, None)
        aux_env = dict(tf)
        aux_env.update(ef)
        aux0 = tuple(aux_env[n] for n in aux_names)

        def _branch(fn, branch_frees):
            def run(_):
                env = dict(ins)
                env.update(branch_frees)
                outs, new_aux = fn(env, key)
                # untaken-branch aux stays put; the taken branch's
                # updates win
                return tuple(outs) + tuple(
                    new_aux.get(n, a) for n, a in zip(aux_names, aux0))
            return run

        result = lax.cond(p.reshape(()).astype(bool),
                          _branch(then_fn, tf), _branch(else_fn, ef),
                          operand=None)
        return result if len(result) > 1 else result[0]

    register("_sym_cond", needs_rng=True,
             num_outputs=lambda a: (int(a.get("n_outputs", 1)) +
                                    len(a.get("aux_names", ()))),
             attr_defaults={"pred_json": None, "then_json": None,
                            "else_json": None, "input_names": (),
                            "pred_free_names": (), "then_free_names": (),
                            "else_free_names": (), "aux_names": (),
                            "n_outputs": 1, "train_mode": False})(
                 _cond_fn)


_register_cf_ops()


def foreach(body, data, init_states, name="foreach"):
    """Scan ``body(data_t, states) -> (outputs, new_states)`` over axis
    0 of ``data`` symbolically (reference: symbol/contrib.py:215).
    Returns (outputs, final_states): outputs stacked on axis 0."""
    from .symbol import var as _var
    from .register import make_op_func
    from ..ops.registry import get_op
    pre = _fresh_prefix("foreach")
    states, states_list = _as_list(init_states)
    mark = _trace_mark()
    dvar = _var(pre + "data")
    svars = [_var(pre + "state%d" % i) for i in range(len(states))]
    outs, new_states = body(dvar, svars if states_list else svars[0])
    outs, outs_list = _as_list(outs)
    new_states, _ = _as_list(new_states)
    assert len(new_states) == len(states), \
        "body must return as many states as it was given"
    bound = [pre + "data"] + [pre + "state%d" % i
                              for i in range(len(states))]
    sub, free_names, free_syms, aux_names = _extract_body(
        outs + new_states, set(bound), mark, pre)
    node = make_op_func(get_op("_sym_foreach"))(
        data, *states, *free_syms, name=name,
        graph_json=sub.tojson(), data_name=bound[0],
        state_names=tuple(bound[1:]), free_names=tuple(free_names),
        aux_names=aux_names, n_outputs=len(outs))
    outputs = [node[i] for i in range(len(outs))]
    finals = [node[len(outs) + i] for i in range(len(states))]
    return (outputs if outs_list else outputs[0],
            finals if states_list else finals[0])


def while_loop(cond, func, loop_vars, max_iterations, name="while_loop"):
    """``while cond(states): outputs, states = func(states)`` with a
    static iteration bound (reference: symbol/contrib.py while_loop).
    Outputs are padded with zeros past termination; lowers to a masked
    lax.scan so the loop stays differentiable."""
    from .symbol import var as _var
    from .register import make_op_func
    from ..ops.registry import get_op
    pre = _fresh_prefix("while")
    states, states_list = _as_list(loop_vars)
    mark = _trace_mark()
    svars = [_var(pre + "state%d" % i) for i in range(len(states))]
    packed = svars if states_list else svars[0]
    pred = cond(packed)
    outs, new_states = func(packed)
    outs, outs_list = _as_list(outs)
    new_states, _ = _as_list(new_states)
    assert len(new_states) == len(states)
    bound = set(pre + "state%d" % i for i in range(len(states)))
    csub, c_free, c_syms, _c_aux = _extract_body([pred], bound, mark,
                                                 pre + "c")
    bsub, b_free, b_syms, aux_names = _extract_body(
        outs + new_states, bound, mark, pre + "b")
    node = make_op_func(get_op("_sym_while_loop"))(
        *states, *c_syms, *b_syms, name=name,
        cond_json=csub.tojson(), body_json=bsub.tojson(),
        state_names=tuple(pre + "state%d" % i
                          for i in range(len(states))),
        cond_free_names=tuple(c_free), body_free_names=tuple(b_free),
        aux_names=aux_names, n_outputs=len(outs),
        max_iterations=int(max_iterations))
    outputs = [node[i] for i in range(len(outs))]
    finals = [node[len(outs) + i] for i in range(len(states))]
    return (outputs if outs_list else outputs[0],
            finals if states_list else finals[0])


def cond(pred, then_func, else_func, inputs=None, name="cond"):
    """Symbolic if/else (reference: symbol/contrib.py cond). ``pred``,
    ``then_func``, ``else_func`` are nullary callables over closed-over
    symbols (or over ``inputs`` symbols when given); both branches must
    produce matching shapes."""
    from .symbol import var as _var
    from .register import make_op_func
    from ..ops.registry import get_op
    pre = _fresh_prefix("cond")
    inputs, _ = _as_list(inputs if inputs is not None else [])
    in_names = [pre + "in%d" % i for i in range(len(inputs))]
    mark = _trace_mark()
    in_vars = [_var(n) for n in in_names]

    def run(f):
        out = f(*in_vars) if inputs else f()
        return _as_list(out)

    p_outs, _ = run(pred)
    t_outs, t_list = run(then_func)
    e_outs, _ = run(else_func)
    assert len(t_outs) == len(e_outs), \
        "then/else branches must produce the same number of outputs"
    bound = set(in_names)
    psub, p_free, p_syms, _pa = _extract_body(p_outs, bound, mark,
                                              pre + "p")
    tsub, t_free, t_syms, t_aux = _extract_body(t_outs, bound, mark,
                                                pre + "t")
    esub, e_free, e_syms, e_aux = _extract_body(e_outs, bound, mark,
                                                pre + "e")
    aux_names = tuple(t_aux) + tuple(a for a in e_aux if a not in t_aux)
    node = make_op_func(get_op("_sym_cond"))(
        *inputs, *p_syms, *t_syms, *e_syms, name=name,
        pred_json=psub.tojson(), then_json=tsub.tojson(),
        else_json=esub.tojson(), input_names=tuple(in_names),
        pred_free_names=tuple(p_free), then_free_names=tuple(t_free),
        else_free_names=tuple(e_free), aux_names=aux_names,
        n_outputs=len(t_outs))
    outs = [node[i] for i in range(len(t_outs))]
    return outs if t_list else outs[0]


__all__ = list(__all__) + ["foreach", "while_loop", "cond"]
