"""Online inference serving: dynamic micro-batching over bucketed
shape-specialized XLA programs.

The serving path the north star ("serve heavy traffic from millions of
users") needs on top of the one-request ``serving.Predictor``:

* :mod:`~mxnet_tpu.serve.batching` — batch buckets + axis-0 padding,
  bounding the compile surface to ``len(buckets)`` programs;
* :mod:`~mxnet_tpu.serve.engine` — :class:`InferenceEngine`: bounded
  queue, request coalescing, per-request deadlines, admission control,
  ahead-of-time bucket warmup, graceful drain;
* :mod:`~mxnet_tpu.serve.http` — stdlib HTTP frontend (``POST
  /predict`` + ``POST /generate`` token streaming + ``/metrics`` +
  ``/healthz``) returning 503 on backpressure and 504 on deadline
  expiry;
* :mod:`~mxnet_tpu.serve.registry` — :class:`ModelRegistry`: atomic
  weight hot-swap with zero dropped requests (attached decode
  sessions drain first); ``swap(quantized=artifact)`` flips to a
  calibrated int8 variant (mxnet_tpu/quantize/) and
  ``enable_shadow(artifact, fraction)`` canaries it under live
  traffic with drift histograms (docs/quantization.md);
* :mod:`~mxnet_tpu.serve.decode` — :class:`DecodeEngine`: continuous
  batching for autoregressive decode — iteration-level scheduling,
  bucketed prefill, streaming tokens (docs/decode_serving.md);
* :mod:`~mxnet_tpu.serve.kv_pages` — :class:`PagePool`: the HBM
  KV-cache page allocator behind the decode engine's block tables;
* :mod:`~mxnet_tpu.serve.router` — the fleet frontend: one port over
  N replicas, least-outstanding load balancing + consistent-hash
  prefix affinity for ``/generate``, ejection + retry on vanished
  replicas, end-to-end trace grafting;
* :mod:`~mxnet_tpu.serve.fleet` — :class:`Fleet`: replica subprocess
  lifecycle (warmset-fast spawn, drain-then-SIGTERM retirement,
  preemption-vs-failure death triage) and the SLO-driven autoscaler
  over each replica's ``/alerts`` burn state (docs/serving.md "Fleet
  tier").

Quick start::

    import mxnet_tpu as mx

    reg = mx.serve.ModelRegistry(symbol_json, param_bytes,
                                 input_shapes={"data": (1, 3, 224, 224)})
    reg.warmup()                              # compile every bucket
    srv = mx.serve.serve_http(reg, port=8080)
    ...
    reg.swap(new_param_bytes)                 # zero-downtime weight update
    srv.close(); reg.close()

Tuning and architecture: docs/serving.md. Knobs: ``MXNET_SERVE_*``
(``python -m mxnet_tpu.config``).
"""
from .batching import (pad_axis0, parse_buckets, pick_bucket,
                       power_of_two_buckets, unpad_axis0,
                       validate_buckets)
from .engine import (DeadlineExceededError, EngineClosedError,
                     InferenceEngine, QueueFullError, ServeConfig,
                     engines_status)
from .kv_pages import PagePool, PagePoolExhausted
from .decode import DecodeConfig, DecodeEngine, DecodeSession
from .http import ServeHTTPServer, serve_http
from .registry import ModelRegistry
from .router import (NoLiveReplicaError, Router, RouterHTTPServer,
                     serve_router)
from .fleet import Fleet

__all__ = ["InferenceEngine", "ServeConfig", "ModelRegistry", "serve_http",
           "ServeHTTPServer", "QueueFullError", "DeadlineExceededError",
           "EngineClosedError", "engines_status", "power_of_two_buckets",
           "parse_buckets", "validate_buckets", "pick_bucket", "pad_axis0",
           "unpad_axis0", "DecodeConfig", "DecodeEngine", "DecodeSession",
           "PagePool", "PagePoolExhausted", "Router", "RouterHTTPServer",
           "serve_router", "NoLiveReplicaError", "Fleet"]
