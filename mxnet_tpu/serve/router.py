"""Fleet frontend: one port, N serve_http replicas behind it.

The router is the layer that multiplies one replica into a fleet: it
accepts ``POST /predict`` and ``POST /generate`` on a single frontend
port and forwards each request to one of N replica subprocesses (each
a :func:`~mxnet_tpu.serve.http.serve_http` worker on its own port),
relaying the response — including ``/generate``'s chunked ndjson token
stream — back to the client.

Routing policy (docs/serving.md "Fleet tier"):

* **least-outstanding-requests** for ``/predict`` (and as the
  fallback): the replica with the fewest requests currently in flight
  through this router wins — outstanding count tracks *actual* load
  including slow decodes, where round-robin would pile onto a stuck
  replica.
* **consistent-hash prefix affinity** for ``/generate``: the hash of
  the prompt *head* (first ``MXNET_FLEET_PREFIX_TOKENS`` token ids)
  picks a replica on a 64-vnode hash ring, so every request of a
  prefix family (same system prompt / few-shot header, multi-turn
  continuations) lands on the same replica — the signal a prefix KV
  cache needs to pay off. Affinity **yields to load**: when the pinned
  replica's outstanding count exceeds the fleet minimum by more than
  ``MXNET_FLEET_AFFINITY_SLACK``, the request falls back to
  least-outstanding (``router/affinity_yields_total``) instead of
  queueing behind a hot prefix.
* **ejection + retry**: a connection failure before the response
  status line arrives (refused, reset, or the ``router.forward``
  fault point firing) looks like a vanished replica — the router
  ejects it (``router/ejections_total``; no new picks until the fleet
  re-admits or replaces it) and retries the next-best replica, up to
  ``MXNET_FLEET_FORWARD_RETRIES`` times. Once a status line has been
  received there are no retries: a mid-stream death surfaces as an
  in-band ``{"error": ..., "code": 502}`` line, exactly like a
  replica-local mid-stream failure.

Per-request propagation: the router forwards ``X-Request-Id``
verbatim, the *remaining* deadline budget as ``X-Deadline-Ms`` (so a
replica gives up no later than the router would), and the forward
span's trace context as ``X-Trace-Context``; the replica ships its
span bundle back in ``X-Trace-Spans`` and the router grafts it —
clock-rebased — into its own trace, so ``/traces`` on the router
shows one end-to-end tree: ``router.request`` → ``router.forward`` →
the replica's ``http.request`` and everything under it.

The router process also mounts ``/healthz`` (ok while >= 1 replica is
routable), ``/metrics``, ``/traces``, ``/alerts``, and ``/fleet``
(live per-replica state plus, when a :class:`~mxnet_tpu.serve.fleet.
Fleet` is attached, the autoscaler's view).
"""
from __future__ import annotations

import bisect
import hashlib
import http.client
import json
import threading
import time

from ..base import MXNetError
from ..config import get as _cfg
from .. import fault as _fault
from .. import telemetry as _tm
from .. import tracing as _tr
from .engine import DeadlineExceededError

__all__ = ["Router", "RouterHTTPServer", "serve_router",
           "NoLiveReplicaError"]

_monotonic = time.perf_counter
_VNODES = 64


class NoLiveReplicaError(MXNetError):
    """Every replica is ejected, quiescing, or gone (mapped to 503)."""


def _hash64(s):
    return int(hashlib.md5(s.encode("utf-8")).hexdigest()[:16], 16)


class ReplicaHandle(object):
    """Router-side state for one replica (URL + in-flight count)."""

    __slots__ = ("name", "host", "port", "outstanding", "healthy",
                 "quiescing")

    def __init__(self, name, host, port):
        self.name = name
        self.host = host
        self.port = int(port)
        self.outstanding = 0
        self.healthy = True
        self.quiescing = False

    @property
    def url(self):
        return "http://%s:%d" % (self.host, self.port)

    def snapshot(self):
        return {"name": self.name, "url": self.url,
                "outstanding": self.outstanding,
                "healthy": self.healthy, "quiescing": self.quiescing}


class _Forward(object):
    """One successfully-opened forward: the picked replica, the live
    connection/response, and the pre-allocated ``router.forward`` span
    id the replica is parenting its spans under. ``close()`` records
    the span, observes the latency histogram, and releases the
    outstanding slot — callers run it in a ``finally``."""

    __slots__ = ("router", "replica", "conn", "resp", "ctx", "span_id",
                 "t0", "attempt", "_done")

    def __init__(self, router, replica, conn, resp, ctx, span_id, t0,
                 attempt):
        self.router = router
        self.replica = replica
        self.conn = conn
        self.resp = resp
        self.ctx = ctx
        self.span_id = span_id
        self.t0 = t0
        self.attempt = attempt
        self._done = False

    def graft(self):
        """Pull the replica's span bundle out of ``X-Trace-Spans`` and
        graft it into the router's trace (clock-rebased onto this
        process's perf_counter epoch). Buffered replies only — the
        streaming path has no response trailer to carry spans."""
        if self.ctx is None:
            return
        hdr = self.resp.getheader("X-Trace-Spans")
        if not hdr:
            return
        try:
            bundle = json.loads(hdr)
            clk = bundle.get("clock")
            clock = ((clk[0], float(clk[1]), _monotonic())
                     if clk else None)
            _tr.graft(bundle.get("spans") or [], ctx=self.ctx,
                      clock=clock)
        except (ValueError, TypeError, KeyError, IndexError):
            pass

    def close(self, status="ok"):
        if self._done:
            return
        self._done = True
        t1 = _monotonic()
        if self.ctx is not None:
            _tr.record_span("router.forward", self.ctx, self.t0, t1,
                            attrs={"replica": self.replica.name,
                                   "attempt": self.attempt},
                            span_id=self.span_id, status=status)
        if _tm._enabled:
            _tm.histogram("router/forward_seconds",
                          "Router-side forward latency (pick to last "
                          "byte relayed)").observe(t1 - self.t0)
        self.router._release(self.replica)
        try:
            self.conn.close()
        except OSError:
            pass


class Router(object):
    """Replica table + hash ring + forward policy (no HTTP server of
    its own — :func:`serve_router` mounts one on top; unit tests drive
    :meth:`pick` / :meth:`open_forward` directly)."""

    def __init__(self, prefix_tokens=None, affinity_slack=None,
                 forward_retries=None, vnodes=_VNODES):
        self._lock = threading.Lock()
        self._replicas = {}              # name -> ReplicaHandle
        self._ring = []                  # sorted [(hash, name), ...]
        self._vnodes = int(vnodes)
        self.prefix_tokens = int(_cfg("MXNET_FLEET_PREFIX_TOKENS")
                                 if prefix_tokens is None
                                 else prefix_tokens)
        self.affinity_slack = int(_cfg("MXNET_FLEET_AFFINITY_SLACK")
                                  if affinity_slack is None
                                  else affinity_slack)
        self.forward_retries = int(_cfg("MXNET_FLEET_FORWARD_RETRIES")
                                   if forward_retries is None
                                   else forward_retries)
        self._fleet_status_fn = None

    # -- replica table ---------------------------------------------------

    def add(self, name, host, port):
        """Admit a replica (or re-admit one previously ejected under
        the same name: its health resets)."""
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None:
                rep = ReplicaHandle(name, host, port)
                self._replicas[name] = rep
                for i in range(self._vnodes):
                    h = _hash64("%s#%d" % (name, i))
                    bisect.insort(self._ring, (h, name))
            else:
                rep.host, rep.port = host, int(port)
            rep.healthy = True
            rep.quiescing = False
            return rep

    def remove(self, name):
        """Forget a replica entirely (fleet retirement / death)."""
        with self._lock:
            self._replicas.pop(name, None)
            self._ring = [(h, n) for h, n in self._ring if n != name]

    def quiesce(self, name):
        """Stop new picks to ``name`` (retirement step 1); returns its
        current outstanding count so the caller can wait for drain."""
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None:
                return 0
            rep.quiescing = True
            return rep.outstanding

    def eject(self, name, reason=""):
        """Mark a replica unroutable after a connection failure; the
        fleet's monitor re-admits (or replaces) it."""
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None or not rep.healthy:
                return
            rep.healthy = False
        if _tm._enabled:
            _tm.counter("router/ejections_total",
                        "Replicas ejected on connection failure",
                        ("reason",)).labels(reason or "conn").inc()

    def outstanding(self, name):
        with self._lock:
            rep = self._replicas.get(name)
            return 0 if rep is None else rep.outstanding

    def replicas(self):
        with self._lock:
            return [r.snapshot() for r in self._replicas.values()]

    def live_count(self):
        with self._lock:
            return sum(1 for r in self._replicas.values()
                       if r.healthy and not r.quiescing)

    # -- policy ----------------------------------------------------------

    def affinity_key(self, path, body):
        """The consistent-hash key for a request, or None when the
        request has no prefix to pin (``/predict``, malformed body).
        The key is the prompt *head* — requests sharing their first
        ``prefix_tokens`` ids share a key."""
        if path != "/generate" or self.prefix_tokens <= 0:
            return None
        try:
            payload = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError, AttributeError):
            return None
        prompt = payload if isinstance(payload, list) \
            else payload.get("prompt") if isinstance(payload, dict) \
            else None
        if not isinstance(prompt, list) or not prompt:
            return None
        return ",".join(str(t) for t in prompt[:self.prefix_tokens])

    def _ring_lookup_locked(self, key, exclude):
        if not self._ring:
            return None
        h = _hash64(key)
        i = bisect.bisect_right(self._ring, (h, ""))
        for step in range(len(self._ring)):
            _, name = self._ring[(i + step) % len(self._ring)]
            rep = self._replicas.get(name)
            if rep is not None and rep.healthy and not rep.quiescing \
                    and name not in exclude:
                return rep
        return None

    def pick(self, affinity_key=None, exclude=()):
        """Pick a replica and take an outstanding slot on it. Returns
        ``(replica, affinity_hit)``; raises :class:`NoLiveReplicaError`
        when nothing is routable (minus ``exclude``)."""
        exclude = set(exclude)
        with self._lock:
            live = [r for r in self._replicas.values()
                    if r.healthy and not r.quiescing
                    and r.name not in exclude]
            if not live:
                raise NoLiveReplicaError(
                    "no live replica (fleet has %d registered, %d "
                    "excluded this attempt)"
                    % (len(self._replicas), len(exclude)))
            chosen, hit = None, False
            if affinity_key is not None:
                pinned = self._ring_lookup_locked(affinity_key, exclude)
                if pinned is not None:
                    min_out = min(r.outstanding for r in live)
                    if pinned.outstanding - min_out \
                            <= self.affinity_slack:
                        chosen, hit = pinned, True
                    elif _tm._enabled:
                        _tm.counter(
                            "router/affinity_yields_total",
                            "Prefix-affinity picks abandoned because "
                            "the pinned replica was saturated").inc()
            if chosen is None:
                chosen = min(live, key=lambda r: (r.outstanding, r.name))
            chosen.outstanding += 1
        if hit and _tm._enabled:
            _tm.counter("router/affinity_hits_total",
                        "Generate requests routed to their prefix-"
                        "affine replica").inc()
        return chosen, hit

    def _release(self, rep):
        with self._lock:
            rep.outstanding = max(0, rep.outstanding - 1)

    # -- forwarding ------------------------------------------------------

    def open_forward(self, path, body, rid=None, ctx=None, deadline=None):
        """Pick a replica and forward one POST until its response
        status line arrives; returns a :class:`_Forward`. Connection
        failures before the status line eject the replica and retry
        the next-best one (``forward_retries`` extra attempts); after
        the status line the exchange is committed to that replica."""
        tried = set()
        last_err = None
        for attempt in range(self.forward_retries + 1):
            if deadline is not None:
                remaining_ms = (deadline - _monotonic()) * 1e3
                if remaining_ms <= 0:
                    raise DeadlineExceededError(
                        "deadline expired in the router after %d "
                        "forward attempt(s)" % attempt)
            else:
                remaining_ms = None
            try:
                rep, _hit = self.pick(
                    self.affinity_key(path, body), exclude=tried)
            except NoLiveReplicaError:
                if last_err is not None:
                    raise NoLiveReplicaError(
                        "no live replica left after %d attempt(s); "
                        "last error: %s" % (attempt, last_err))
                raise
            sid = _tr.new_span_id() if (ctx is not None
                                        and ctx.sampled) else None
            t0 = _monotonic()
            headers = {"Content-Type": "application/json"}
            if rid:
                headers["X-Request-Id"] = rid
            if remaining_ms is not None:
                headers["X-Deadline-Ms"] = "%.1f" % max(0.0,
                                                        remaining_ms)
            if sid is not None:
                headers["X-Trace-Context"] = json.dumps(
                    {"trace_id": ctx.trace_id, "span_id": sid,
                     "sampled": True})
            conn = None
            try:
                _fault.inject("router.forward")
                conn = http.client.HTTPConnection(rep.host, rep.port)
                conn.request("POST", path, body, headers)
                resp = conn.getresponse()
            except (OSError, http.client.HTTPException,
                    _fault.FaultInjected) as e:
                if conn is not None:
                    try:
                        conn.close()
                    except OSError:
                        pass
                self._release(rep)
                self.eject(rep.name, reason="conn")
                tried.add(rep.name)
                last_err = e
                if sid is not None:
                    _tr.record_span("router.forward", ctx, t0,
                                    _monotonic(),
                                    attrs={"replica": rep.name,
                                           "attempt": attempt,
                                           "error": str(e)},
                                    span_id=sid, status="error")
                if _tm._enabled:
                    _tm.counter("router/forward_retries_total",
                                "Forward attempts retried on another "
                                "replica after a connection "
                                "failure").inc()
                continue
            return _Forward(self, rep, conn, resp, ctx, sid, t0,
                            attempt)
        raise NoLiveReplicaError(
            "every forward attempt failed (%d tried); last error: %s"
            % (len(tried), last_err))

    # -- status ----------------------------------------------------------

    def set_fleet_status_fn(self, fn):
        """The owning Fleet installs its status callback here so the
        router's ``/fleet`` endpoint shows the autoscaler's view."""
        self._fleet_status_fn = fn

    def status(self):
        out = {"replicas": self.replicas(),
               "live": self.live_count(),
               "prefix_tokens": self.prefix_tokens,
               "affinity_slack": self.affinity_slack}
        fn = self._fleet_status_fn
        if fn is not None:
            try:
                out["fleet"] = fn()
            except Exception as e:      # status must never 500
                out["fleet"] = {"error": str(e)}
        return out


# ---------------------------------------------------------------------------
# HTTP frontend
# ---------------------------------------------------------------------------

class RouterHTTPServer(object):
    """Handle on a running router frontend (from :func:`serve_router`)."""

    def __init__(self, httpd, thread, router):
        self._httpd = httpd
        self._thread = thread
        self.router = router
        self.port = httpd.server_address[1]
        self.url = "http://%s:%d" % (httpd.server_address[0], self.port)

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    stop = close

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _body_timeout_ms(body):
    """Best-effort read of the request body's ``timeout_ms`` (the
    router's deadline view; malformed bodies forward as-is and 400 at
    the replica)."""
    try:
        payload = json.loads(body.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(payload, dict):
        return None
    t = payload.get("timeout_ms")
    return float(t) if isinstance(t, (int, float)) else None


def serve_router(router, port=0, addr="127.0.0.1"):
    """Start the fleet frontend over ``router``; returns a
    :class:`RouterHTTPServer` (``port=0`` picks a free port)."""
    import http.server
    from .http import _REQ_ID_RE

    class _Handler(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        _rid = None

        def _reply(self, code, payload, ctype="application/json",
                   headers=()):
            body = (json.dumps(payload).encode() + b"\n"
                    if not isinstance(payload, bytes) else payload)
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            if self._rid is not None:
                self.send_header("X-Request-Id", self._rid)
            for k, v in headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            self._rid = None
            path, _, query = self.path.partition("?")
            if path == "/metrics":
                self._reply(200, _tm.render_prometheus().encode(),
                            ctype="text/plain; version=0.0.4; "
                                  "charset=utf-8")
            elif path == "/healthz":
                if router.live_count() > 0:
                    self._reply(200, b"ok\n",
                                ctype="text/plain; charset=utf-8")
                else:
                    self._reply(503, b"no-replicas\n",
                                ctype="text/plain; charset=utf-8")
            elif path == "/fleet":
                self._reply(200, router.status())
            elif path == "/traces":
                code, payload = _tr.traces_endpoint(query)
                self._reply(code, payload)
            elif path == "/alerts":
                from .. import health as _hl
                code, payload = _hl.alerts_endpoint(query)
                self._reply(code, payload)
            else:
                self._reply(404, {"error": "not found"})

        def _chunk(self, data):
            self.wfile.write(b"%X\r\n" % len(data) + data + b"\r\n")
            self.wfile.flush()

        def do_POST(self):
            self._rid = None
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length)
            path = self.path.split("?")[0]
            if path not in ("/predict", "/generate"):
                self._reply(404, {"error": "not found"})
                return
            rid = self.headers.get("X-Request-Id", "")
            if not _REQ_ID_RE.match(rid):
                rid = _tr.new_trace_id()
            self._rid = rid
            if _tm._enabled:
                _tm.counter("router/requests_total",
                            "Requests accepted by the fleet router",
                            ("path",)).labels(path).inc()
            timeout_ms = _body_timeout_ms(body)
            deadline = (_monotonic() + timeout_ms / 1e3
                        if timeout_ms is not None else None)
            with _tr.start_span("router.request", trace_id=rid,
                                attrs={"path": path}) as span:
                self._route(path, body, rid, deadline, span)

        def _route(self, path, body, rid, deadline, span):
            try:
                fwd = router.open_forward(path, body, rid=rid,
                                          ctx=span.ctx,
                                          deadline=deadline)
            except NoLiveReplicaError as e:
                span.set_attr("http_status", 503)
                _tr.mark_error(e, ctx=span.ctx)
                self._reply(503, {"error": str(e)},
                            headers=(("Retry-After", "1"),))
                return
            except DeadlineExceededError as e:
                span.set_attr("http_status", 504)
                _tr.mark_error(e, ctx=span.ctx)
                self._reply(504, {"error": str(e)})
                return
            status = "ok"
            try:
                resp = fwd.resp
                te = (resp.getheader("Transfer-Encoding") or "").lower()
                span.set_attr("replica", fwd.replica.name)
                span.set_attr("http_status", resp.status)
                if te == "chunked":
                    status = self._relay_stream(fwd, span)
                else:
                    payload = resp.read()
                    fwd.graft()
                    if resp.status >= 500:
                        _tr.mark_error("replica returned %d"
                                       % resp.status, ctx=span.ctx)
                    extra = []
                    ra = resp.getheader("Retry-After")
                    if ra:
                        extra.append(("Retry-After", ra))
                    self._reply(resp.status, payload,
                                ctype=resp.getheader(
                                    "Content-Type",
                                    "application/json"),
                                headers=tuple(extra))
            finally:
                fwd.close(status=status)

        def _relay_stream(self, fwd, span):
            """Relay a chunked ndjson token stream line-by-line. A
            replica death mid-stream becomes an in-band error line (the
            status line is already out — same contract as a replica-
            local mid-stream failure); a client hang-up just stops the
            relay."""
            resp = fwd.resp
            self.send_response(resp.status)
            self.send_header("Content-Type",
                             resp.getheader("Content-Type",
                                            "application/x-ndjson"))
            self.send_header("Transfer-Encoding", "chunked")
            if self._rid is not None:
                self.send_header("X-Request-Id", self._rid)
            self.end_headers()
            upstream_err = None
            try:
                while True:
                    try:
                        line = resp.readline()
                    except (OSError, http.client.HTTPException) as e:
                        upstream_err = e
                        break
                    if not line:
                        break
                    self._chunk(line)
                if upstream_err is not None:
                    router.eject(fwd.replica.name, reason="stream")
                    _tr.mark_error(upstream_err, ctx=span.ctx)
                    span.set_attr("http_status", 502)
                    self._chunk(json.dumps(
                        {"error": "replica died mid-stream: %s"
                                  % upstream_err,
                         "code": 502}).encode() + b"\n")
                self.wfile.write(b"0\r\n\r\n")
                self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                pass
            return "error" if upstream_err is not None else "ok"

        def log_message(self, *args):
            pass

    httpd = http.server.ThreadingHTTPServer((addr, port), _Handler)
    httpd.daemon_threads = True
    thread = threading.Thread(target=httpd.serve_forever,
                              name="mxnet-serve-router", daemon=True)
    thread.start()
    return RouterHTTPServer(httpd, thread, router)
