"""Imperative autograd.

Reference: python/mxnet/autograd.py + src/imperative/imperative.cc
(RecordOp/Backward, SURVEY.md §3.2).

TPU-native design: the tape records (op, attrs, input values, node links)
per eager call. ``backward`` walks the tape in reverse and computes each
entry's input cotangents with a **jitted, cached ``jax.vjp``** of the op's
pure function — per-op FGradient registrations (the reference's
``pass::Gradient`` machinery) are unnecessary because JAX differentiates
the op body directly. Re-running the forward inside vjp is deliberate
rematerialization: it trades HBM for FLOPs, which is the right default on
TPU (SURVEY.md §7 notes XLA buffer reuse replaces PlanMemory).
"""
from __future__ import annotations

import functools
import threading
import weakref

from .base import MXNetError, canonical_attrs

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "mark_variables", "mark_variable", "backward",
           "grad", "set_recording", "set_training", "record_op", "Function"]

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
    return _state


def is_recording():
    return _st().recording


def is_training():
    return _st().training


def set_recording(is_record):
    prev = _st().recording
    _state.recording = bool(is_record)
    return prev


def set_training(train):
    prev = _st().training
    _state.training = bool(train)
    return prev


class _RecordingScope:
    def __init__(self, is_record, train):
        self._is_record = is_record
        self._train = train

    def __enter__(self):
        self._prev_r = (set_recording(self._is_record)
                        if self._is_record is not None else None)
        self._prev_t = (set_training(self._train)
                        if self._train is not None else None)
        return self

    def __exit__(self, *exc):
        if self._is_record is not None:
            set_recording(self._prev_r)
        if self._train is not None:
            set_training(self._prev_t)


def record(train_mode=True):
    """Scope enabling tape recording (reference: autograd.py:122)."""
    return _RecordingScope(True, train_mode)


def pause(train_mode=False):
    return _RecordingScope(False, train_mode)


def train_mode():
    return _RecordingScope(None, True)


def predict_mode():
    return _RecordingScope(None, False)


# ---------------------------------------------------------------------------
# tape structures
# ---------------------------------------------------------------------------

class AGNode:
    """Autograd graph node: one output of one recorded op, or a leaf
    variable (the analog of Imperative::AGInfo + nnvm NodeEntry,
    include/mxnet/imperative.h:39)."""

    __slots__ = ("entry", "out_index", "array_ref", "grad_req", "__weakref__")

    def __init__(self, entry=None, out_index=0, array=None, grad_req=None):
        self.entry = entry
        self.out_index = out_index
        self.array_ref = weakref.ref(array) if array is not None else None
        self.grad_req = grad_req

    @property
    def is_leaf(self):
        return self.entry is None


class TapeEntry:
    __slots__ = ("op", "attrs", "input_nodes", "input_values", "key",
                 "n_outputs", "output_nodes", "freed", "__weakref__")

    def __init__(self, op, attrs, input_nodes, input_values, key, n_outputs):
        self.op = op
        self.attrs = attrs
        self.input_nodes = input_nodes
        self.input_values = input_values
        self.key = key
        self.n_outputs = n_outputs
        self.output_nodes = []
        self.freed = False
        _UNFREED_ENTRIES.add(self)


# Entries whose saved input buffers are still live. Optimizer buffer
# donation (ops/registry.py) consults this: while ANY unfreed entry
# exists (retain_graph=True, autograd.grad() without backward, a graph
# recorded but not yet differentiated), a weight buffer might still be
# read by a later backward, so donating it would be unsound. A WeakSet
# so entries garbage-collected with their output arrays drop out.
_UNFREED_ENTRIES = weakref.WeakSet()


def has_live_tape():
    """True while any recorded-but-unfreed tape entry exists (used by
    the donation gate in ops/registry.py)."""
    return len(_UNFREED_ENTRIES) > 0


def mark_variable(x, grad_req="write"):
    from .ndarray.ndarray import NDArray, zeros
    node = AGNode(array=x, grad_req=grad_req)
    x._ag_node = node
    x._grad_req = grad_req
    if grad_req != "null":
        x.grad = zeros(x.shape, ctx=x.context, dtype=x.dtype)


def mark_variables(variables, gradients=None, grad_reqs="write"):
    """Reference: python/mxnet/autograd.py mark_variables."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for i, v in enumerate(variables):
        mark_variable(v, grad_reqs[i])
        if gradients is not None:
            v.grad = gradients[i]


def record_op(op, attrs, inputs, outputs, key=None):
    """Append an op application to the tape (called by invoke_op)."""
    from .ndarray.ndarray import NDArray
    input_nodes = []
    any_node = False
    for x in inputs:
        n = x._ag_node if isinstance(x, NDArray) else None
        input_nodes.append(n)
        any_node = any_node or n is not None
    if not any_node:
        return
    vals = tuple(x._data if isinstance(x, NDArray) else x for x in inputs)
    entry = TapeEntry(op, dict(attrs), input_nodes, vals, key, len(outputs))
    for i, o in enumerate(outputs):
        node = AGNode(entry=entry, out_index=i, array=o)
        o._ag_node = node
        entry.output_nodes.append(node)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

class RowSparseCT:
    """A row-sparse cotangent flowing through the tape: (indices, values)
    over ``shape``. Produced by sparse-grad ops (SparseEmbedding, csr
    dot); stays sparse through accumulation so a large-vocab embedding
    backward never materialises an O(vocab) dense gradient (reference
    capability: row_sparse gradients, python/mxnet/ndarray/sparse.py +
    optimizer lazy_update). Densified on demand when it flows into an op
    that needs a dense cotangent."""

    __slots__ = ("indices", "values", "shape")

    def __init__(self, indices, values, shape):
        self.indices = indices
        self.values = values
        self.shape = tuple(shape)

    def to_dense(self):
        # cotangent indices may contain duplicates (repeated embedding
        # ids, repeated csr column ids) — densify by scatter-ADD, not
        # set, or duplicate contributions overwrite each other
        import jax.numpy as jnp
        out = jnp.zeros(self.shape, dtype=self.values.dtype)
        return out.at[self.indices].add(self.values)

    def concat(self, other):
        import jax.numpy as jnp
        return RowSparseCT(
            jnp.concatenate([self.indices, other.indices]),
            jnp.concatenate([self.values, other.values]), self.shape)

    def aggregated(self):
        """Canonical form: unique sorted indices, duplicates summed."""
        from .ops.sparse_ops import rsp_aggregate
        idx, vals = rsp_aggregate(self.indices, self.values)
        return RowSparseCT(idx, vals, self.shape)


def _densify_ct(g):
    return g.to_dense() if isinstance(g, RowSparseCT) else g

@functools.lru_cache(maxsize=None)
def _vjp_fn(name, attr_key, with_key):
    """Jitted (inputs, cotangents) -> input gradients for one (op, attrs)."""
    import jax
    from .ops.registry import get_op
    op = get_op(name)
    attrs = dict(attr_key)

    def fwd(*arrs):
        out = op.fn(*arrs, **attrs)
        return tuple(out) if isinstance(out, (tuple, list)) else (out,)

    def run(inputs, cts):
        _, vjp = jax.vjp(fwd, *inputs)
        grads = vjp(tuple(cts))
        return grads[1:] if with_key else grads

    return jax.jit(run)


def _topo_entries(head_nodes):
    seen = set()
    order = []

    def visit(entry):
        if entry is None or id(entry) in seen:
            return
        seen.add(id(entry))
        for n in entry.input_nodes:
            if n is not None and n.entry is not None:
                visit(n.entry)
        order.append(entry)

    for n in head_nodes:
        if n is not None:
            visit(n.entry)
    return order


def _run_backward(heads, head_grads=None, free_graph=False):
    """Walk the tape in reverse, returning (grad_map keyed by id(node),
    leaf_nodes dict). Pure with respect to NDArray state — callers decide
    whether to write results into ``.grad`` slots.

    ``free_graph=True`` (the backward() default) drops each consumed
    entry's saved input buffers afterwards — prompt memory release, and
    the safety condition for optimizer buffer donation (no stale tape
    reference can read a donated weight buffer). A second backward over
    a freed graph raises, like the reference frees its graph after
    Backward unless retain_graph."""
    import jax.numpy as jnp
    from .ndarray.ndarray import NDArray

    head_nodes = []
    for h in heads:
        if h._ag_node is None:
            raise MXNetError(
                "cannot differentiate a head that is not in a recorded "
                "computation (reference: imperative.cc Backward check)")
        head_nodes.append(h._ag_node)

    grad_map = {}

    def add_grad(node, g):
        prev = grad_map.get(id(node))
        if prev is None:
            grad_map[id(node)] = g
        elif isinstance(prev, RowSparseCT) and isinstance(g, RowSparseCT):
            grad_map[id(node)] = prev.concat(g)
        else:
            grad_map[id(node)] = _densify_ct(prev) + _densify_ct(g)

    for i, h in enumerate(heads):
        if head_grads is None or head_grads[i] is None:
            g = jnp.ones(h.shape, dtype=h.dtype)
        else:
            hg = head_grads[i]
            g = hg._data if isinstance(hg, NDArray) else jnp.asarray(hg)
        add_grad(h._ag_node, g)

    entries = _topo_entries(head_nodes)
    leaf_nodes = {}
    for n in head_nodes:
        if n.is_leaf:
            leaf_nodes[id(n)] = n
    for e in entries:
        for n in e.input_nodes:
            if n is not None and n.is_leaf:
                leaf_nodes[id(n)] = n

    for entry in reversed(entries):
        if entry.freed:
            raise MXNetError(
                "Trying to backward through a graph whose saved buffers "
                "were already freed; pass retain_graph=True to the first "
                "backward to differentiate it again")
        cts = []
        needed = False
        for i, onode in enumerate(entry.output_nodes):
            g = _densify_ct(grad_map.get(id(onode)))
            if g is None:
                # zero cotangent for unused outputs
                arr = onode.array_ref() if onode.array_ref else None
                if arr is not None:
                    g = jnp.zeros(arr.shape, dtype=arr.dtype)
                else:
                    import jax
                    shape_dtype = jax.eval_shape(
                        lambda *a: _normalize(entry.op.fn(*a, **entry.attrs))[i],
                        *(((entry.key,) if entry.key is not None else ())
                          + entry.input_values))
                    g = jnp.zeros(shape_dtype.shape, dtype=shape_dtype.dtype)
            else:
                needed = True
            cts.append(g)
        if not needed:
            continue
        custom_bwd = getattr(entry.op, "custom_bwd", None)
        if custom_bwd is not None:
            # autograd.Function: user-supplied backward (may return
            # RowSparseNDArray for sparse-grad inputs)
            in_grads = custom_bwd(tuple(cts))
            for node, g in zip(entry.input_nodes, in_grads):
                if node is None or g is None:
                    continue
                from .ndarray.ndarray import NDArray as _ND
                from .ndarray.sparse import RowSparseNDArray as _RSP
                if isinstance(g, _RSP):
                    g = RowSparseCT(g.indices, g.data, g.shape)
                elif isinstance(g, _ND):
                    g = g._data
                add_grad(node, g)
            continue
        with_key = entry.key is not None
        inputs = ((entry.key,) + entry.input_values) if with_key \
            else entry.input_values
        from .ops.registry import _REGISTRY
        if entry.op.name in _REGISTRY:
            fn = _vjp_fn(entry.op.name, canonical_attrs(entry.attrs), with_key)
            in_grads = fn(inputs, tuple(cts))
        else:
            # synthetic tape entries (e.g. _grad_of_grad for higher-order
            # autograd) are differentiated directly, uncached
            import jax as _jax

            def _fwd(*arrs):
                return _normalize(entry.op.fn(*arrs, **entry.attrs))

            _, _vjp = _jax.vjp(_fwd, *inputs)
            in_grads = _vjp(tuple(cts))
            if with_key:
                in_grads = in_grads[1:]
        for node, g in zip(entry.input_nodes, in_grads):
            if node is None or g is None:
                continue
            if hasattr(g, "dtype") and g.dtype.name == "float0":
                continue
            add_grad(node, g)

    if free_graph:
        for entry in entries:
            entry.input_values = ()
            entry.freed = True
            _UNFREED_ENTRIES.discard(entry)

    return grad_map, leaf_nodes


def _as_list(x):
    if x is None or isinstance(x, (list, tuple)):
        return x
    return [x]


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Compute gradients of heads w.r.t. marked variables and accumulate
    them into each leaf's ``.grad`` per its ``grad_req``
    (reference: Imperative::Backward, src/imperative/imperative.cc:270;
    accepts a single NDArray or a list for both arguments like the
    reference's _parse_head)."""
    grad_map, leaf_nodes = _run_backward(_as_list(heads),
                                         _as_list(head_grads),
                                         free_graph=not retain_graph)

    # write accumulated gradients into leaf arrays
    for node in leaf_nodes.values():
        g = grad_map.get(id(node))
        if g is None or node.grad_req == "null":
            continue
        arr = node.array_ref() if node.array_ref else None
        if arr is None:
            continue
        if isinstance(g, RowSparseCT):
            from .ndarray.sparse import RowSparseNDArray
            agg = g.aggregated()
            if node.grad_req == "add" and isinstance(arr.grad,
                                                     RowSparseNDArray):
                both = RowSparseCT(arr.grad.indices, arr.grad.data,
                                   g.shape).concat(agg).aggregated()
                arr.grad = RowSparseNDArray(both.values, both.indices,
                                            g.shape, ctx=arr.context)
            elif node.grad_req == "add" and arr.grad is not None:
                # mixed dense/sparse accumulation: correctness over
                # laziness (grad_req='write', the default, stays sparse)
                arr.grad._set_data(arr.grad._data + agg.to_dense())
            else:
                arr.grad = RowSparseNDArray(agg.values, agg.indices,
                                            g.shape, ctx=arr.context)
            continue
        if node.grad_req == "add" and arr.grad is not None:
            from .ndarray.sparse import RowSparseNDArray
            if isinstance(arr.grad, RowSparseNDArray):
                arr.grad = arr.grad + type(arr)(g, ctx=arr.context)
            else:
                arr.grad._set_data(arr.grad._data + g)
        else:
            if arr.grad is None:
                from .ndarray.ndarray import zeros
                arr.grad = zeros(arr.shape, ctx=arr.context, dtype=arr.dtype)
            from .ndarray.sparse import RowSparseNDArray
            if isinstance(arr.grad, RowSparseNDArray):
                from .ndarray.ndarray import NDArray
                arr.grad = NDArray(g, ctx=arr.context)
            else:
                arr.grad._set_data(g)


def _normalize(out):
    return tuple(out) if isinstance(out, (tuple, list)) else (out,)


class _FunctionOp(object):
    """Tape-entry op descriptor for autograd.Function (OpDef has
    __slots__; Function entries carry an explicit backward instead of a
    differentiable fn)."""

    def __init__(self, name, n_outputs, custom_bwd):
        self.name = name
        self.fn = None
        self.num_outputs = n_outputs
        self.custom_bwd = custom_bwd


class Function(object):
    """User-defined differentiable function
    (reference: python/mxnet/autograd.py Function / src/c_api/
    c_api_function.cc). Subclass and implement ``forward(self, *inputs)``
    and ``backward(self, *output_grads)``; each instance is used for one
    call, like the reference."""

    def __init__(self):
        self._used = False
        self.saved_tensors = ()

    def save_for_backward(self, *args):
        self.saved_tensors = args

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray
        from .ops.registry import OpDef
        if self._used:
            raise MXNetError(
                "Each Function instance can only be called once "
                "(reference: autograd.Function semantics)")
        self._used = True
        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (tuple, list))
        outs = [outputs] if single else list(outputs)
        if is_recording():
            fn_self = self

            def custom_bwd(cts):
                with pause():
                    grads = fn_self.backward(
                        *[NDArray(c) for c in cts])
                if not isinstance(grads, (tuple, list)):
                    grads = (grads,)
                return grads

            op = _FunctionOp("_function_%s" % type(self).__name__,
                             len(outs), custom_bwd)
            record_op(op, {}, list(inputs), outs, key=None)
        return outputs

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Functional gradient API: returns gradients of ``heads`` w.r.t.
    ``variables`` WITHOUT touching any ``.grad`` buffers or grad_req state
    (reference: python/mxnet/autograd.py grad). With ``create_graph=True``
    the returned arrays are recorded so they can be differentiated again
    (higher-order gradients)."""
    from .ndarray.ndarray import NDArray
    heads_l = _as_list(heads)
    head_grads = _as_list(head_grads)
    vars_single = not isinstance(variables, (list, tuple))
    vars_l = [variables] if vars_single else list(variables)
    for v in vars_l:
        if v._ag_node is None or not v._ag_node.is_leaf:
            raise MXNetError("grad requires marked leaf variables "
                             "(call attach_grad / mark_variables first)")
    if create_graph:
        return _grad_create_graph(heads_l, vars_l, head_grads, vars_single)
    # like the reference (and torch): retain_graph defaults to
    # create_graph — a plain grad() frees the saved buffers, keeping
    # memory bounded and the donation gate open
    retain = bool(create_graph) if retain_graph is None else retain_graph
    grad_map, _ = _run_backward(heads_l, head_grads,
                                free_graph=not retain)
    outs = []
    for v in vars_l:
        g = grad_map.get(id(v._ag_node))
        if g is None:
            raise MXNetError(
                "one of the variables does not participate in the "
                "computation of the heads (reference: autograd.grad)")
        if isinstance(g, RowSparseCT):
            from .ndarray.sparse import RowSparseNDArray
            agg = g.aggregated()
            outs.append(RowSparseNDArray(agg.values, agg.indices,
                                         agg.shape, ctx=v.context))
        else:
            outs.append(NDArray(g, ctx=v.context))
    return outs[0] if vars_single else outs


def _grad_create_graph(heads, variables, head_grads, single):
    """Higher-order grad: symbolically replay the tape as a pure function
    of the leaf variables' values and take ``jax.vjp``. The whole
    grads-from-variables computation is one pure function ``grad_fn``; it
    is evaluated eagerly for the returned values and, when recording,
    appended to the tape as a single synthetic entry — so a further
    ``backward`` on the result differentiates *through* grad_fn
    (vjp-of-vjp), giving d²y/dx²."""
    from .ndarray.ndarray import NDArray
    import jax
    import jax.numpy as jnp

    entries = _topo_entries([h._ag_node for h in heads])
    var_nodes = [v._ag_node for v in variables]
    head_nodes = [h._ag_node for h in heads]
    ct_vals = None if head_grads is None else tuple(
        hg._data if isinstance(hg, NDArray) else jnp.asarray(hg)
        for hg in head_grads)

    # grad_fn must be a function of EVERY live leaf feeding the heads (not
    # just the requested variables), so a later backward on the result can
    # propagate mixed second derivatives (d²y/dx dw) into other leaves.
    # Deduplicate variables (grad(y, [x, x]) is legal) and compute w.r.t.
    # the unique nodes, mapping results back per requested position.
    leaf_map = {}
    for v, n in zip(variables, var_nodes):
        if id(n) not in leaf_map:
            leaf_map[id(n)] = (n, v)
    uniq_var_nodes = [n for (n, _a) in leaf_map.values()]
    n_vars = len(uniq_var_nodes)
    for e in entries:
        for n in e.input_nodes:
            if n is not None and n.is_leaf and id(n) not in leaf_map:
                arr = n.array_ref() if n.array_ref else None
                if arr is not None:
                    leaf_map[id(n)] = (n, arr)
    leaf_nodes = [n for (n, _a) in leaf_map.values()]
    leaf_arrays = [a for (_n, a) in leaf_map.values()]
    var_nodes = uniq_var_nodes

    def grad_fn(*leaf_vals, **_attrs):
        env0 = {id(n): val for n, val in zip(leaf_nodes, leaf_vals)}

        def replay(vv):
            env = dict(env0)
            env.update({id(n): val for n, val in zip(var_nodes, vv)})
            for e in entries:
                ins = [env.get(id(n), recorded) if n is not None else recorded
                       for n, recorded in zip(e.input_nodes, e.input_values)]
                if e.key is not None:
                    ins = [e.key] + ins
                outs = _normalize(e.op.fn(*ins, **e.attrs))
                for i, onode in enumerate(e.output_nodes):
                    env[id(onode)] = outs[i]
            return tuple(env[id(n)] for n in head_nodes)

        out_vals, vjp = jax.vjp(replay, tuple(leaf_vals[:n_vars]))
        cts = ct_vals if ct_vals is not None else tuple(
            jnp.ones(o.shape, o.dtype) for o in out_vals)
        (grads,) = vjp(cts)
        return tuple(grads)

    grads = grad_fn(*(a._data for a in leaf_arrays))
    uniq_outs = [NDArray(g, ctx=a.context)
                 for a, g in zip(leaf_arrays[:n_vars], grads)]
    if is_recording():
        from .ops.registry import OpDef
        op = OpDef("_grad_of_grad", grad_fn, num_outputs=len(uniq_outs))
        record_op(op, {}, list(leaf_arrays), uniq_outs, key=None)
    grad_of = {id(n): o for n, o in zip(var_nodes, uniq_outs)}
    outs = [grad_of[id(v._ag_node)] for v in variables]
    return outs[0] if single else outs
