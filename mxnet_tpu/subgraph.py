"""Subgraph framework: property-based graph partitioning.

Reference: src/operator/subgraph/ (partition_graph.cc:774 partitions an
nnvm graph by a SubgraphProperty's selection; subgraph_property.h
registry; default_subgraph_property.cc executes matched subgraphs via
CachedOp).

TPU-native design: a partitioned region becomes ONE ``_subgraph`` op
node whose attr carries the serialized sub-symbol; the op executes the
sub-symbol through the registry's jit cache, so each matched region
compiles to a single fused XLA program — the partition is exactly the
compilation-unit boundary (the reference's accelerator-handoff use case
maps to "compile this region as one unit").
"""
from __future__ import annotations

import json

from .base import MXNetError

__all__ = ["SubgraphProperty", "register_subgraph_property",
           "partition_graph", "get_subgraph_property"]

_PROPERTIES = {}


class SubgraphProperty(object):
    """Node-selection policy (reference: subgraph_property.h).

    Subclass and override :meth:`match`; optionally :meth:`min_size`."""

    name = "default"

    def match(self, node):
        """True if the op node may join a subgraph. Ops with auxiliary
        states (BatchNorm moving stats) never join: the fused region
        cannot thread functional aux updates back to the executor."""
        from .symbol.symbol import AUX_STATES
        return node.op not in AUX_STATES

    def min_size(self):
        """Smallest region worth fusing."""
        return 2


def register_subgraph_property(prop):
    """Register a property instance or class (reference:
    MXNET_REGISTER_SUBGRAPH_PROPERTY)."""
    inst = prop() if isinstance(prop, type) else prop
    _PROPERTIES[inst.name] = inst
    return prop


def get_subgraph_property(name):
    try:
        return _PROPERTIES[name]
    except KeyError:
        raise MXNetError("subgraph property %r is not registered"
                         % name) from None


register_subgraph_property(SubgraphProperty)


# ---------------------------------------------------------------------------
# the _subgraph executor op
# ---------------------------------------------------------------------------

def _subgraph_fn(key, *arrays, graph_json=None, in_names=(), n_out=1,
                 train_mode=False, **_ig):
    """Evaluate a serialized sub-symbol on the given inputs. Jitted by
    the registry keyed on (graph_json, in_names) — one compiled program
    per matched region (the CachedOp analog, cached_op.cc:835).
    train_mode threads through like any stateful op; the leading rng key
    serves any samplers inside the region."""
    from .symbol.symbol import load_json, _graph_eval_fn
    sub = load_json(graph_json)
    fn = _graph_eval_fn(sub, is_train=bool(train_mode))
    env = dict(zip(in_names, arrays))
    outs, _aux = fn(env, key)
    return outs if len(outs) > 1 else outs[0]


def _register_subgraph_op():
    from .ops.registry import register, get_op, MXNetError as _E
    try:
        get_op("_subgraph")
    except Exception:
        register("_subgraph", needs_rng=True,
                 num_outputs=lambda attrs: int(attrs.get("n_out", 1)),
                 attr_defaults={"graph_json": None, "in_names": (),
                                "n_out": 1, "train_mode": False})(
                     _subgraph_fn)


_register_subgraph_op()


# ---------------------------------------------------------------------------
# partitioning pass
# ---------------------------------------------------------------------------

def partition_graph(symbol, prop="default", excluded_names=()):
    """Collapse maximal contiguous runs of property-matched nodes into
    ``_subgraph`` nodes (reference: partition_graph.cc BuildSubgraph).

    Returns a new Symbol computing the same outputs.
    """
    from .symbol import symbol as _S
    if isinstance(prop, str):
        prop = get_subgraph_property(prop)
    excluded = set(excluded_names)

    from .symbol.symbol import AUX_STATES

    nodes = _S._topo(symbol._entries)
    # head entries must stay addressable: map old entry -> new entry
    runs = []
    cur = []
    # outputs of the whole symbol (cannot be internal to a region unless
    # they are the region's outputs — handled below via out mapping)
    for node in nodes:
        if node.is_var:
            continue            # params/inputs never break a run
        if (prop.match(node) and node.name not in excluded
                and node.op not in AUX_STATES):
            cur.append(node)
        else:
            if len(cur) >= prop.min_size():
                runs.append(list(cur))
            cur = []
    if len(cur) >= prop.min_size():
        runs.append(cur)

    in_region = {}
    for ri, run in enumerate(runs):
        for n in run:
            in_region[id(n)] = ri

    new_of = {}          # id(old node) -> {out_idx: (new node, new idx)}

    def sub_entry(src, oi):
        if src.is_var:
            if id(src) not in new_of:
                new_of[id(src)] = {0: (src, 0)}
            return new_of[id(src)][0]
        return new_of[id(src)][oi]

    emitted = set()
    for node in nodes:
        if node.is_var:
            continue
        ri = in_region.get(id(node))
        if ri is None:
            # ordinary node: rebuild with remapped inputs
            new_inputs = [sub_entry(s, oi) for (s, oi) in node.inputs]
            nn = _S._Node(node.op, node.name, dict(node.attrs),
                          new_inputs, in_names=node.in_names)
            new_of[id(node)] = {i: (nn, i)
                                for i in range(_S._n_outputs(node))}
            continue
        if ri in emitted:
            continue
        emitted.add(ri)
        run = runs[ri]
        run_ids = {id(n) for n in run}
        # region inputs: entries produced outside, in first-use order
        ext_in = []
        seen = set()
        for n in run:
            for (s, oi) in n.inputs:
                k = (id(s), oi)
                if (s.is_var or id(s) not in run_ids) and k not in seen:
                    seen.add(k)
                    ext_in.append((s, oi))
        # region outputs: entries consumed outside the region (or heads)
        head_set = {(id(n), oi) for (n, oi) in symbol._entries}
        consumers = {}
        for m in nodes:
            if m.is_var or id(m) in run_ids:
                continue
            for (s, oi) in m.inputs:
                consumers.setdefault((id(s), oi), True)
        reg_out = []
        for n in run:
            for i in range(_S._n_outputs(n)):
                k = (id(n), i)
                if k in consumers or k in head_set:
                    reg_out.append((n, i))
        # build the sub-symbol: region nodes with external inputs turned
        # into fresh variables named in0, in1, ...
        var_of = {}
        for j, (s, oi) in enumerate(ext_in):
            var_of[(id(s), oi)] = _S._Node(None, "in%d" % j)
        sub_map = {}

        def sub_in(s, oi):
            k = (id(s), oi)
            if k in var_of:
                return (var_of[k], 0)
            return sub_map[id(s)][oi]

        for n in run:
            ni = [sub_in(s, oi) for (s, oi) in n.inputs]
            nn = _S._Node(n.op, n.name, dict(n.attrs), ni,
                          in_names=n.in_names)
            sub_map[id(n)] = {i: (nn, i)
                              for i in range(_S._n_outputs(n))}
        sub_sym = _S.Symbol([sub_map[id(n)][i] for (n, i) in reg_out])
        gjson = sub_sym.tojson()
        sg_node = _S._Node(
            "_subgraph", "subgraph%d" % ri,
            {"graph_json": gjson,
             "in_names": tuple("in%d" % j for j in range(len(ext_in))),
             "n_out": len(reg_out)},
            [sub_entry(s, oi) for (s, oi) in ext_in],
            in_names=["in%d" % j for j in range(len(ext_in))])
        for k, (n, i) in enumerate(reg_out):
            new_of.setdefault(id(n), {})[i] = (sg_node, k)

    entries = [new_of[id(n)][oi] for (n, oi) in symbol._entries]
    return _S.Symbol(entries)
