#!/usr/bin/env python
"""Train an MLP / LeNet on MNIST (the reference's first CLI milestone).

Reference analog: example/image-classification/train_mnist.py +
common/fit.py (argparse CLI driving Module.fit with --network,
--kv-store, --lr...).

MNIST loads from --data-dir (idx files, as the reference's iterator
reads); without one, a synthetic separable dataset of the same shape is
generated so the script runs in zero-egress environments.

    python examples/train_mnist.py --network mlp --num-epochs 3
"""
import argparse
import logging
import gzip
import os
import struct
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import mxnet_tpu as mx  # noqa: E402


def load_mnist(data_dir, split):
    img = os.path.join(data_dir, "%s-images-idx3-ubyte.gz" % split)
    lbl = os.path.join(data_dir, "%s-labels-idx1-ubyte.gz" % split)
    with gzip.open(lbl) as f:
        struct.unpack(">II", f.read(8))
        label = np.frombuffer(f.read(), dtype=np.uint8)
    with gzip.open(img) as f:
        _, n, rows, cols = struct.unpack(">IIII", f.read(16))
        image = np.frombuffer(f.read(), dtype=np.uint8)
        image = image.reshape(n, 1, rows, cols).astype(np.float32) / 255.0
    return image, label.astype(np.float32)


def synthetic_mnist(n, seed=0):
    """Separable 10-class images: class-dependent blob positions."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 10, n)
    x = rng.randn(n, 1, 28, 28).astype(np.float32) * 0.3
    for i, c in enumerate(y):
        r, col = divmod(int(c), 4)
        x[i, 0, 4 + r * 6:10 + r * 6, 4 + col * 6:10 + col * 6] += 2.0
    return x, y.astype(np.float32)


def get_symbol(network):
    data = mx.sym.Variable("data")
    if network == "mlp":
        h = mx.sym.Flatten(data)
        h = mx.sym.FullyConnected(h, num_hidden=128, name="fc1")
        h = mx.sym.Activation(h, act_type="relu")
        h = mx.sym.FullyConnected(h, num_hidden=64, name="fc2")
        h = mx.sym.Activation(h, act_type="relu")
        h = mx.sym.FullyConnected(h, num_hidden=10, name="fc3")
    elif network == "lenet":
        h = mx.sym.Convolution(data, kernel=(5, 5), num_filter=20)
        h = mx.sym.Activation(h, act_type="tanh")
        h = mx.sym.Pooling(h, pool_type="max", kernel=(2, 2), stride=(2, 2))
        h = mx.sym.Convolution(h, kernel=(5, 5), num_filter=50)
        h = mx.sym.Activation(h, act_type="tanh")
        h = mx.sym.Pooling(h, pool_type="max", kernel=(2, 2), stride=(2, 2))
        h = mx.sym.Flatten(h)
        h = mx.sym.FullyConnected(h, num_hidden=500)
        h = mx.sym.Activation(h, act_type="tanh")
        h = mx.sym.FullyConnected(h, num_hidden=10)
    else:
        raise ValueError("unknown network %r" % network)
    return mx.sym.SoftmaxOutput(h, name="softmax")


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--network", default="mlp", choices=["mlp", "lenet"])
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--num-examples", type=int, default=6000)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.3)
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--num-epochs", type=int, default=6)
    ap.add_argument("--kv-store", default="local")
    ap.add_argument("--optimizer", default="sgd")
    ap.add_argument("--model-prefix", default=None)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    if args.data_dir:
        X, Y = load_mnist(args.data_dir, "train")
        Xv, Yv = load_mnist(args.data_dir, "t10k")
    else:
        X, Y = synthetic_mnist(args.num_examples)
        Xv, Yv = synthetic_mnist(args.num_examples // 6, seed=1)

    train = mx.io.NDArrayIter(X, Y, batch_size=args.batch_size,
                              shuffle=True, label_name="softmax_label")
    val = mx.io.NDArrayIter(Xv, Yv, batch_size=args.batch_size,
                            label_name="softmax_label")
    mod = mx.module.Module(get_symbol(args.network))
    mod.fit(train, eval_data=val, eval_metric="acc",
            optimizer=args.optimizer,
            optimizer_params={"learning_rate": args.lr,
                              "momentum": args.momentum},
            kvstore=args.kv_store, num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 50))
    score = mod.score(val, "acc")
    print("final validation accuracy: %.4f" % dict(score)["accuracy"])
    if args.model_prefix:
        mod.save_checkpoint(args.model_prefix, args.num_epochs)


if __name__ == "__main__":
    main()
