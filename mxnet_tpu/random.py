"""Global PRNG state.

Reference: python/mxnet/random.py + per-device RandGenerator
(include/mxnet/random_generator.h). TPU-native design: a single counter
advanced per random op, folded into a threefry key — deterministic given
``seed()``, cheap to split across a device mesh, and safe to capture in
traced programs (the trace takes the key as an input).
"""
from __future__ import annotations

import threading

__all__ = ["seed", "next_key", "current_seed"]

_state = threading.local()


def _ensure():
    if not hasattr(_state, "seed"):
        _state.seed = 0
        _state.counter = 0


def seed(seed_state: int, ctx=None):
    """Seed the global generator (reference: python/mxnet/random.py:30)."""
    _ensure()
    _state.seed = int(seed_state)
    _state.counter = 0


def current_seed():
    _ensure()
    return _state.seed


def next_key():
    """Return a fresh jax PRNG key; advances the global counter."""
    import jax
    _ensure()
    _state.counter += 1
    return jax.random.fold_in(jax.random.PRNGKey(_state.seed), _state.counter)
