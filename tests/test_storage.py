"""Storage/memory component: accounting, per-step HBM profiling,
optimizer buffer donation, tape freeing, device prefetch staging.

Reference behavior: src/storage/pooled_storage_manager.h,
src/profiler/storage_profiler.h, kWriteInplace optimizer requests.
"""
import gc

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd, storage
from mxnet_tpu.base import MXNetError


def test_memory_stats_and_live_bytes():
    a = nd.zeros((256, 256))          # 256 KB
    a.wait_to_read()
    lb = storage.live_bytes()
    assert lb >= a.size * 4
    rows = storage.largest_live(5)
    assert rows and rows[0][0] >= 256 * 256 * 4
    # memory_stats is backend-dependent; must be a dict either way
    assert isinstance(storage.memory_stats(), dict)


def test_step_memory_profiler_records():
    smp = storage.StepMemoryProfiler()
    x = nd.zeros((64, 64))
    x.wait_to_read()
    rec = smp.step()
    assert rec["bytes_in_use"] > 0
    assert smp.peak >= rec["bytes_in_use"] * 0  # peak tracked
    assert smp.report()["steps"] == 1


def test_update_donates_weight_buffer():
    """sgd_update must alias weight input->output (no double-buffering):
    the pre-update buffer is invalidated, the NDArray sees new data."""
    gc.collect()        # drop any unfreed tape entries from other tests
    w = nd.array(np.ones((8, 8), np.float32))
    g = nd.array(np.full((8, 8), 0.5, np.float32))
    w.wait_to_read()
    old = w._data
    nd.sgd_update(w, g, lr=1.0, wd=0.0)
    np.testing.assert_allclose(w.asnumpy(), 0.5)
    with pytest.raises(RuntimeError):
        _ = np.asarray(old)            # donated buffer: deleted


def test_update_donates_momentum_state_too():
    gc.collect()
    w = nd.array(np.ones((4,), np.float32))
    g = nd.array(np.ones((4,), np.float32))
    m = nd.zeros((4,))
    m.wait_to_read()
    old_m = m._data
    nd.sgd_mom_update(w, g, m, lr=0.1, momentum=0.9, wd=0.0)
    assert float(m.asnumpy()[0]) != 0.0
    with pytest.raises(RuntimeError):
        _ = np.asarray(old_m)


def test_training_loop_with_donation_is_safe():
    """forward -> backward -> donated update -> next forward: the freed
    tape guarantees no stale reference reads a donated buffer."""
    w = nd.array(np.random.RandomState(0).randn(4, 1).astype(np.float32))
    w.attach_grad()
    x = nd.array(np.random.RandomState(1).randn(16, 4).astype(np.float32))
    y = nd.dot(x, nd.array(np.array([[2.0], [0.0], [-1.0], [0.5]],
                                    np.float32)))
    first = prev = None
    for _ in range(40):
        with autograd.record():
            loss = nd.sum((nd.dot(x, w) - y) ** 2) / 16
        loss.backward()
        nd.sgd_update(w, w.grad, lr=0.1, wd=0.0)
        cur = float(loss.asscalar())
        if prev is not None:
            assert cur <= prev * 1.001
        first = first if first is not None else cur
        prev = cur
    assert prev < first * 0.05


def test_backward_frees_graph_second_backward_raises():
    w = nd.array(np.ones((3,), np.float32))
    w.attach_grad()
    with autograd.record():
        loss = nd.sum(w * w)
    loss.backward()
    with pytest.raises(MXNetError):
        loss.backward()


def test_retain_graph_allows_second_backward():
    w = nd.array(np.ones((3,), np.float32))
    w.attach_grad()
    with autograd.record():
        loss = nd.sum(w * w)
    loss.backward(retain_graph=True)
    g1 = w.grad.asnumpy().copy()
    loss.backward()
    np.testing.assert_allclose(w.grad.asnumpy(), g1)


def test_module_update_path_donates():
    """The Module/executor DP path (the CLI path) gets donation through
    the same update kernels."""
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    out = mx.sym.SoftmaxOutput(fc, name="softmax")
    mod = mx.module.Module(out, data_names=("data",),
                           label_names=("softmax_label",))
    from mxnet_tpu.io import NDArrayIter
    rng = np.random.RandomState(0)
    it = NDArrayIter(rng.randn(8, 6).astype(np.float32),
                     rng.randint(0, 4, 8).astype(np.float32), batch_size=8)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    batch = next(it)
    mod.forward(batch)
    mod.backward()
    wname = "fc_weight"
    old = mod._exec.arg_dict[wname]._data
    old.block_until_ready()
    gc.collect()
    mod.update()
    with pytest.raises(RuntimeError):
        _ = np.asarray(old)            # param buffer was donated
    assert np.isfinite(mod._exec.arg_dict[wname].asnumpy()).all()


def test_donation_disabled_by_env(monkeypatch):
    monkeypatch.setenv("MXNET_UPDATE_BUFFER_DONATION", "0")
    from mxnet_tpu.ops import registry
    registry._jitted.cache_clear()
    try:
        w = nd.array(np.ones((4,), np.float32))
        g = nd.array(np.ones((4,), np.float32))
        w.wait_to_read()
        old = w._data
        nd.sgd_update(w, g, lr=0.5, wd=0.0)
        np.testing.assert_allclose(np.asarray(old), 1.0)   # still readable
        np.testing.assert_allclose(w.asnumpy(), 0.5)
    finally:
        registry._jitted.cache_clear()


def test_prefetching_iter_device_staging():
    from mxnet_tpu.io import NDArrayIter, PrefetchingIter
    rng = np.random.RandomState(0)
    base = NDArrayIter(rng.randn(32, 3).astype(np.float32),
                       rng.randn(32).astype(np.float32), batch_size=8)
    it = PrefetchingIter(base, device_prefetch=True)
    b = next(it)
    arr = b.data[0]._data
    import jax
    assert list(arr.devices())[0] in jax.devices()
    np.testing.assert_allclose(b.data[0].asnumpy().shape, (8, 3))


def test_donation_suspended_with_retained_graph():
    """retain_graph=True keeps the tape alive; an update in that state
    must NOT donate (the second backward still reads the old weight)."""
    gc.collect()
    w = nd.array(np.ones((3,), np.float32))
    w.attach_grad()
    with autograd.record():
        loss = nd.sum(w * w)
    loss.backward(retain_graph=True)
    old = w._data
    nd.sgd_update(w, w.grad, lr=0.1, wd=0.0)
    np.testing.assert_allclose(np.asarray(old), 1.0)   # NOT donated
    loss.backward()                                    # still works
    assert np.isfinite(w.grad.asnumpy()).all()


def test_grad_api_with_sparse_ct_returns_rsp():
    from mxnet_tpu.ndarray import sparse
    from mxnet_tpu.ndarray.sparse import RowSparseNDArray
    W = nd.array(np.ones((6, 2), np.float32))
    W.attach_grad()
    ids = nd.array(np.array([1, 4, 1], np.float32))
    with autograd.record():
        out = sparse.embedding(ids, W)
        loss = nd.sum(out)
    g = autograd.grad(loss, W)
    assert isinstance(g, RowSparseNDArray)
    dense = g.todense().asnumpy()
    np.testing.assert_allclose(dense[1], 2.0)          # duplicate summed
    np.testing.assert_allclose(dense[4], 1.0)
    np.testing.assert_allclose(dense[0], 0.0)


def test_kvstore_pull_does_not_alias_store():
    import mxnet_tpu as mx
    from mxnet_tpu import optimizer as opt
    gc.collect()
    kv = mx.kvstore.create("local")
    w = nd.array(np.ones((4,), np.float32))
    kv.init(0, w)
    kv.set_optimizer(opt.create("sgd", learning_rate=0.1))
    out = nd.zeros((4,))
    kv.pull(0, out=out)
    out.wait_to_read()
    kv.push(0, nd.array(np.ones((4,), np.float32)))   # donating update
    # the pulled copy must survive the store-side donation
    np.testing.assert_allclose(out.asnumpy(), 1.0)


def test_dataloader_process_workers_shared_memory():
    """Process workers ship batches through shared memory (reference:
    gluon/data/dataloader.py multiprocessing + shm transport)."""
    from mxnet_tpu.gluon import data as gdata
    rng = np.random.RandomState(0)
    ds = gdata.ArrayDataset(rng.rand(48, 5).astype(np.float32),
                            np.arange(48, dtype=np.float32))
    dl = gdata.DataLoader(ds, batch_size=12, num_workers=2)
    seen = []
    for x, y in dl:
        assert x.shape == (12, 5)
        seen.extend(y.asnumpy().tolist())
    assert seen == list(range(48))           # order + completeness
    # error propagation from a worker process
    class Bad(gdata.Dataset):
        def __len__(self):
            return 4
        def __getitem__(self, i):
            raise ValueError("boom")
    with pytest.raises(RuntimeError):
        for _ in gdata.DataLoader(Bad(), batch_size=2, num_workers=1):
            pass


def test_detach_survives_donating_update():
    gc.collect()
    w = nd.array(np.ones((4,), np.float32))
    snap = w.detach()
    g = nd.array(np.ones((4,), np.float32))
    nd.sgd_update(w, g, lr=0.5, wd=0.0)
    np.testing.assert_allclose(snap.asnumpy(), 1.0)   # snapshot intact
    np.testing.assert_allclose(w.asnumpy(), 0.5)


def test_grad_frees_graph_by_default():
    w = nd.array(np.ones((3,), np.float32))
    w.attach_grad()
    with autograd.record():
        loss = nd.sum(w * w)
    autograd.grad(loss, w)
    with pytest.raises(MXNetError):
        autograd.grad(loss, w)            # freed, like backward()
    with autograd.record():
        loss2 = nd.sum(w * w * w)
    autograd.grad(loss2, w, retain_graph=True)
    g = autograd.grad(loss2, w)           # retained -> works again
    np.testing.assert_allclose(g.asnumpy(), 3.0)


def test_kvstore_mixed_dense_sparse_push_densifies():
    import mxnet_tpu as mx
    from mxnet_tpu.ndarray.sparse import RowSparseNDArray
    kv = mx.kvstore.create("local")
    kv.init(0, nd.zeros((4, 1)))
    rsp = RowSparseNDArray(np.ones((1, 1), np.float32), np.array([2]),
                           (4, 1))
    dense = nd.array(np.full((4, 1), 2.0, np.float32))
    kv.push(0, [rsp, dense])
    out = nd.zeros((4, 1))
    kv.pull(0, out=out)
    np.testing.assert_allclose(out.asnumpy().ravel(), [2, 2, 3, 2])


def test_dataloader_abandoned_iteration_reclaims_shm():
    from mxnet_tpu.gluon import data as gdata
    import glob
    rng = np.random.RandomState(0)
    ds = gdata.ArrayDataset(rng.rand(64, 4).astype(np.float32),
                            np.arange(64, dtype=np.float32))
    before = set(glob.glob("/dev/shm/psm_*"))
    dl = gdata.DataLoader(ds, batch_size=8, num_workers=2, prefetch=6)
    it = iter(dl)
    next(it)
    it.close()                            # abandon mid-epoch
    gc.collect()
    leaked = set(glob.glob("/dev/shm/psm_*")) - before
    assert not leaked, leaked
