"""Random sampling operators.

Reference: src/operator/random/sample_op.cc, multisample_op.cc,
shuffle_op.cc and the per-device RandGenerator
(include/mxnet/random_generator.h). TPU-native design: counter-based
stateless PRNG — every op takes an explicit threefry key supplied by the
runtime (mxnet_tpu.random keeps the global seed state), so sampling is
reproducible, parallelizable across a device mesh by key-splitting, and
trace-safe under jit.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register, alias
from ..base import np_dtype


def _shape(shape):
    if shape is None or shape == ():
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


@register("_random_uniform", needs_rng=True, differentiable=False,
          attr_defaults={"low": 0.0, "high": 1.0, "shape": (), "dtype": "float32"})
def _random_uniform(key, low=0.0, high=1.0, shape=(), dtype="float32", **_ig):
    return jax.random.uniform(key, _shape(shape), dtype=np_dtype(dtype),
                              minval=low, maxval=high)


@register("_random_normal", needs_rng=True, differentiable=False,
          attr_defaults={"loc": 0.0, "scale": 1.0, "shape": (), "dtype": "float32"})
def _random_normal(key, loc=0.0, scale=1.0, shape=(), dtype="float32", **_ig):
    return loc + scale * jax.random.normal(key, _shape(shape),
                                           dtype=np_dtype(dtype))


@register("_random_gamma", needs_rng=True, differentiable=False,
          attr_defaults={"alpha": 1.0, "beta": 1.0, "shape": (), "dtype": "float32"})
def _random_gamma(key, alpha=1.0, beta=1.0, shape=(), dtype="float32", **_ig):
    return beta * jax.random.gamma(key, alpha, _shape(shape),
                                   dtype=np_dtype(dtype))


@register("_random_exponential", needs_rng=True, differentiable=False,
          attr_defaults={"lam": 1.0, "shape": (), "dtype": "float32"})
def _random_exponential(key, lam=1.0, shape=(), dtype="float32", **_ig):
    return jax.random.exponential(key, _shape(shape),
                                  dtype=np_dtype(dtype)) / lam


@register("_random_poisson", needs_rng=True, differentiable=False,
          attr_defaults={"lam": 1.0, "shape": (), "dtype": "float32"})
def _random_poisson(key, lam=1.0, shape=(), dtype="float32", **_ig):
    return jax.random.poisson(key, lam, _shape(shape)).astype(np_dtype(dtype))


@register("_random_randint", needs_rng=True, differentiable=False,
          attr_defaults={"low": 0, "high": 1, "shape": (), "dtype": "int32"})
def _random_randint(key, low=0, high=1, shape=(), dtype="int32", **_ig):
    return jax.random.randint(key, _shape(shape), int(low), int(high),
                              dtype=np_dtype(dtype))


@register("_random_negative_binomial", needs_rng=True, differentiable=False,
          attr_defaults={"k": 1, "p": 1.0, "shape": (), "dtype": "float32"})
def _random_negative_binomial(key, k=1, p=1.0, shape=(), dtype="float32", **_ig):
    kg, kp = jax.random.split(key)
    lam = jax.random.gamma(kg, float(k), _shape(shape)) * (1.0 - p) / p
    return jax.random.poisson(kp, lam).astype(np_dtype(dtype))


@register("_sample_multinomial", needs_rng=True, differentiable=False,
          num_outputs=lambda attrs: 2 if dict(attrs).get("get_prob") else 1,
          attr_defaults={"shape": (), "get_prob": False, "dtype": "int32"})
def _sample_multinomial(key, data, shape=(), get_prob=False, dtype="int32",
                        **_ig):
    """Categorical sampling from probabilities along the last axis
    (reference: src/operator/random/multisample_op.cc)."""
    logits = jnp.log(jnp.maximum(data, 1e-37))
    n = 1
    for s in _shape(shape):
        n *= s
    batch = data.shape[:-1]
    draws = jax.random.categorical(key, logits, axis=-1,
                                   shape=_shape(shape) + batch if shape else batch)
    # gather log-probs while sample dims still lead: logp (batch, m)
    # broadcasts against draws (sample + batch) by trailing alignment
    gathered = None
    if get_prob:
        logp = jax.nn.log_softmax(logits, axis=-1)
        gathered = jnp.take_along_axis(
            jnp.broadcast_to(logp, draws.shape + (data.shape[-1],)),
            draws[..., None].astype(jnp.int32), axis=-1)[..., 0]
    # moveaxis so batch dims lead, sample dims trail (MXNet convention)
    if shape:
        k = len(_shape(shape))
        perm = tuple(range(k))
        dst = tuple(range(draws.ndim - k, draws.ndim))
        draws = jnp.moveaxis(draws, perm, dst)
        if gathered is not None:
            gathered = jnp.moveaxis(gathered, perm, dst)
    out = draws.astype(np_dtype(dtype))
    if get_prob:
        return out, gathered
    return out


@register("_shuffle", needs_rng=True, differentiable=False)
def _shuffle(key, data, **_ig):
    return jax.random.permutation(key, data, axis=0)


@register("_sample_unique_zipfian", needs_rng=True, differentiable=False,
          attr_defaults={"range_max": 1, "shape": ()})
def _sample_unique_zipfian(key, range_max=1, shape=(), **_ig):
    u = jax.random.uniform(key, _shape(shape))
    out = jnp.expm1(u * jnp.log1p(float(range_max) - 1.0)).astype(jnp.int64)
    return jnp.clip(out, 0, range_max - 1).astype(jnp.int32)


# Public legacy aliases (reference registers these as public op names:
# src/operator/random/sample_op.cc "random_uniform"/"uniform" etc. and
# multinomial as "sample_multinomial").
alias("random_uniform", "_random_uniform")
alias("uniform", "_random_uniform")
alias("random_normal", "_random_normal")
alias("normal", "_random_normal")
alias("random_gamma", "_random_gamma")
alias("random_exponential", "_random_exponential")
alias("random_poisson", "_random_poisson")
alias("random_randint", "_random_randint")
alias("random_negative_binomial", "_random_negative_binomial")
alias("sample_multinomial", "_sample_multinomial")
alias("shuffle", "_shuffle")


@register("_random_generalized_negative_binomial", needs_rng=True,
          differentiable=False,
          attr_defaults={"mu": 1.0, "alpha": 1.0, "shape": (),
                         "dtype": "float32"})
def _random_gnb(key, mu=1.0, alpha=1.0, shape=(), dtype="float32", **_ig):
    """Generalized negative binomial = gamma-mixed Poisson (reference:
    src/operator/random/sample_op.cc GeneralizedNegativeBinomial):
    lambda ~ Gamma(1/alpha, mu*alpha); x ~ Poisson(lambda)."""
    k1, k2 = jax.random.split(key)
    r = 1.0 / alpha
    lam = jax.random.gamma(k1, r, _shape(shape)) * (mu * alpha)
    return jax.random.poisson(k2, lam).astype(np_dtype(dtype))


alias("random_generalized_negative_binomial",
      "_random_generalized_negative_binomial")
alias("generalized_negative_binomial",
      "_random_generalized_negative_binomial")


# ---------------------------------------------------------------------------
# sample_* family: one draw (or ``shape`` draws) PER ROW of the
# parameter arrays (reference: src/operator/random/multisample_op.cc)
# ---------------------------------------------------------------------------

def _multisample(name, n_params, draw):
    @register("_sample_" + name, needs_rng=True, differentiable=False,
              attr_defaults={"shape": (), "dtype": "float32"})
    def _op(key, *params, shape=(), dtype="float32", **_ig):
        import numpy as _onp
        ps = params[:n_params]
        per = _shape(shape)
        batch = tuple(ps[0].shape)
        n = int(_onp.prod(batch)) if batch else 1
        keys = jax.random.split(key, n)

        def one(k, *args):
            return draw(k, *args, shape=per)

        flat = [p.reshape(-1) for p in ps]
        out = jax.vmap(one)(keys, *flat)
        return out.reshape(batch + per).astype(np_dtype(dtype))
    alias("sample_" + name, "_sample_" + name)


_multisample("uniform", 2,
             lambda k, lo, hi, shape: jax.random.uniform(
                 k, shape, minval=lo, maxval=hi))
_multisample("normal", 2,
             lambda k, mu, sigma, shape: mu + sigma *
             jax.random.normal(k, shape))
_multisample("gamma", 2,
             lambda k, alpha, beta, shape: jax.random.gamma(
                 k, alpha, shape) * beta)
_multisample("exponential", 1,
             lambda k, lam, shape: jax.random.exponential(k, shape) / lam)
_multisample("poisson", 1,
             lambda k, lam, shape: jax.random.poisson(
                 k, lam, shape).astype(jnp.float32))
_multisample("negative_binomial", 2,
             lambda k, kk, p, shape: _nb_draw(k, kk, p, shape))
_multisample("generalized_negative_binomial", 2,
             lambda k, mu, alpha, shape: _gnb_draw(k, mu, alpha, shape))


def _nb_draw(key, k_param, p, shape):
    # NB(k, p) = Poisson(lambda), lambda ~ Gamma(k, (1-p)/p)
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, k_param, shape) * ((1.0 - p) / p)
    return jax.random.poisson(k2, lam).astype(jnp.float32)


def _gnb_draw(key, mu, alpha, shape):
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, 1.0 / alpha, shape) * (mu * alpha)
    return jax.random.poisson(k2, lam).astype(jnp.float32)
