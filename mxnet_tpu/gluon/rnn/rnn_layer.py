"""Gluon fused recurrent layers (RNN / LSTM / GRU).

Reference: python/mxnet/gluon/rnn/rnn_layer.py — thin wrappers over the
fused RNN op (src/operator/rnn.cc → here a lax.scan program, ops/nn.py
``RNN``). Parameters are kept per-layer/per-direction (MXNet naming
``{l,r}{i}_{i2h,h2h}_{weight,bias}``) and concatenated into the op's flat
cuDNN-style layout at call time.
"""
from __future__ import annotations

from ..block import HybridBlock
from ..nn.basic_layers import _init

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, projection_size=None, **kwargs):
        super(_RNNLayer, self).__init__(**kwargs)
        assert layout in ("TNC", "NTC"), "Invalid layout %s" % layout
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]

        ng, ni, nh = self._gates, input_size, hidden_size
        hout = projection_size if projection_size else hidden_size
        for i in range(num_layers):
            for j in ["l", "r"][:self._dir]:
                self._register_param(
                    "%s%d_i2h_weight" % (j, i), (ng * nh, ni),
                    i2h_weight_initializer)
                self._register_param(
                    "%s%d_h2h_weight" % (j, i), (ng * nh, hout),
                    h2h_weight_initializer)
                self._register_param(
                    "%s%d_i2h_bias" % (j, i), (ng * nh,),
                    i2h_bias_initializer)
                self._register_param(
                    "%s%d_h2h_bias" % (j, i), (ng * nh,),
                    h2h_bias_initializer)
                if projection_size:
                    self._register_param(
                        "%s%d_h2r_weight" % (j, i), (projection_size, nh),
                        h2h_weight_initializer)
            ni = hout * self._dir

    def _register_param(self, name, shape, init_arg):
        p = self.params.get(name, shape=shape, init=_init(init_arg),
                            allow_deferred_init=True)
        self._reg_params[name] = p
        setattr(self, name, p)

    def __repr__(self):
        mapping = "%s -> %s" % (self._input_size or None, self._hidden_size)
        return "%s(%s, %s, layers=%s%s)" % (
            self.__class__.__name__, mapping, self._layout, self._num_layers,
            ", bidirectional" if self._dir == 2 else "")

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def infer_shape(self, x, *args):
        ni = x.shape[2] if self._layout == "TNC" else x.shape[2]
        hout = self._projection_size or self._hidden_size
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                p = self._reg_params["%s%d_i2h_weight" % (j, i)]
                p._set_shape_from((self._gates * self._hidden_size, ni))
            ni = hout * self._dir

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial recurrent states (reference: rnn_layer.py begin_state)."""
        from ... import ndarray as nd
        if func is None:
            func = nd.zeros
        states = []
        for info in self.state_info(batch_size):
            info = dict(info)
            shape = info.pop("shape")
            info.pop("__layout__", None)
            states.append(func(shape=shape, **kwargs))
        return states

    def hybrid_forward(self, F, inputs, states=None, **params):
        if self._layout == "NTC":
            inputs = inputs.swapaxes(0, 1)
        batch_size = inputs.shape[1]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch_size)
        if not isinstance(states, (list, tuple)):
            states = [states]
        flat = self._flatten_params(F, params)
        rnn_args = [inputs, flat] + list(states)
        kwargs = dict(state_size=self._hidden_size,
                      num_layers=self._num_layers,
                      bidirectional=self._dir == 2, mode=self._mode,
                      p=self._dropout, state_outputs=True)
        if self._projection_size:
            kwargs["projection_size"] = self._projection_size
        out = F.RNN(*rnn_args, **kwargs)
        outputs, states_out = out[0], list(out[1:])
        if self._layout == "NTC":
            outputs = outputs.swapaxes(0, 1)
        if skip_states:
            return outputs
        return outputs, states_out

    def _flatten_params(self, F, params):
        """Concat per-layer params into the fused op's flat layout
        (per layer, per dir: W, R, bW, bR[, P])."""
        chunks = []
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                chunks.append(params["%s%d_i2h_weight" % (j, i)].reshape((-1,)))
                chunks.append(params["%s%d_h2h_weight" % (j, i)].reshape((-1,)))
                chunks.append(params["%s%d_i2h_bias" % (j, i)])
                chunks.append(params["%s%d_h2h_bias" % (j, i)])
                if self._projection_size:
                    chunks.append(
                        params["%s%d_h2r_weight" % (j, i)].reshape((-1,)))
        return F.Concat(*chunks, dim=0)

    def __call__(self, inputs, states=None):
        return super(_RNNLayer, self).__call__(inputs, states) \
            if states is not None else super(_RNNLayer, self).__call__(inputs)

    def forward(self, inputs, states=None):
        from ... import ndarray as F
        from ..parameter import DeferredInitializationError
        try:
            params = {n: p.data() for n, p in self._reg_params.items()}
        except DeferredInitializationError:
            self.infer_shape(inputs)
            for p in self.collect_params().values():
                if p._deferred_init is not None:
                    p._finish_deferred_init()
            params = {n: p.data() for n, p in self._reg_params.items()}
        return self.hybrid_forward(F, inputs, states, **params)


class RNN(_RNNLayer):
    """Elman RNN layer (reference: rnn_layer.py RNN)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, **kwargs):
        super(RNN, self).__init__(
            hidden_size, num_layers, layout, dropout, bidirectional,
            input_size, i2h_weight_initializer, h2h_weight_initializer,
            i2h_bias_initializer, h2h_bias_initializer,
            "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """LSTM layer (reference: rnn_layer.py LSTM)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 projection_size=None, **kwargs):
        super(LSTM, self).__init__(
            hidden_size, num_layers, layout, dropout, bidirectional,
            input_size, i2h_weight_initializer, h2h_weight_initializer,
            i2h_bias_initializer, h2h_bias_initializer, "lstm",
            projection_size=projection_size, **kwargs)

    def state_info(self, batch_size=0):
        hout = self._projection_size or self._hidden_size
        return [{"shape": (self._num_layers * self._dir, batch_size, hout),
                 "__layout__": "LNC"},
                {"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """GRU layer (reference: rnn_layer.py GRU)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super(GRU, self).__init__(
            hidden_size, num_layers, layout, dropout, bidirectional,
            input_size, i2h_weight_initializer, h2h_weight_initializer,
            i2h_bias_initializer, h2h_bias_initializer, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
