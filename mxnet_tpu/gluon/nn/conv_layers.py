"""Gluon convolution & pooling layers.

Reference: python/mxnet/gluon/nn/conv_layers.py (1187 LoC: Conv1D/2D/3D,
Conv1DTranspose/2D/3D, MaxPool/AvgPool 1/2/3D, GlobalMaxPool/GlobalAvgPool,
ReflectionPad2D).

All layers use NC{D,H,W} layouts; XLA's layout assignment handles MXU
tiling so no manual NHWC conversion is exposed.
"""
from __future__ import annotations

from ..block import HybridBlock
from .basic_layers import Activation, _init

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D",
           "GlobalMaxPool2D", "GlobalMaxPool3D", "GlobalAvgPool1D",
           "GlobalAvgPool2D", "GlobalAvgPool3D", "ReflectionPad2D"]


def _tup(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(v)
    assert len(v) == n
    return v


class _Conv(HybridBlock):
    """Base convolution layer (reference: conv_layers.py _Conv)."""

    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 op_name="Convolution", adj=None, **kwargs):
        super(_Conv, self).__init__(**kwargs)
        self._channels = channels
        self._in_channels = in_channels
        ndim = len(kernel_size)
        self._op_name = op_name
        self._kwargs = {
            "kernel": kernel_size, "stride": strides, "dilate": dilation,
            "pad": padding, "num_filter": channels, "num_group": groups,
            "no_bias": not use_bias, "layout": layout}
        if adj is not None:
            self._kwargs["adj"] = adj
        if op_name == "Convolution":
            wshape = (channels, in_channels // groups if in_channels else 0) \
                + tuple(kernel_size)
        else:   # Deconvolution: (in_channels, channels//groups, *kernel)
            wshape = (in_channels, channels // groups) + tuple(kernel_size)
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=wshape, init=_init(weight_initializer),
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(channels,), init=_init(bias_initializer),
                    allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def infer_shape(self, x, *args):
        c_in = x.shape[1]
        groups = self._kwargs["num_group"]
        if self._op_name == "Convolution":
            self.weight._set_shape_from(
                (self._channels, c_in // groups) +
                tuple(self._kwargs["kernel"]))
        else:
            self.weight._set_shape_from(
                (c_in, self._channels // groups) +
                tuple(self._kwargs["kernel"]))

    def hybrid_forward(self, F, x, weight, bias=None):
        op = getattr(F, self._op_name)
        if bias is None:
            out = op(x, weight, **self._kwargs)
        else:
            out = op(x, weight, bias, **self._kwargs)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        s = "{name}({mapping}, kernel_size={kernel}, stride={stride}"
        mapping = "{0} -> {1}".format(
            self._in_channels if self._in_channels else None, self._channels)
        return s.format(name=self.__class__.__name__, mapping=mapping,
                        kernel=self._kwargs["kernel"],
                        stride=self._kwargs["stride"]) + ")"


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout="NCW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super(Conv1D, self).__init__(
            channels, _tup(kernel_size, 1), _tup(strides, 1), _tup(padding, 1),
            _tup(dilation, 1), groups, layout, in_channels, activation,
            use_bias, weight_initializer, bias_initializer, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super(Conv2D, self).__init__(
            channels, _tup(kernel_size, 2), _tup(strides, 2), _tup(padding, 2),
            _tup(dilation, 2), groups, layout, in_channels, activation,
            use_bias, weight_initializer, bias_initializer, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super(Conv3D, self).__init__(
            channels, _tup(kernel_size, 3), _tup(strides, 3), _tup(padding, 3),
            _tup(dilation, 3), groups, layout, in_channels, activation,
            use_bias, weight_initializer, bias_initializer, **kwargs)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super(Conv1DTranspose, self).__init__(
            channels, _tup(kernel_size, 1), _tup(strides, 1), _tup(padding, 1),
            _tup(dilation, 1), groups, layout, in_channels, activation,
            use_bias, weight_initializer, bias_initializer,
            op_name="Deconvolution", adj=_tup(output_padding, 1), **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super(Conv2DTranspose, self).__init__(
            channels, _tup(kernel_size, 2), _tup(strides, 2), _tup(padding, 2),
            _tup(dilation, 2), groups, layout, in_channels, activation,
            use_bias, weight_initializer, bias_initializer,
            op_name="Deconvolution", adj=_tup(output_padding, 2), **kwargs)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super(Conv3DTranspose, self).__init__(
            channels, _tup(kernel_size, 3), _tup(strides, 3), _tup(padding, 3),
            _tup(dilation, 3), groups, layout, in_channels, activation,
            use_bias, weight_initializer, bias_initializer,
            op_name="Deconvolution", adj=_tup(output_padding, 3), **kwargs)


class _Pooling(HybridBlock):
    """Base pooling layer (reference: conv_layers.py _Pooling)."""

    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, count_include_pad=None, **kwargs):
        super(_Pooling, self).__init__(**kwargs)
        if strides is None:
            strides = pool_size
        self._kwargs = {
            "kernel": pool_size, "stride": strides, "pad": padding,
            "global_pool": global_pool, "pool_type": pool_type,
            "pooling_convention": "full" if ceil_mode else "valid"}
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def _alias(self):
        return "pool"

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)

    def __repr__(self):
        return "%s(size=%s, stride=%s, padding=%s, ceil_mode=%s)" % (
            self.__class__.__name__, self._kwargs["kernel"],
            self._kwargs["stride"], self._kwargs["pad"],
            self._kwargs["pooling_convention"] == "full")


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        super(MaxPool1D, self).__init__(
            _tup(pool_size, 1), strides if strides is None else _tup(strides, 1),
            _tup(padding, 1), ceil_mode, False, "max", **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, **kwargs):
        super(MaxPool2D, self).__init__(
            _tup(pool_size, 2), strides if strides is None else _tup(strides, 2),
            _tup(padding, 2), ceil_mode, False, "max", **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        super(MaxPool3D, self).__init__(
            _tup(pool_size, 3), strides if strides is None else _tup(strides, 3),
            _tup(padding, 3), ceil_mode, False, "max", **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super(AvgPool1D, self).__init__(
            _tup(pool_size, 1), strides if strides is None else _tup(strides, 1),
            _tup(padding, 1), ceil_mode, False, "avg", count_include_pad,
            **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super(AvgPool2D, self).__init__(
            _tup(pool_size, 2), strides if strides is None else _tup(strides, 2),
            _tup(padding, 2), ceil_mode, False, "avg", count_include_pad,
            **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super(AvgPool3D, self).__init__(
            _tup(pool_size, 3), strides if strides is None else _tup(strides, 3),
            _tup(padding, 3), ceil_mode, False, "avg", count_include_pad,
            **kwargs)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super(GlobalMaxPool1D, self).__init__(
            (1,), None, (0,), True, True, "max", **kwargs)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super(GlobalMaxPool2D, self).__init__(
            (1, 1), None, (0, 0), True, True, "max", **kwargs)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super(GlobalMaxPool3D, self).__init__(
            (1, 1, 1), None, (0, 0, 0), True, True, "max", **kwargs)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super(GlobalAvgPool1D, self).__init__(
            (1,), None, (0,), True, True, "avg", **kwargs)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super(GlobalAvgPool2D, self).__init__(
            (1, 1), None, (0, 0), True, True, "avg", **kwargs)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super(GlobalAvgPool3D, self).__init__(
            (1, 1, 1), None, (0, 0, 0), True, True, "avg", **kwargs)


class ReflectionPad2D(HybridBlock):
    """Reference: conv_layers.py ReflectionPad2D (op: Pad reflect mode)."""

    def __init__(self, padding=0, **kwargs):
        super(ReflectionPad2D, self).__init__(**kwargs)
        if isinstance(padding, int):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        self._padding = tuple(padding)

    def hybrid_forward(self, F, x):
        return F.Pad(x, mode="reflect", pad_width=self._padding)
