"""Gluon DataLoader.

Reference: python/mxnet/gluon/data/dataloader.py:55-112 (multiprocessing
workers + shared-memory NDArray transport) and src/io/iter_prefetcher.h
(engine-async double buffering).

TPU-native design: worker PROCESSES batchify into numpy and ship each
batch through POSIX shared memory (one segment per array — the same
zero-serialization transport the reference builds on rec_io sockets);
the main process maps the segment, device_puts straight out of it, and
unlinks. Decode/augment parallelism scales past the GIL while device
transfer stays on the dispatch thread (PjRt requirement).
``thread_pool=True`` falls back to threads (useful when the dataset is
not fork-shareable).
"""
from __future__ import annotations

import multiprocessing
import os
import threading
import queue as _queue

import numpy as _np

from ...ndarray.ndarray import NDArray, array
from .sampler import SequentialSampler, RandomSampler, BatchSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference: dataloader.py
    default_batchify_fn). Produces numpy; the loader converts to device
    arrays on the main thread."""
    if isinstance(data[0], NDArray):
        return _np.stack([d.asnumpy() for d in data])
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(list(i)) for i in data]
    return _np.asarray(data)


def _as_device(batch):
    if isinstance(batch, (list, tuple)):
        return [_as_device(b) for b in batch]
    if isinstance(batch, _np.ndarray):
        return array(batch, dtype=batch.dtype)
    return batch


# -- nested-batch (de)construction for the shared-memory transport ---------

def _flatten_np(batch, leaves):
    if isinstance(batch, (list, tuple)):
        return ["T", [_flatten_np(b, leaves) for b in batch]]
    if isinstance(batch, _np.ndarray):
        leaves.append(batch)
        return ["L", len(leaves) - 1]
    leaves.append(_np.asarray(batch))
    return ["L", len(leaves) - 1]


def _unflatten(tree, leaves):
    tag, payload = tree
    if tag == "T":
        return [_unflatten(t, leaves) for t in payload]
    return leaves[payload]


def _worker_loop(dataset, batchify_fn, in_q, out_q):
    """Process-worker body: index batch -> numpy batch -> shm segments.
    (module-level so fork/spawn can reach it)."""
    from multiprocessing import shared_memory, resource_tracker
    while True:
        item = in_q.get()
        if item is None:
            break
        seq, indices = item
        metas = []
        try:
            leaves = []
            tree = _flatten_np(batchify_fn([dataset[i] for i in indices]),
                               leaves)
            for arr in leaves:
                arr = _np.ascontiguousarray(arr)
                shm = shared_memory.SharedMemory(
                    create=True, size=max(1, arr.nbytes))
                dst = _np.ndarray(arr.shape, arr.dtype, buffer=shm.buf)
                dst[...] = arr
                metas.append((shm.name, arr.shape, str(arr.dtype)))
                # the CONSUMER unlinks; unregister here so this process's
                # resource tracker doesn't double-free at exit
                try:
                    resource_tracker.unregister(shm._name, "shared_memory")
                except Exception:
                    pass
                shm.close()
            out_q.put((seq, (tree, metas), None))
        except Exception as e:  # propagate to the consumer
            # segments created before the failure are untracked and will
            # never reach the consumer: unlink them here or they leak in
            # /dev/shm — compounding pressure exactly when shm is tight
            for name, _shape, _dt in metas:
                try:
                    seg = shared_memory.SharedMemory(name=name)
                    seg.close()
                    seg.unlink()
                except Exception:
                    pass
            out_q.put((seq, None, repr(e)))


def _unlink_payload(payload):
    """Release the shm segments of a batch that will never be consumed."""
    from multiprocessing import shared_memory
    if not payload:
        return
    _tree, metas = payload
    for name, _shape, _dtype in metas:
        try:
            shm = shared_memory.SharedMemory(name=name)
            shm.close()
            shm.unlink()
        except FileNotFoundError:
            pass


def _load_shared(payload):
    """Map each shm segment, copy to device, unlink."""
    from multiprocessing import shared_memory
    tree, metas = payload
    leaves = []
    for name, shape, dtype in metas:
        shm = shared_memory.SharedMemory(name=name)
        view = _np.ndarray(shape, _np.dtype(dtype), buffer=shm.buf)
        leaves.append(array(view.copy()))
        shm.close()
        try:
            shm.unlink()
        except FileNotFoundError:
            pass
    return _unflatten(tree, leaves)


class _ThreadWorker(threading.Thread):
    """Thread fallback: pulls index batches, produces numpy batches."""

    def __init__(self, dataset, batchify_fn, in_q, out_q):
        super().__init__(daemon=True)
        self._dataset = dataset
        self._batchify_fn = batchify_fn
        self._in_q = in_q
        self._out_q = out_q

    def run(self):
        while True:
            item = self._in_q.get()
            if item is None:
                break
            seq, indices = item
            try:
                batch = self._batchify_fn(
                    [self._dataset[i] for i in indices])
                self._out_q.put((seq, batch, None))
            except Exception as e:
                self._out_q.put((seq, None, e))


class DataLoader(object):
    """Loads batches from a Dataset (reference: dataloader.py DataLoader).

    ``num_workers>0`` forks worker PROCESSES that ship batches through
    shared memory (reference parity: multiprocessing Pool + shm
    NDArray); ``thread_pool=True`` keeps workers as threads instead.
    """

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=False):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError(
                "batch_size/shuffle/sampler/last_batch must not be "
                "specified if batch_sampler is")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._thread_pool = thread_pool
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)

    def _spawn(self):
        if self._thread_pool:
            in_q, out_q = _queue.Queue(), _queue.Queue()
            workers = [
                _ThreadWorker(self._dataset, self._batchify_fn, in_q, out_q)
                for _ in range(self._num_workers)]
            for w in workers:
                w.start()
            return workers, in_q, out_q, False
        # fork shares the dataset copy-on-write (no pickling) but
        # inherits JAX's threads (fork-safety hazard); the start method
        # is configurable for hosts where forked workers crash. spawn/
        # forkserver need the worker loop picklable (it is,
        # module-level).
        from ... import config as _config
        method = _config.get("MXNET_DATALOADER_START_METHOD")
        valid = multiprocessing.get_all_start_methods()
        if method not in valid:
            if "MXNET_DATALOADER_START_METHOD" in os.environ:
                # an EXPLICIT bad value is an error the user should see
                raise ValueError(
                    "MXNET_DATALOADER_START_METHOD=%r is not a start "
                    "method on this platform (valid: %s)"
                    % (method, ", ".join(valid)))
            method = valid[0]    # default 'fork' absent (Windows): spawn
        ctx = multiprocessing.get_context(method)
        in_q, out_q = ctx.Queue(), ctx.Queue()
        workers = [
            ctx.Process(target=_worker_loop,
                        args=(self._dataset, self._batchify_fn, in_q,
                              out_q), daemon=True)
            for _ in range(self._num_workers)]
        for w in workers:
            w.start()
        return workers, in_q, out_q, True

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield _as_device(self._batchify_fn(
                    [self._dataset[i] for i in indices]))
            return

        workers, in_q, out_q, is_proc = self._spawn()
        buffered = {}
        try:
            it = iter(self._batch_sampler)
            sent = 0
            for _ in range(self._prefetch or self._num_workers):
                try:
                    in_q.put((sent, next(it)))
                    sent += 1
                except StopIteration:
                    break
            received = 0
            while received < sent:
                while received not in buffered:
                    try:
                        seq, batch, err = out_q.get(timeout=5.0)
                    except _queue.Empty:
                        if is_proc and not all(w.is_alive()
                                               for w in workers):
                            raise RuntimeError(
                                "DataLoader worker died unexpectedly "
                                "(killed / crashed in native code)")
                        continue
                    buffered[seq] = (batch, err)
                batch, err = buffered.pop(received)
                received += 1
                try:
                    in_q.put((sent, next(it)))
                    sent += 1
                except StopIteration:
                    pass
                if err is not None:
                    raise RuntimeError("DataLoader worker failed: %s"
                                       % (err,)) if is_proc else err
                yield _load_shared(batch) if is_proc else _as_device(batch)
        finally:
            for _ in workers:
                in_q.put(None)
            if is_proc:
                # reclaim any prefetched-but-unconsumed shm segments
                # (abandoned iteration / error path) — the consumer is
                # the only party that unlinks. Drain while workers wind
                # down AND after they exit, so a batch that lands
                # mid-shutdown is still reclaimed.
                for batch, _err in buffered.values():
                    _unlink_payload(batch)
                import time as _time
                deadline = _time.time() + 10.0
                while _time.time() < deadline and \
                        any(w.is_alive() for w in workers):
                    try:
                        _s, batch, _e = out_q.get(timeout=0.25)
                        _unlink_payload(batch)
                    except _queue.Empty:
                        pass
                for w in workers:
                    w.join(timeout=5)
                    if w.is_alive():
                        w.terminate()
                while True:          # final sweep: queue is now quiet
                    try:
                        _s, batch, _e = out_q.get(timeout=0.1)
                        _unlink_payload(batch)
                    except _queue.Empty:
                        break

    def __len__(self):
        return len(self._batch_sampler)
