"""Device mesh management.

Reference analog: the context lists passed to Module/-Trainer
(`ctx=[mx.gpu(0), mx.gpu(1), ...]`, executor_group.py:143) and the KVStore
device topology (comm_tree.h link solver). On TPU the mesh IS the
topology: axes map onto ICI rings, so laying out ('dp','tp') over a pod
slice makes gradient reduction ride ICI without any tree solver.
"""
from __future__ import annotations

import threading

__all__ = ["make_mesh", "current_mesh", "set_mesh", "data_parallel_sharding",
           "replicated_sharding", "global_dp_mesh", "mesh_process_count",
           "host_local_value", "make_replicated_global",
           "make_batch_global", "make_accum_batch_global"]

_state = threading.local()


def make_mesh(shape=None, axis_names=("dp",), devices=None):
    """Create a Mesh over the visible devices.

    ``shape``: tuple of axis sizes (product must divide the device count),
    or None to put every device on the first axis."""
    import jax
    import numpy as np
    devs = devices if devices is not None else jax.devices()
    if shape is None:
        shape = (len(devs),)
    shape = tuple(int(s) for s in shape)
    n = int(np.prod(shape))
    if n > len(devs):
        raise ValueError("mesh shape %s needs %d devices, have %d"
                         % (shape, n, len(devs)))
    arr = np.asarray(devs[:n]).reshape(shape)
    from jax.sharding import Mesh
    return Mesh(arr, axis_names[:len(shape)])


def set_mesh(mesh):
    prev = getattr(_state, "mesh", None)
    _state.mesh = mesh
    return prev


def current_mesh():
    return getattr(_state, "mesh", None)


def data_parallel_sharding(mesh, axis="dp", ndim=2):
    """NamedSharding splitting the leading (batch) dim over ``axis``."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def replicated_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P())


# ---------------------------------------------------------------------------
# multi-host (dist_tpu_sync) mesh + placement helpers
# ---------------------------------------------------------------------------

def global_dp_mesh(axis="dp"):
    """1-D data-parallel mesh over EVERY device of EVERY process, in
    canonical ``(process_index, device id)`` order — each process's
    local devices own a contiguous run of mesh positions, so rank r's
    local batch maps onto global batch rows ``[r*local, (r+1)*local)``.
    This is the mesh ``dist_tpu_sync`` folds the gradient all-reduce
    into (GSPMD inserts the ``psum`` over the 'dp' axis inside the
    fused train-step program)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    return Mesh(np.array(devs), (axis,))


def mesh_process_count(mesh):
    """How many processes own devices of ``mesh`` (1 = fully local)."""
    return len({d.process_index for d in mesh.devices.flat})


def host_local_value(arr):
    """This process's addressable view of a (possibly multi-process)
    jax array: the full value for a replicated array, the local rows
    (concatenated over local shards, mesh order) for a batch array
    sharded on dim 0.  Fully-addressable arrays pass through — the
    single-process path pays nothing."""
    import jax
    if not isinstance(arr, jax.Array) or arr.is_fully_addressable:
        return arr
    shards = {}
    for s in arr.addressable_shards:
        key = tuple(sl.start or 0 for sl in s.index)
        shards.setdefault(key, s.data)
    if len(shards) == 1:                   # replicated: any shard is all
        return next(iter(shards.values()))
    # multiple local shards (several local devices): assemble on host —
    # the shards are committed to DIFFERENT devices, and jax refuses a
    # device computation over mixed placements
    import numpy as np
    return np.concatenate(
        [np.asarray(d) for _, d in sorted(shards.items())], axis=0)


def make_replicated_global(mesh, host_value):
    """Global replicated array over a multi-process ``mesh`` from a
    host value every process holds identically (params, optimizer
    state): the value lands on each LOCAL device and the shards
    assemble into one global array — no cross-host transfer, because
    replication needs none when every host already has the value."""
    import jax
    import numpy as np
    data = np.asarray(host_value)
    sh = replicated_sharding(mesh)
    arrs = [jax.device_put(data, d) for d in mesh.local_devices]
    return jax.make_array_from_single_device_arrays(data.shape, sh, arrs)


def make_batch_global(mesh, host_local_batch, axis="dp"):
    """Global batch array sharded on dim 0 over ``axis``, assembled
    from each process's LOCAL batch rows (the per-host input-sharding
    contract: rank r feeds shard r of the iterator, see
    ``io.dist_parts``).  Global batch = local batch x process count;
    every process must contribute the same local batch size."""
    import jax
    import numpy as np
    data = np.asarray(host_local_batch)
    sh = data_parallel_sharding(mesh, axis=axis, ndim=max(data.ndim, 1))
    make = getattr(jax, "make_array_from_process_local_data", None)
    if make is not None:
        return make(sh, data)
    # older jax: split the local rows over the local devices by hand
    local = list(mesh.local_devices)
    chunks = np.split(data, len(local))
    nproc = mesh_process_count(mesh)
    gshape = (data.shape[0] * nproc,) + data.shape[1:]
    arrs = [jax.device_put(c, d) for c, d in zip(chunks, local)]
    return jax.make_array_from_single_device_arrays(gshape, sh, arrs)


def make_accum_batch_global(mesh, host_local_batch, axis="dp"):
    """Microbatched global batch for the gradient-accumulation fused
    step: local rows ``[A, L, ...]`` (A microbatches of L rows each)
    assemble into a global ``[A, world*L, ...]`` sharded on dim **1**
    (``P(None, 'dp')``) — microbatch ``a``'s global rows are the
    concatenation of every process's ``a``-th microbatch, exactly the
    rows the pre-rescale world's ranks ``a*world..(a+1)*world-1`` fed
    in one step (see ``elastic.plan_microbatches``)."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    data = np.asarray(host_local_batch)
    if data.ndim < 2:
        raise ValueError("accum batch needs shape [A, L, ...], got %s"
                         % (tuple(data.shape),))
    sh = NamedSharding(mesh, P(None, axis, *([None] * (data.ndim - 2))))
    make = getattr(jax, "make_array_from_process_local_data", None)
    if make is not None:
        return make(sh, data)
    local = list(mesh.local_devices)
    chunks = np.split(data, len(local), axis=1)
    nproc = mesh_process_count(mesh)
    gshape = (data.shape[0], data.shape[1] * nproc) + data.shape[2:]
    arrs = [jax.device_put(c, d) for c, d in zip(chunks, local)]
    return jax.make_array_from_single_device_arrays(gshape, sh, arrs)
