"""sym.linalg namespace (reference: python/mxnet/symbol/linalg.py —
wrappers over the _linalg_* ops), mirroring nd.linalg."""
from __future__ import annotations

from .register import populate_prefixed, prefixed_getattr

__all__ = populate_prefixed(__name__, "_linalg_")
__getattr__ = prefixed_getattr("_linalg_")
