"""Reference-binary NDArray file codec (reference:
src/ndarray/ndarray.cc:1565-1800 — the ``.params`` format every
published MXNet checkpoint uses).

Layout (little-endian, dmlc::Stream serialization):
  uint64 0x112 (kMXAPINDArrayListMagic), uint64 reserved
  uint64 n; n x NDArray       (vector<NDArray>)
  uint64 k; k x (uint64 len, bytes)   (vector<string> names)

NDArray v2 (uint32 magic 0xF993fac9):
  int32 stype; [storage_shape Tuple if sparse]; shape Tuple;
  int32 dev_type, int32 dev_id; int32 type_flag;
  [per aux: int32 aux_type, Tuple aux_shape]; raw data; [raw aux data]

Tuple = uint32 ndim + ndim dims. The dim width changed across MXNet
releases (uint32 through ~1.4, int64 from 1.5 with int64-TShape
builds); both are accepted — each array is parsed with one width and
re-parsed with the other if validation (device-type / dtype ranges,
stream bounds) rejects it.
"""
from __future__ import annotations

import struct

import numpy as _np

from ..base import MXNetError

__all__ = ["LIST_MAGIC", "is_mxnet_params", "loads", "dumps"]

LIST_MAGIC = 0x112
_V1_MAGIC = 0xF993FAC8
_V2_MAGIC = 0xF993FAC9

# mshadow type flags (mshadow/base.h)
_DTYPES = {0: _np.float32, 1: _np.float64, 2: _np.float16, 3: _np.uint8,
           4: _np.int32, 5: _np.int8, 6: _np.int64}
_FLAGS = {_np.dtype(v): k for k, v in _DTYPES.items()}

# storage types (include/mxnet/ndarray.h:61-65); value -> n aux arrays
_NAD = {0: 0, 1: 1, 2: 2}      # default, row_sparse, csr


class _Cursor(object):
    def __init__(self, buf):
        self.buf = buf
        self.pos = 0

    def read(self, n):
        if self.pos + n > len(self.buf):
            raise MXNetError("truncated NDArray file")
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def u32(self):
        return struct.unpack("<I", self.read(4))[0]

    def i32(self):
        return struct.unpack("<i", self.read(4))[0]

    def u64(self):
        return struct.unpack("<Q", self.read(8))[0]


def _read_tuple(cur, dim64):
    ndim = cur.u32()
    if ndim > 32:
        raise MXNetError("implausible ndim %d" % ndim)
    fmt = "<%d%s" % (ndim, "q" if dim64 else "I")
    size = 8 * ndim if dim64 else 4 * ndim
    dims = struct.unpack(fmt, cur.read(size))
    # d == 0 is legal (zero-size arrays, e.g. an empty row_sparse with 0
    # stored rows); only negatives and absurd magnitudes disambiguate
    # the dim width
    if any(d < 0 or d > 2 ** 40 for d in dims):
        raise MXNetError("implausible dims %s" % (dims,))
    return tuple(int(d) for d in dims)


def _read_array(cur, dim64):
    magic = cur.u32()
    if magic == _V2_MAGIC:
        stype = cur.i32()
        if stype not in _NAD:
            raise MXNetError("bad storage type %d" % stype)
        nad = _NAD[stype]
        sshape = _read_tuple(cur, dim64) if nad > 0 else None
        shape = _read_tuple(cur, dim64)
    elif magic == _V1_MAGIC:
        stype, nad, sshape = 0, 0, None
        shape = _read_tuple(cur, dim64)
    else:
        # oldest legacy: the "magic" IS ndim, dims always uint32
        stype, nad, sshape = 0, 0, None
        ndim = magic
        if ndim > 32:
            raise MXNetError("bad magic 0x%x" % magic)
        shape = struct.unpack("<%dI" % ndim, cur.read(4 * ndim))
    if len(shape) == 0:
        return None, None, None                      # none-array
    dev_type = cur.i32()
    dev_id = cur.i32()
    # loose plausibility bound: exists only to disambiguate the dim
    # width, must not reject real files from high-numbered devices
    if not (1 <= dev_type <= 6 and 0 <= dev_id <= 255):
        raise MXNetError("implausible context (%d,%d)"
                         % (dev_type, dev_id))
    type_flag = cur.i32()
    if type_flag not in _DTYPES:
        raise MXNetError("unknown type flag %d" % type_flag)
    aux = []
    for _ in range(nad):
        aux_type = cur.i32()
        if aux_type not in _DTYPES:
            raise MXNetError("unknown aux type flag %d" % aux_type)
        aux.append((aux_type, _read_tuple(cur, dim64)))
    data_shape = sshape if nad > 0 else shape
    dtype = _np.dtype(_DTYPES[type_flag])
    n = int(_np.prod(data_shape)) if data_shape else 1
    data = _np.frombuffer(cur.read(n * dtype.itemsize),
                          dtype=dtype).reshape(data_shape)
    aux_arrays = []
    for aux_type, ashape in aux:
        adt = _np.dtype(_DTYPES[aux_type])
        an = int(_np.prod(ashape)) if ashape else 1
        aux_arrays.append(_np.frombuffer(cur.read(an * adt.itemsize),
                                         dtype=adt).reshape(ashape))
    return stype, (shape, data), aux_arrays


def is_mxnet_params(head):
    """First 8+ bytes → is this the reference binary list format?"""
    return len(head) >= 8 and \
        struct.unpack("<Q", head[:8])[0] == LIST_MAGIC


def _parse_all(buf, dim64, ctx):
    from .ndarray import array
    from .sparse import RowSparseNDArray, CSRNDArray
    cur = _Cursor(buf)
    if cur.u64() != LIST_MAGIC:
        raise MXNetError("not an MXNet NDArray list file")
    cur.u64()                                        # reserved
    n = cur.u64()
    if n > 10 ** 7:
        raise MXNetError("implausible array count %d" % n)
    arrays = []
    for _ in range(n):
        stype, payload, aux = _read_array(cur, dim64)
        if payload is None:
            arrays.append(None)
            continue
        shape, data = payload
        if stype == 0:
            arrays.append(array(data, ctx=ctx, dtype=data.dtype))
        elif stype == 1:                             # row_sparse
            arrays.append(RowSparseNDArray(data, aux[0], shape, ctx=ctx))
        else:                                        # csr
            arrays.append(CSRNDArray(data, aux[1], aux[0], shape,
                                     ctx=ctx))
    k = cur.u64()
    if k not in (0, n):
        raise MXNetError("key count %d != array count %d" % (k, n))
    keys = []
    for _ in range(k):
        ln = cur.u64()
        if ln > 4096:
            raise MXNetError("implausible key length %d" % ln)
        keys.append(cur.read(ln).decode())
    if cur.pos != len(buf):
        raise MXNetError("trailing bytes (%d) after parse"
                         % (len(buf) - cur.pos))
    return keys, arrays


def loads(buf, ctx=None):
    """Decode a reference ``.params`` blob → (keys, ndarray list).
    Sparse entries decode to RowSparse/CSR NDArrays. The TShape dim
    width is a property of the WRITER's version: try uint32 (<=1.4),
    fall back to int64 (>=1.5) — exactly one parses the stream to the
    end. float64 entries land at float32 precision under JAX's default
    x64-off config."""
    try:
        return _parse_all(buf, False, ctx)
    except MXNetError as first:
        try:
            return _parse_all(buf, True, ctx)
        except MXNetError:
            # a corrupt file fails both widths; the uint32 pass usually
            # gets further, so its error is the informative one
            raise first


def _check_writable(name, a):
    if a.ndim == 0:
        raise MXNetError(
            "cannot write %r: the reference format has no 0-dim "
            "arrays (ndim=0 marks a none-entry); reshape to (1,)"
            % name)
    if a.dtype not in _FLAGS:
        raise MXNetError(
            "cannot write %r: dtype %s has no mshadow type flag in "
            "the reference format; cast explicitly (e.g. float32)"
            % (name, a.dtype))


def _tuple_bytes(shape):
    return struct.pack("<I%dI" % len(shape), len(shape), *shape)


def dumps(items, keyed):
    """Encode (name, NDArray-or-sparse) pairs as a reference-compatible
    blob (v2 arrays, uint32 dims — the 1.x layout). Row-sparse and CSR
    arrays write true sparse records, so sparse checkpoints round-trip
    with the reference."""
    from .sparse import RowSparseNDArray, CSRNDArray
    out = [struct.pack("<QQ", LIST_MAGIC, 0),
           struct.pack("<Q", len(items))]
    for name, v in items:
        if isinstance(v, RowSparseNDArray):
            data = _np.ascontiguousarray(_np.asarray(v.data))
            idx = _np.ascontiguousarray(
                _np.asarray(v.indices).astype(_np.int64))
            _check_writable(name, data)
            out.append(struct.pack("<Ii", _V2_MAGIC, 1))
            out.append(_tuple_bytes(data.shape))      # storage shape
            out.append(_tuple_bytes(v.shape))
            out.append(struct.pack("<ii", 1, 0))
            out.append(struct.pack("<i", _FLAGS[data.dtype]))
            out.append(struct.pack("<i", _FLAGS[_np.dtype(_np.int64)]))
            out.append(_tuple_bytes(idx.shape))
            out.append(data.tobytes() + idx.tobytes())
            continue
        if isinstance(v, CSRNDArray):
            data = _np.ascontiguousarray(_np.asarray(v.data))
            indptr = _np.ascontiguousarray(
                _np.asarray(v.indptr).astype(_np.int64))
            idx = _np.ascontiguousarray(
                _np.asarray(v.indices).astype(_np.int64))
            _check_writable(name, data)
            out.append(struct.pack("<Ii", _V2_MAGIC, 2))
            out.append(_tuple_bytes(data.shape))
            out.append(_tuple_bytes(v.shape))
            out.append(struct.pack("<ii", 1, 0))
            out.append(struct.pack("<i", _FLAGS[data.dtype]))
            i64 = struct.pack("<i", _FLAGS[_np.dtype(_np.int64)])
            out.append(i64 + _tuple_bytes(indptr.shape))
            out.append(i64 + _tuple_bytes(idx.shape))
            out.append(data.tobytes() + indptr.tobytes() + idx.tobytes())
            continue
        a = _np.ascontiguousarray(v.asnumpy())
        _check_writable(name, a)
        out.append(struct.pack("<Ii", _V2_MAGIC, 0))
        out.append(_tuple_bytes(a.shape))
        out.append(struct.pack("<ii", 1, 0))          # cpu(0)
        out.append(struct.pack("<i", _FLAGS[a.dtype]))
        out.append(a.tobytes())
    if keyed:
        out.append(struct.pack("<Q", len(items)))
        for name, _v in items:
            b = name.encode()
            out.append(struct.pack("<Q", len(b)) + b)
    else:
        out.append(struct.pack("<Q", 0))
    return b"".join(out)
