"""Symbolic MLP (reference: example/image-classification/symbols/mlp.py)."""
from __future__ import annotations

from .. import symbol as sym

__all__ = ["get_symbol"]


def get_symbol(num_classes=10, hidden=(128, 64)):
    data = sym.Variable("data")
    net = data
    for i, h in enumerate(hidden):
        net = sym.FullyConnected(net, name="fc%d" % (i + 1), num_hidden=h)
        net = sym.Activation(net, name="relu%d" % (i + 1), act_type="relu")
    net = sym.FullyConnected(net, name="fc%d" % (len(hidden) + 1),
                             num_hidden=num_classes)
    return sym.SoftmaxOutput(net, name="softmax")
