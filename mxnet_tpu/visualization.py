"""Network visualization.

Reference: python/mxnet/visualization.py (print_summary, plot_network
via graphviz). plot_network degrades gracefully when graphviz is not
installed (this image has no graphviz); print_summary is pure text.
"""
from __future__ import annotations

from .base import MXNetError
from .symbol.symbol import Symbol, _topo

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Layer-by-layer text summary (reference: visualization.py
    print_summary)."""
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be a Symbol")
    positions = positions or [0.44, 0.64, 0.74, 1.0]
    shape_dict = {}
    out_shape_dict = {}
    if shape is not None:
        # one inference pass over the internals yields both the argument
        # shapes and every intermediate output shape
        internals = symbol.get_internals()
        arg_shapes, int_shapes, _ = internals.infer_shape(**shape)
        for name, s in zip(internals.list_arguments(), arg_shapes):
            shape_dict[name] = s
        for name, s in zip(internals.list_outputs(), int_shapes):
            out_shape_dict[name] = s
    positions = [int(line_length * p) for p in positions]
    fields = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(row, positions):
        line = ""
        for i, field in enumerate(row):
            line += str(field)
            line = line[:positions[i]]
            line += " " * (positions[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(fields, positions)
    print("=" * line_length)
    total_params = [0]

    nodes = _topo(symbol._entries)
    for node in nodes:
        if node.is_var:
            continue
        n_params = 0
        pre = []
        for (src, _i) in node.inputs:
            if src.is_var and src.name in shape_dict:
                cnt = 1
                for d in shape_dict[src.name]:
                    cnt *= d
                if not src.name.endswith(("data", "label")):
                    n_params += cnt
            if not src.is_var:
                pre.append(src.name)
        total_params[0] += n_params
        oshape = (out_shape_dict.get(node.name + "_output")
                  or out_shape_dict.get(node.name + "_output0") or "")
        print_row(["%s (%s)" % (node.name, node.op), str(oshape), n_params,
                   ",".join(pre)], positions)
    print("=" * line_length)
    print("Total params: %d" % total_params[0])
    print("_" * line_length)


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Graphviz rendering (reference: visualization.py plot_network).
    Requires the optional graphviz package."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise MXNetError(
            "plot_network requires the graphviz python package, which is "
            "not installed in this environment; use print_summary instead")
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be a Symbol")
    node_attr = {"shape": "box", "fixedsize": "true", "width": "1.3",
                 "height": "0.8034", "style": "filled"}
    if node_attrs:
        node_attr.update(node_attrs)
    dot = Digraph(name=title)
    nodes = _topo(symbol._entries)
    for node in nodes:
        if node.is_var:
            if hide_weights and not node.name.endswith(("data", "label")):
                continue
            dot.node(node.name, label=node.name, shape="oval")
        else:
            dot.node(node.name, label="%s\n%s" % (node.op, node.name),
                     **node_attr)
    for node in nodes:
        if node.is_var:
            continue
        for (src, _i) in node.inputs:
            if src.is_var and hide_weights and \
                    not src.name.endswith(("data", "label")):
                continue
            dot.edge(src.name, node.name)
    return dot
