"""KVStore: key-value parameter synchronization.

Reference: python/mxnet/kvstore.py (API :105-221), src/kvstore/
kvstore_local.h (reduce→update→broadcast), kvstore_dist.h:44 (PS
semantics: rank-0 init, aggregate-then-update), kvstore_nccl.h.

TPU-native design: the reference's three transports (CPU/GPU tree reduce,
NCCL, ps-lite) collapse onto XLA collectives. Within one process a "push"
of per-device values is a tree-sum (PjRt handles device-to-device);
across hosts (``dist_tpu_sync``) the aggregation is a ``psum`` over the
global device mesh riding ICI/DCN — the `dist_sync` aggregate-then-update
contract with allreduce instead of a parameter server. Async PS mode
(`dist_async`) has no allreduce analog; it is served by the same class
with per-push updates (single-host) and documented as host-driven.
"""
from __future__ import annotations

import functools
import pickle
import time

import numpy as _np

from . import fault as _fault
from .base import MXNetError
from .fault import FaultInjected, TransientKVError
from .ndarray.ndarray import NDArray, zeros
from . import telemetry as _tm
from . import tracing as _tr

__all__ = ["KVStore", "create", "TransientKVError"]


def _note_straggler_wait(seconds):
    """Book time parked at a distributed rendezvous into the goodput
    ledger's `straggler_wait` category (no-op without a live ledger)."""
    try:
        from . import goodput as _gp
        _gp.note("straggler_wait", seconds)
    except Exception:
        pass

# PS ops that mutate server state: they carry a sequence number so a
# retried/resent RPC whose first copy already applied (reply lost on a
# dead connection) is deduplicated server-side instead of double-applied
_MUTATING_OPS = frozenset(
    ("PUSH", "INIT", "SET_OPTIMIZER", "SET_COMPRESSION", "BARRIER"))


def _approx_nbytes(value):
    """Total payload bytes of a push/pull value tree (NDArray, sparse
    NDArray, or nested lists of them) — feeds kvstore/bytes_total."""
    if isinstance(value, (list, tuple)):
        return sum(_approx_nbytes(v) for v in value)
    total = 0
    for attr in ("_data", "data", "indices", "indptr"):
        arr = getattr(value, attr, None)
        nb = getattr(arr, "nbytes", None)
        if nb is not None:
            total += int(nb)
            if attr == "_data":
                break
    return total


@functools.lru_cache(maxsize=None)
def _proc_reducer(nproc):
    """One mesh + one jitted sum-over-processes per process lifetime.

    Cached so the hot push path reuses the same compiled reducer (jit's
    own cache then keys on shape/dtype); a fresh lambda per call would
    retrace + recompile every gradient push."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    per_proc = {}
    for d in sorted(jax.devices(), key=lambda d: (d.process_index, d.id)):
        per_proc.setdefault(d.process_index, d)
    mesh = Mesh(np.array([per_proc[i] for i in range(nproc)]), ("proc",))
    rep = NamedSharding(mesh, P())
    reducer = jax.jit(lambda x: jnp.sum(x, axis=0), out_shardings=rep)
    return (NamedSharding(mesh, P("proc")), rep,
            per_proc[jax.process_index()], reducer)


def _ctype_key_value(keys, vals):
    """Normalize to (list_of_keys, list_of_value_lists)."""
    if isinstance(keys, (str, int)):
        keys = [keys]
        vals = [vals]
    from .ndarray.sparse import BaseSparseNDArray
    out_vals = []
    for v in vals:
        if isinstance(v, (NDArray, BaseSparseNDArray)):
            out_vals.append([v])
        else:
            out_vals.append(list(v))
    return list(keys), out_vals


class KVStore(object):
    """A store for synchronized parameter values (reference:
    python/mxnet/kvstore.py:105)."""

    def __init__(self, kv_type="local"):
        import os
        self._type = kv_type
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._compression_params = None
        self._compressor = None
        self._barrier_count = 0
        self._sock = None
        self._sock_lock = None
        self._ps_host = None
        self._closed = False
        # mutating-RPC sequence numbers start from a random per-client
        # base: the server's at-most-once cache (and its snapshot-
        # restored commit records) matches on seq equality per rank, so
        # a RESTARTED worker process — a fresh client whose counter
        # would otherwise also start at 1 — must never collide with its
        # predecessor's committed seqs and have its first mutating RPC
        # swallowed as a duplicate
        self._seq = int.from_bytes(os.urandom(6), "big") << 16
        self._dist_acquired = False
        if kv_type == "dist_tpu_sync":
            # the synchronous hot path never touches the socket PS:
            # push/pull fold into the fused XLA program as in-program
            # collectives (Executor.train_step under the global dp
            # mesh), so this type only needs the multi-host runtime up
            from . import dist_runtime as _dist
            _dist.acquire()
            self._dist_acquired = True
            if os.environ.get("MXNET_TPU_PS_URI"):
                import logging
                logging.info(
                    "dist_tpu_sync ignores MXNET_TPU_PS_URI: the sync "
                    "hot path runs on in-program collectives (use "
                    "dist_async for the socket parameter server)")
            if _tm._enabled:
                _tm.gauge("kvstore/dist_world_size",
                          "Processes in the dist_tpu_sync cluster"
                          ).set(self.num_workers)
                _tm.gauge("kvstore/dist_rank",
                          "This process's rank in the dist_tpu_sync "
                          "cluster").set(self.rank)
        elif kv_type.startswith("dist") and \
                os.environ.get("MXNET_TPU_PS_URI"):
            self._connect_ps()

    # -- parameter-server transport (DCN tier) -----------------------------
    def _connect_ps(self):
        """Connect to the host-side PS (kvstore_server.py) — the analog of
        ps-lite ZPush/ZPull over DCN (src/kvstore/kvstore_dist.h:50).
        Used for dist_async / cross-pod coordination; the synchronous
        intra-pod path stays on XLA allreduce."""
        import os
        import threading
        from .config import get as _cfg
        self._ps_host = os.environ["MXNET_TPU_PS_URI"]
        self._ps_port = int(os.environ.get("MXNET_TPU_PS_PORT", "9090"))
        self._env_rank = int(os.environ.get("MXNET_TPU_RANK", "0"))
        self._env_nw = int(os.environ.get("MXNET_TPU_NUM_WORKERS", "1"))
        self._ps_token = os.environ.get("MXNET_TPU_PS_TOKEN", "")
        self._dead_s = float(_cfg("MXNET_KV_DEAD_S"))
        self._server_inc = None      # last observed server incarnation
        self._member_epoch = 1       # this rank's membership epoch
        self._sock_lock = threading.Lock()
        with self._sock_lock:
            self._dial()
        self._start_heartbeat()

    def _dial(self):
        """(Re-)establish the PS connection: socket (with the
        ``MXNET_KV_TIMEOUT_MS`` deadline so a dead server can never hang
        an op), auth, and rank-registration HELLO. The HELLO response
        names the server's incarnation — a change means the server
        restarted (failover): this rank is re-registered here and the
        retry loop replays any in-flight RPC under its original
        sequence number. Caller holds ``_sock_lock``."""
        import socket
        from .config import get as _cfg
        from .kvstore_server import send_msg, recv_msg
        _fault.inject("kv.client.reconnect")
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        timeout_ms = int(_cfg("MXNET_KV_TIMEOUT_MS"))
        if timeout_ms > 0:
            sock.settimeout(timeout_ms / 1e3)
        try:
            sock.connect((self._ps_host, self._ps_port))
            if self._ps_token:
                send_msg(sock, ("AUTH", None, self._ps_token))
                status, payload = recv_msg(sock)[:2]
                if status != "OK":
                    raise MXNetError(
                        "kvstore server auth failed: %s" % payload)
            # register this rank for liveness tracking / membership
            send_msg(sock, ("HELLO", None, self._env_rank))
            resp = recv_msg(sock)
            status, payload = resp[0], resp[1]
            if status != "OK":
                raise MXNetError(
                    "kvstore server rejected HELLO: %s" % payload)
            if isinstance(payload, dict):
                self._member_epoch = int(payload.get("member_epoch", 1))
                self._note_incarnation(payload.get("incarnation"))
            elif len(resp) > 2:
                self._note_incarnation(resp[2])
        except BaseException:
            sock.close()
            raise
        self._sock = sock

    def _note_incarnation(self, inc):
        """Track the server incarnation carried in every response; a
        change mid-session is a completed failover — counted and
        logged, with the at-most-once seq numbers guaranteeing the
        replayed in-flight RPCs apply exactly once."""
        if inc is None:
            return
        if self._server_inc is None:
            self._server_inc = inc
        elif inc != self._server_inc:
            old, self._server_inc = self._server_inc, inc
            if _tm._enabled:
                _tm.counter(
                    "kvstore/server_failovers_total",
                    "KVStore server restarts observed by this client "
                    "(incarnation changes)").inc()
            try:
                from . import blackbox as _bb
                _bb.record_event("failover", old=str(old), new=str(inc),
                                 rank=self._env_rank)
            except Exception:
                pass
            import logging
            logging.warning(
                "kvstore server restarted (incarnation %s -> %s); rank "
                "%d re-registered, in-flight RPCs replay under their "
                "original sequence numbers", old, inc, self._env_rank)

    def _start_heartbeat(self):
        """Background liveness beacon: HELLO every ``MXNET_KV_DEAD_S/3``
        seconds on a DEDICATED connection, so a rank parked in a long
        sync round (or a long local compile) on the main socket never
        reads as dead. Dies with the process — which is exactly the
        signal the server's liveness timeout exists to catch."""
        import threading
        self._hb_stop = threading.Event()
        interval = max(0.2, self._dead_s / 3.0)

        def _beat():
            from .kvstore_server import send_msg, recv_msg
            sock = None
            while not self._hb_stop.wait(interval):
                try:
                    if sock is None:
                        sock = self._hb_dial()
                    send_msg(sock, ("HELLO", None, self._env_rank))
                    recv_msg(sock)
                except Exception:
                    if sock is not None:
                        try:
                            sock.close()
                        except OSError:
                            pass
                    sock = None   # redial on the next beat
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass

        t = threading.Thread(target=_beat, daemon=True,
                             name="mx-kv-heartbeat-%d" % self._env_rank)
        t.start()
        self._hb_thread = t

    def _hb_dial(self):
        import socket
        from .kvstore_server import send_msg, recv_msg
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.settimeout(max(1.0, self._dead_s / 3.0))
        sock.connect((self._ps_host, self._ps_port))
        if self._ps_token:
            send_msg(sock, ("AUTH", None, self._ps_token))
            if recv_msg(sock)[0] != "OK":
                sock.close()
                raise MXNetError("heartbeat auth failed")
        return sock

    def close(self):
        """Tear down the PS transport (heartbeat thread + socket) and
        make the store TERMINAL: further PS ops raise instead of
        silently redialing — a resurrected connection would run without
        its liveness heartbeat and read as a dead rank mid-round. Safe
        to call twice; a no-op for local/device stores.

        A ``dist_tpu_sync`` store instead releases its reference on the
        ``jax.distributed`` runtime (dist_runtime.py): the last release
        shuts the coordinator connection down cleanly when this
        framework initialized it."""
        if self._dist_acquired:
            self._dist_acquired = False
            from . import dist_runtime as _dist
            _dist.release()
        if self._ps_host is not None:
            # only a PS-backed store becomes terminal; local/device
            # stores have no transport to tear down
            self._closed = True
        hb = getattr(self, "_hb_stop", None)
        if hb is not None:
            hb.set()
        if self._sock is not None:
            with self._sock_lock:
                if self._sock is not None:
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None

    @property
    def member_epoch(self):
        """This rank's membership epoch at the server (PS mode): 1 on
        first registration, +1 per re-admission after being declared
        dead — >1 identifies a REJOINING worker, which should pull the
        cluster's current weights instead of pushing its own
        initializer output (model._initialize_kvstore does)."""
        return getattr(self, "_member_epoch", 1)

    def _ps_call(self, op, key=None, value=None):
        """One PS RPC under the retry policy. Mutating ops carry a
        sequence number assigned ONCE here, so every resend after a
        reconnect is deduplicated server-side — at-most-once apply,
        zero lost and zero doubled updates."""
        seq = None
        if op in _MUTATING_OPS:
            self._seq += 1
            seq = self._seq
        return self._retrying(
            "ps_" + op.lower(),
            lambda: self._ps_call_once(op, key, value, seq))

    def _check_open(self, op):
        """A closed PS store is TERMINAL: with its socket gone the op
        routing would silently fall back to LOCAL-store semantics (and
        a resurrected connection would run without its liveness
        heartbeat), so every op refuses instead."""
        if self._closed:
            raise MXNetError(
                "kvstore %s on a closed store: close() tore down the "
                "PS transport (heartbeat included); create a new "
                "KVStore to rejoin" % op)

    def _ps_call_once(self, op, key, value, seq):
        from .kvstore_server import send_msg, recv_msg
        self._check_open(op.lower())
        # the active span context (the kv.attempt span) rides in the
        # RPC payload, so server-side handling — and the seq-cache
        # replay shield — surfaces under the client's trace
        tctx = _tr.wire_context()
        msg = (op, key, value, seq) if tctx is None \
            else (op, key, value, seq, tctx)
        with self._sock_lock:
            if self._sock is None:
                raise ConnectionError("kvstore server connection lost")
            send_msg(self._sock, msg)
            resp = recv_msg(self._sock)
        status, payload = resp[0], resp[1]
        if len(resp) > 2:
            # every response names the server incarnation: restart
            # detection even when the TCP connection survived
            self._note_incarnation(resp[2])
        if len(resp) > 3 and resp[3]:
            # (proc_token, server_now, spans) recorded for this RPC;
            # graft() deduplicates on span id (a cache-replayed response
            # cannot double-count them) and rebases an out-of-process
            # server's perf_counter epoch onto ours via the clock pair
            token, server_now, spans = resp[3]
            _tr.graft(spans,
                      clock=(token, server_now, _tm.monotonic()))
        if status == "RETRY":
            raise TransientKVError(
                "kvstore server asked to retry %s: %s" % (op, payload))
        if status != "OK":
            raise MXNetError("kvstore server error: %s" % payload)
        return payload

    def _retrying(self, op, fn):
        """Run ``fn`` under the kvstore transport retry policy: up to
        ``MXNET_KV_RETRIES`` retries with jittered exponential backoff
        (base ``MXNET_KV_BACKOFF_MS``), bounded by the
        ``MXNET_KV_TIMEOUT_MS`` per-op deadline, reconnecting to the PS
        between attempts. Only transport-class failures
        (:class:`TransientKVError`, :class:`FaultInjected`, socket/OS
        errors) are retried; exhausting the policy raises a clear
        :class:`MXNetError` naming the op and attempt count — a dead
        server degrades to an error, never a hang."""
        import random as _pyrandom
        import socket
        import time as _time
        from .config import get as _cfg
        retries = int(_cfg("MXNET_KV_RETRIES"))
        budget_s = int(_cfg("MXNET_KV_TIMEOUT_MS")) / 1e3
        base_s = max(1, int(_cfg("MXNET_KV_BACKOFF_MS"))) / 1e3
        deadline = (_tm.monotonic() + budget_s) if budget_s > 0 else None
        attempt = 0
        while True:
            try:
                if _tr.active() is None:
                    return fn()
                # one span per attempt under the op's client span: a
                # retried op shows each try (the second onward marked
                # retried), all sharing the same parent
                attrs = {"op": op, "attempt": attempt + 1}
                if attempt:
                    attrs["retried"] = True
                with _tr.child_span("kv.attempt", attrs=attrs):
                    return fn()
            except (TransientKVError, FaultInjected, ConnectionError,
                    socket.timeout, TimeoutError, OSError) as exc:
                attempt += 1
                timed_out = (deadline is not None
                             and _tm.monotonic() >= deadline)
                if attempt > retries or timed_out:
                    if _tm._enabled:
                        _tm.counter(
                            "kvstore/giveups_total",
                            "KVStore ops abandoned after exhausting "
                            "retries or deadline", ("op",)).labels(op).inc()
                    reason = ("deadline of %d ms exceeded"
                              % int(budget_s * 1e3)) if timed_out \
                        else "%d retries exhausted" % retries
                    raise MXNetError(
                        "kvstore %s failed after %d attempt(s) (%s); "
                        "last error: %s" % (op, attempt, reason, exc)
                    ) from exc
                if _tm._enabled:
                    _tm.counter("kvstore/retries_total",
                                "KVStore attempts retried after a "
                                "transient failure", ("op",)
                                ).labels(op).inc()
                delay = base_s * (2 ** (attempt - 1))
                delay *= 0.5 + _pyrandom.random() * 0.5    # full jitter
                if deadline is not None:
                    delay = min(delay, max(0.0,
                                           deadline - _tm.monotonic()))
                _time.sleep(delay)
                if self._ps_host is not None:
                    with self._sock_lock:
                        try:
                            self._dial()
                        except (OSError, MXNetError):
                            pass   # next attempt surfaces the failure

    def _server_profiler_command(self, cmd, payload):
        """Route a profiler command to the PS server process
        (reference: KVStoreServerProfilerCommand, kvstore.h:49;
        exercised by tests/nightly/test_server_profiling.py)."""
        if self._sock is None:
            raise MXNetError(
                "server profiler commands need a dist kvstore connected "
                "to a PS server")
        return self._ps_call("PROFILER", cmd, payload)

    # -- identity ----------------------------------------------------------
    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        """This worker's rank (reference: kvstore.py rank). PS mode reads
        MXNET_TPU_RANK; multi-host JAX maps to ``jax.process_index()``."""
        if self._sock is not None:
            return self._env_rank
        import jax
        return jax.process_index()

    @property
    def num_workers(self):
        if self._sock is not None:
            return self._env_nw
        import jax
        return jax.process_count()

    # -- core API ----------------------------------------------------------
    def init(self, key, value):
        """Initialize a key. Rank-0 value wins (reference:
        kvstore_dist.h rank-0 init + broadcast; with allreduce semantics
        every worker holds the full value, so init is local assignment).
        The PS INIT RPC runs under the transport retry policy and
        precedes the local store mutation, so a retried init never trips
        the double-init check."""
        self._check_open("init")
        with _tr.child_span("kv.init"):
            keys, vals = _ctype_key_value(key, value)
            for k, vlist in zip(keys, vals):
                if k in self._store:
                    raise MXNetError("key %r already initialized" % (k,))
                if self._sock is not None:
                    self._ps_call("INIT", k, vlist[0].asnumpy())
                if self._type == "dist_tpu_sync" and self._sock is None \
                        and self.num_workers > 1:
                    # rank-0 broadcast through a device collective in
                    # place of the reference's socket INIT round: every
                    # rank adopts process 0's value, so all replicas
                    # start from identical params without a PS hop
                    self._store[k] = self._broadcast0(vlist[0])
                else:
                    self._store[k] = vlist[0].copy()
        if _tm._enabled:
            _tm.record_kvstore("init", None, _approx_nbytes(value))

    def push(self, key, value, priority=0):
        """Aggregate values; if an optimizer is installed, run the update
        on the store (reference: kvstore_local.h:184-212 PushImpl:
        comm_->Reduce then updater_). Transient transport failures
        (injected at the ``kv.push`` point, or socket-level in PS mode)
        retry with jittered backoff under the per-op deadline; the
        ``kv.push`` injection point fires before any mutation, so a
        retried push applies exactly once."""
        ctx = _tr.active()
        t0 = _tm.monotonic() if _tm._enabled else None
        with _tr.child_span("kv.push", ctx=ctx):
            ret = self._retrying(
                "push", lambda: self._push_impl(key, value, priority))
        if t0 is not None:
            _tm.record_kvstore("push", _tm.monotonic() - t0,
                               _approx_nbytes(value),
                               trace_id=ctx.trace_id if ctx else None)
        return ret

    def _push_impl(self, key, value, priority=0):
        self._check_open("push")
        _fault.inject("kv.push")
        keys, vals = _ctype_key_value(key, value)
        for k, vlist in zip(keys, vals):
            if k not in self._store:
                raise MXNetError("please init key %r before push" % (k,))
            from .ndarray.sparse import RowSparseNDArray
            if any(isinstance(v, RowSparseNDArray) for v in vlist) and \
                    not all(isinstance(v, RowSparseNDArray)
                            for v in vlist):
                # mixed dense/sparse slices for one key: densify and take
                # the dense path (reference kvstore_local densifies when
                # storage types disagree)
                vlist = [v.todense() if isinstance(v, RowSparseNDArray)
                         else v for v in vlist]
            if any(isinstance(v, RowSparseNDArray) for v in vlist):
                # row_sparse gradient flow (reference: kvstore_local.h
                # PushImpl kRowSparseStorage): concat per-device rows,
                # sum duplicates, then lazy-update or scatter-add
                import jax.numpy as jnp
                from .ops.sparse_ops import rsp_aggregate
                idx = jnp.concatenate([v.indices for v in vlist])
                data = jnp.concatenate([v.data for v in vlist])
                i2, v2 = rsp_aggregate(idx, data)
                agg = RowSparseNDArray(v2, i2, vlist[0].shape)
                # (gradient compression is not applied to sparse pushes,
                # matching the reference: kvstore_dist rejects compression
                # for kRowSparseStorage)
                if self._sock is not None:
                    self._ps_call("PUSH", k, agg.todense().asnumpy())
                elif self._updater is not None:
                    self._updater(self._key_index(k), agg, self._store[k])
                else:
                    # same semantics as the dense no-updater path: the
                    # store holds the latest reduced value, not a running
                    # accumulation
                    self._store[k]._set_data(agg.todense()._data)
                continue
            agg = self._aggregate(k, vlist)
            if self._sock is not None:
                # PS hop: local reduce -> (compress) -> ZPush analog
                # (kvstore_dist.h:349-371); server aggregates / updates.
                g = agg.asnumpy()
                if self._compressor is not None:
                    self._ps_call("PUSH", k, self._compressor.compress(k, g))
                else:
                    self._ps_call("PUSH", k, g)
                continue
            if self._updater is not None:
                # updater mutates the stored weight in place
                self._updater(self._key_index(k), agg, self._store[k])
            else:
                self._store[k]._set_data(agg._data)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """Broadcast the stored value into ``out`` (reference:
        kvstore_local.h PullImpl → comm_->Broadcast)."""
        ctx = _tr.active()
        t0 = _tm.monotonic() if _tm._enabled else None
        with _tr.child_span("kv.pull", ctx=ctx):
            ret = self._retrying(
                "pull",
                lambda: self._pull_impl(key, out, priority, ignore_sparse))
        if t0 is not None:
            _tm.record_kvstore("pull", _tm.monotonic() - t0,
                               _approx_nbytes(out),
                               trace_id=ctx.trace_id if ctx else None)
        return ret

    def _pull_impl(self, key, out=None, priority=0, ignore_sparse=True):
        self._check_open("pull")
        _fault.inject("kv.pull")
        assert out is not None
        keys, outs = _ctype_key_value(key, out)
        for k, olist in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("please init key %r before pull" % (k,))
            if self._sock is not None:
                import jax.numpy as jnp
                fresh = jnp.asarray(self._ps_call("PULL", k))
                self._store[k]._set_data(fresh)
            src = self._store[k]
            for o in olist:
                # copy, don't alias: a store-side updater may later run
                # a buffer-donating update on src; an aliased out would
                # be invalidated with it
                o._set_data(src._data.copy())

    def pushpull(self, key, value, out=None, priority=0):
        """Fused push+pull (reference: kvstore.py pushpull — on TPU this is
        the natural allreduce: one collective, no server round-trip)."""
        self.push(key, value, priority=priority)
        self.pull(key, out=out if out is not None else value,
                  priority=priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in ``row_ids`` (reference: kvstore.py
        row_sparse_pull; sparse embedding workflows). Dense rows are
        gathered host-side until row_sparse storage lands."""
        self._check_open("row_sparse_pull")
        assert out is not None and row_ids is not None
        keys, outs = _ctype_key_value(key, out)
        rids, _ = _ctype_key_value(row_ids, row_ids)
        for k, olist in zip(keys, outs):
            rows = row_ids if isinstance(row_ids, NDArray) else row_ids[0]
            if self._sock is not None:
                # server-side row gather: only the requested embedding rows
                # cross the wire (reference: kvstore_dist.h
                # PullRowSparse over ps-lite)
                import jax.numpy as jnp
                sub = self._ps_call("PULL_ROWS", k,
                                    rows.asnumpy().astype("int64"))
                for o in olist:
                    o._set_data(jnp.asarray(sub))
                continue
            src = self._store[k]
            for o in olist:
                o._set_data(src._data[rows._data.astype("int32")])

    def _broadcast0(self, value):
        """Process-0's value to every process as a fresh NDArray — the
        ``dist_tpu_sync`` replacement for socket INIT rounds.  One
        collective over the device links at init time; the steady-state
        hot path (the fused train step's in-program ``psum``) never
        calls back here."""
        import jax.numpy as jnp
        from jax.experimental import multihost_utils
        out = multihost_utils.broadcast_one_to_all(value.asnumpy())
        if _tm._enabled:
            _tm.counter("kvstore/broadcast_init_total",
                        "dist_tpu_sync rank-0 init broadcasts (one "
                        "collective per key, replacing socket INIT "
                        "rounds)").inc()
        return NDArray(jnp.asarray(_np.asarray(out)), ctx=value.context)

    # -- aggregation -------------------------------------------------------
    def _aggregate(self, key, vlist):
        """Sum per-device contributions. Single values pass through; the
        multi-host ``dist_tpu_sync`` path additionally allreduces across
        processes (ICI/DCN via XLA psum). With gradient compression on,
        each contribution goes through the codec (quantize + error
        feedback) before the reduce — the reference applies compression
        on exactly this hop (gradient_compression.h wiring in
        kvstore_dist.h:586)."""
        if self._compressor is not None and self._sock is None:
            import jax.numpy as jnp
            vlist = [NDArray(jnp.asarray(self._compressor.roundtrip(
                (key, i), v.asnumpy())), ctx=v.context)
                for i, v in enumerate(vlist)]
        agg = vlist[0]
        if len(vlist) > 1:
            total = vlist[0]._data
            for v in vlist[1:]:
                total = total + v._data
            agg = NDArray(total, ctx=vlist[0].context)
        if self._type.startswith("dist") and self._sock is None \
                and self.num_workers > 1:
            agg = self._cross_process_allreduce(agg)
        return agg

    def _cross_process_allreduce(self, value):
        """Device-side allreduce across processes (multi-host). Reference
        analog: kvstore_dist.h PushDefault → server aggregation; here ONE
        XLA all-reduce over ICI/DCN replaces the PS round trip.

        The contribution is staged as one shard of a process-sharded
        global array and summed under jit with a replicated output, so
        the reduction runs on device links with O(1) host memory — not
        the O(n_workers) host-side gather-and-sum a naive
        process_allgather would cost (wrong shape for a 256-chip pod)."""
        import jax
        import jax.numpy as jnp
        nproc = jax.process_count()
        if nproc == 1:
            return value
        shard_sh, rep_sh, my_dev, reducer = _proc_reducer(nproc)
        local = jax.device_put(value._data[None], my_dev)
        garr = jax.make_array_from_single_device_arrays(
            (nproc,) + tuple(value.shape), shard_sh, [local])
        summed = reducer(garr)
        return NDArray(jnp.asarray(summed.addressable_shards[0].data),
                       ctx=value.context)

    def _key_index(self, k):
        if isinstance(k, int):
            return k
        return k

    # -- optimizer installation -------------------------------------------
    def set_optimizer(self, optimizer):
        """Install an optimizer to run updates on the store
        (reference: kvstore.py set_optimizer; in dist mode the reference
        pickles the optimizer to the servers — with allreduce every worker
        runs the same update locally, which is semantically identical for
        sync mode)."""
        from .optimizer import get_updater
        self._check_open("set_optimizer")
        self._optimizer = optimizer
        if self._sock is not None:
            # ship the optimizer to the server, which then runs updates
            # store-side (reference: kvstore.py set_optimizer pickling to
            # servers via _send_command_to_servers)
            if self.rank == 0:
                self._ps_call("SET_OPTIMIZER", None, pickle.dumps(optimizer))
            return
        self._updater = get_updater(optimizer)

    def _set_updater(self, updater):
        self._updater = updater

    def set_gradient_compression(self, compression_params):
        """Enable 2-bit/int8 gradient compression with error feedback
        (reference: gradient_compression.h:38; see
        mxnet_tpu/gradient_compression.py). Applies on the communication
        hop: worker→server in PS mode, per-contribution quantization in
        local/allreduce mode."""
        from .gradient_compression import create_compressor
        self._check_open("set_gradient_compression")
        self._compression_params = dict(compression_params)
        self._compressor = create_compressor(self._compression_params)
        if self._sock is not None:
            self._ps_call("SET_COMPRESSION", None, self._compression_params)

    # -- sync --------------------------------------------------------------
    def barrier(self):
        """Global barrier (reference: kvstore.py _barrier → ps
        Postoffice::Barrier). In PS mode a dead rank fails the barrier
        fast with an :class:`MXNetError` naming the rank(s) — never a
        hang; the ``kv.barrier_wait`` span times how long this rank
        sat at the rendezvous (straggler forensics)."""
        self._check_open("barrier")
        if self._sock is not None:
            _t0 = time.perf_counter()
            with _tr.child_span("kv.barrier_wait"):
                self._ps_call("BARRIER")
            _note_straggler_wait(time.perf_counter() - _t0)
            self._barrier_count += 1
            return
        import jax
        if self.num_workers > 1:
            from jax.experimental import multihost_utils
            _t0 = time.perf_counter()
            multihost_utils.sync_global_devices(
                "kvstore_barrier_%d" % self._barrier_count)
            _note_straggler_wait(time.perf_counter() - _t0)
        self._barrier_count += 1

    def num_dead_node(self, node_id=0, timeout=None):
        """Count of workers presumed dead: no traffic (RPCs or
        heartbeats) for ``timeout`` seconds, default the cluster's
        ``MXNET_KV_DEAD_S`` (reference: include/mxnet/kvstore.h:353
        ps-lite heartbeat liveness). 0 outside PS mode —
        XLA-collective workers fail as a unit, there is no
        partial-death state to query."""
        self._check_open("num_dead_node")
        if self._sock is None:
            return 0
        return len(self._ps_call("DEAD_NODES", None, timeout))

    # -- optimizer state io ------------------------------------------------
    def save_optimizer_states(self, fname, dump_optimizer=False):
        assert self._updater is not None, "updater is not initialized"
        from .checkpoint import atomic_writer
        with atomic_writer(fname) as fout:
            fout.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        assert self._updater is not None, "updater is not initialized"
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())


def create(name="local"):
    """Create a KVStore (reference: src/kvstore/kvstore.cc:40-77 factory).

    Supported types: ``local``, ``device`` (both intra-process),
    ``dist_tpu_sync`` (multi-host in-program collectives: the gradient
    all-reduce folds into the fused train step as a GSPMD ``psum`` over
    the global dp mesh — no socket parameter server on the hot path;
    see docs/distributed_training.md), ``dist_sync``/``dist_device_sync``
    (host-driven allreduce, or the socket PS when ``MXNET_TPU_PS_URI``
    is set), ``dist_async`` (per-push PS update, no barrier — the
    elastic/failover tier of docs/fault_tolerance.md), ``nccl`` (alias
    of device — collectives are XLA's job on TPU)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    known = ("local", "device", "nccl", "dist_sync", "dist_device_sync",
             "dist_tpu_sync", "dist_async", "dist")
    if name not in known:
        raise MXNetError("unknown KVStore type %r (supported: %s)"
                         % (name, ", ".join(known)))
    return KVStore(name)
