"""RecordIO + image pipeline tests
(reference: tests/python/unittest/test_recordio.py, test_image.py)."""
import os
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio, image
from mxnet_tpu.gluon.data import DataLoader
from mxnet_tpu.gluon.data.dataset import ArrayDataset


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "test.rec")
    w = recordio.MXRecordIO(path, "w")
    records = [b"x" * n for n in (1, 5, 100, 1000)]
    for r in records:
        w.write(r)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    for expect in records:
        assert r.read() == expect
    assert r.read() is None
    r.close()


def test_recordio_native_backend_used():
    from mxnet_tpu import _native
    lib = _native.recordio_lib()
    assert lib is not None, "native recordio library failed to build"


def test_indexed_recordio(tmp_path):
    path = str(tmp_path / "test.rec")
    idx = str(tmp_path / "test.idx")
    w = recordio.MXIndexedRecordIO(idx, path, "w")
    for i in range(10):
        w.write_idx(i, b"record%d" % i)
    w.close()
    r = recordio.MXIndexedRecordIO(idx, path, "r")
    assert r.keys == list(range(10))
    assert r.read_idx(7) == b"record7"
    assert r.read_idx(2) == b"record2"
    r.close()


def test_pack_unpack_label_array():
    header = recordio.IRHeader(0, np.array([1.0, 2.0, 3.0]), 7, 0)
    s = recordio.pack(header, b"payload")
    h2, payload = recordio.unpack(s)
    assert payload == b"payload"
    assert h2.id == 7
    assert np.allclose(h2.label, [1.0, 2.0, 3.0])


def test_pack_unpack_scalar_label():
    s = recordio.pack((0, 3.0, 1, 0), b"data")
    h, payload = recordio.unpack(s)
    assert h.label == 3.0
    assert payload == b"data"


def test_pack_img_unpack_img():
    img = (np.random.rand(32, 32, 3) * 255).astype(np.uint8)
    s = recordio.pack_img((0, 1.0, 0, 0), img, quality=100, img_fmt=".png")
    header, decoded = recordio.unpack_img(s)
    assert header.label == 1.0
    assert decoded.shape == (32, 32, 3)
    # png is lossless: exact round trip (RGB order preserved)
    assert np.array_equal(decoded.asnumpy(), img)


def test_image_resize_crop():
    img = mx.nd.array((np.random.rand(40, 60, 3) * 255).astype(np.uint8),
                      dtype="uint8")
    out = image.imresize(img, 30, 20)
    assert out.shape == (20, 30, 3)
    short = image.resize_short(img, 20)
    assert min(short.shape[:2]) == 20
    crop, rect = image.center_crop(img, (20, 20))
    assert crop.shape == (20, 20, 3)
    rnd, rect = image.random_crop(img, (16, 16))
    assert rnd.shape == (16, 16, 3)


def test_augmenter_list():
    augs = image.CreateAugmenter((3, 24, 24), resize=26, rand_mirror=True,
                                 mean=True, std=True)
    img = mx.nd.array((np.random.rand(40, 60, 3) * 255).astype(np.uint8),
                      dtype="uint8")
    for aug in augs:
        img = aug(img)
    assert img.shape == (24, 24, 3)
    assert img.dtype == np.float32


def test_image_iter_from_rec(tmp_path):
    # build a small rec pack
    path = str(tmp_path / "imgs.rec")
    idx = str(tmp_path / "imgs.idx")
    w = recordio.MXIndexedRecordIO(idx, path, "w")
    for i in range(8):
        img = (np.random.rand(32, 32, 3) * 255).astype(np.uint8)
        w.write_idx(i, recordio.pack_img((0, float(i % 2), i, 0), img))
    w.close()
    it = image.ImageIter(batch_size=4, data_shape=(3, 28, 28),
                         path_imgrec=path, rand_crop=True, rand_mirror=True)
    batch = next(it)
    assert batch.data[0].shape == (4, 3, 28, 28)
    assert batch.label[0].shape == (4,)
    n = 1 + sum(1 for _ in it)
    assert n == 2


def test_dataloader_with_workers():
    X = np.random.rand(32, 4).astype(np.float32)
    y = np.arange(32, dtype=np.float32)
    ds = ArrayDataset(X, y)
    loader = DataLoader(ds, batch_size=8, shuffle=False, num_workers=2)
    seen = 0
    for data, label in loader:
        assert data.shape == (8, 4)
        np.testing.assert_allclose(label.asnumpy(),
                                   y[seen:seen + 8])
        seen += 8
    assert seen == 32


def test_record_file_dataset(tmp_path):
    path = str(tmp_path / "ds.rec")
    idx = str(tmp_path / "ds.idx")
    w = recordio.MXIndexedRecordIO(idx, path, "w")
    for i in range(5):
        w.write_idx(i, b"item%d" % i)
    w.close()
    from mxnet_tpu.gluon.data.dataset import RecordFileDataset
    ds = RecordFileDataset(path)
    assert len(ds) == 5
    assert ds[3] == b"item3"
