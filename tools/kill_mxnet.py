#!/usr/bin/env python
"""Kill stray distributed-training processes, locally or over ssh.

Capability analog of the reference's ``tools/kill-mxnet.py`` (which
pdsh-kills python jobs on every host in a hostfile): finds processes
whose command line mentions the target script or the MXNET_TPU PS
contract, and terminates them. The invoking process (and its parents)
are never touched — a naive ``pkill -f`` matches its own command line.

    python tools/kill_mxnet.py                      # this host, default pattern
    python tools/kill_mxnet.py --pattern train.py   # custom match
    python tools/kill_mxnet.py --hostfile hosts.txt # over ssh too
"""
import argparse
import os
import signal
import subprocess
import sys


DEFAULT_PATTERNS = ("mxnet_tpu.kvstore_server", "kv-store dist",
                    "MXNET_TPU_ROLE")


def _candidates(patterns):
    """(pid, cmdline) of matching processes, excluding self+ancestors."""
    skip = set()
    pid = os.getpid()
    while pid > 1:
        skip.add(pid)
        try:
            with open("/proc/%d/stat" % pid) as f:
                pid = int(f.read().split()[3])
        except (OSError, ValueError, IndexError):
            break
    out = []
    for entry in os.listdir("/proc"):
        if not entry.isdigit() or int(entry) in skip:
            continue
        try:
            with open("/proc/%s/cmdline" % entry, "rb") as f:
                cmd = f.read().replace(b"\0", b" ").decode(
                    "utf-8", "replace").strip()
        except OSError:
            continue
        if any(p in cmd for p in patterns):
            out.append((int(entry), cmd))
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--pattern", action="append", default=[],
                    help="extra substring(s) to match (repeatable)")
    ap.add_argument("--hostfile",
                    help="also kill on every host listed (ssh)")
    ap.add_argument("--ssh-port", type=int, default=22)
    ap.add_argument("--dry-run", action="store_true",
                    help="list matches without killing")
    args = ap.parse_args()

    patterns = tuple(args.pattern) or DEFAULT_PATTERNS
    n = 0
    for pid, cmd in _candidates(patterns):
        print("%s pid %d: %s" % ("would kill" if args.dry_run
                                 else "killing", pid, cmd[:120]))
        if not args.dry_run:
            try:
                os.kill(pid, signal.SIGTERM)
            except OSError as e:
                print("  failed: %s" % e, file=sys.stderr)
                continue
        n += 1
    print("%d local process(es) matched" % n)

    if args.hostfile:
        with open(args.hostfile) as f:
            hosts = [ln.strip() for ln in f
                     if ln.strip() and not ln.startswith("#")]
        remote = "python %s %s %s" % (
            os.path.abspath(__file__),
            " ".join("--pattern %s" % p for p in patterns),
            "--dry-run" if args.dry_run else "")
        for host in hosts:
            r = subprocess.run(
                ["ssh", "-p", str(args.ssh_port),
                 "-o", "StrictHostKeyChecking=no", "-o", "BatchMode=yes",
                 host, remote], capture_output=True, text=True)
            tag = "ok" if r.returncode == 0 else "rc=%d" % r.returncode
            print("[%s] %s %s" % (host, tag,
                                  (r.stdout or r.stderr).strip()[:200]))


if __name__ == "__main__":
    main()
