"""Name manager (reference: python/mxnet/name.py — NameManager and the
``with mx.name.Prefix("foo_")`` pattern used throughout the examples).

The active manager is the symbol layer's thread-local auto-namer; these
context managers scope its prefix."""
from __future__ import annotations

from .symbol.symbol import _name_mgr

__all__ = ["NameManager", "Prefix", "current"]


class NameManager(object):
    """Scoped control of automatic symbol naming (reference:
    name.py NameManager). Entering installs this manager's prefix;
    exiting restores the previous one."""

    def __init__(self):
        self._prefix = ""
        self._old = None

    def get(self, name, hint):
        """Resolve a name: explicit names pass through, anonymous
        symbols get ``prefix + hint + counter``."""
        if name is not None:
            return name
        return _name_mgr.get(hint)

    def __enter__(self):
        self._old = _name_mgr.prefix
        _name_mgr.prefix = self._prefix
        return self

    def __exit__(self, *exc):
        _name_mgr.prefix = self._old


class Prefix(NameManager):
    """Prepend ``prefix`` to every auto-generated symbol name inside the
    scope (reference: name.py Prefix)."""

    def __init__(self, prefix):
        super(Prefix, self).__init__()
        self._prefix = prefix


def current():
    """The active (thread-local) auto-namer."""
    return _name_mgr
