"""Reference-binary .params interchange (reference:
src/ndarray/ndarray.cc:1565-1800). Files are hand-built byte-for-byte
per the dmlc serialization layout, covering the uint32-dim (<=1.4) and
int64-dim (>=1.5) TShape eras, the v1 magic, and sparse entries — so
published MXNet checkpoints load directly."""
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd

V1 = 0xF993FAC8
V2 = 0xF993FAC9


def _tuple(dims, dim64):
    fmt = "<I%d%s" % (len(dims), "q" if dim64 else "I")
    return struct.pack(fmt, len(dims), *dims)


def _dense(arr, dim64, magic=V2):
    out = b""
    if magic == V2:
        out += struct.pack("<Ii", V2, 0)
    else:
        out += struct.pack("<I", V1)
    out += _tuple(arr.shape, dim64)
    out += struct.pack("<ii", 1, 0)                  # cpu(0)
    flag = {np.dtype(np.float32): 0, np.dtype(np.float64): 1,
            np.dtype(np.float16): 2, np.dtype(np.uint8): 3,
            np.dtype(np.int32): 4, np.dtype(np.int8): 5,
            np.dtype(np.int64): 6}[arr.dtype]
    out += struct.pack("<i", flag)
    return out + np.ascontiguousarray(arr).tobytes()


def _row_sparse(data, indices, shape, dim64):
    out = struct.pack("<Ii", V2, 1)                  # stype row_sparse
    out += _tuple(data.shape, dim64)                 # storage shape
    out += _tuple(shape, dim64)
    out += struct.pack("<ii", 1, 0)
    out += struct.pack("<i", 0)                      # float32
    out += struct.pack("<i", 6)                      # aux idx int64
    out += _tuple(indices.shape, dim64)
    return out + data.tobytes() + indices.tobytes()


def _file(entries, names):
    out = struct.pack("<QQQ", 0x112, 0, len(entries)) + b"".join(entries)
    out += struct.pack("<Q", len(names))
    for n in names:
        b = n.encode()
        out += struct.pack("<Q", len(b)) + b
    return out


@pytest.mark.parametrize("dim64", [False, True])
def test_load_reference_params(tmp_path, dim64):
    rng = np.random.RandomState(0)
    w = rng.randn(3, 4).astype(np.float32)
    b = rng.randn(4).astype(np.float64)
    i8 = rng.randint(0, 100, (2, 2)).astype(np.int8)
    path = str(tmp_path / "ref.params")
    with open(path, "wb") as f:
        f.write(_file([_dense(w, dim64), _dense(b, dim64),
                       _dense(i8, dim64)],
                      ["arg:fc_weight", "arg:fc_bias", "aux:counts"]))
    out = nd.load(path)
    assert set(out) == {"arg:fc_weight", "arg:fc_bias", "aux:counts"}
    np.testing.assert_array_equal(out["arg:fc_weight"].asnumpy(), w)
    # float64 entries land at f32 precision (JAX default x64-off)
    np.testing.assert_allclose(out["arg:fc_bias"].asnumpy(), b,
                               rtol=1e-6)
    np.testing.assert_array_equal(out["aux:counts"].asnumpy(), i8)


def test_load_v1_and_unkeyed_and_sparse(tmp_path):
    rng = np.random.RandomState(1)
    a = rng.randn(2, 3).astype(np.float32)
    data = rng.randn(2, 5).astype(np.float32)
    idx = np.array([1, 3], np.int64)
    path = str(tmp_path / "mixed.params")
    with open(path, "wb") as f:
        f.write(_file([_dense(a, False, magic=V1),
                       _row_sparse(data, idx, (6, 5), False)], []))
    out = nd.load(path)
    assert isinstance(out, list) and len(out) == 2
    np.testing.assert_array_equal(out[0].asnumpy(), a)
    assert out[1].stype == "row_sparse"
    dense = out[1].todense().asnumpy()
    np.testing.assert_array_equal(dense[1], data[0])
    np.testing.assert_array_equal(dense[3], data[1])
    np.testing.assert_array_equal(dense[0], 0)


def test_save_mxnet_format_round_trip(tmp_path):
    rng = np.random.RandomState(2)
    params = {"arg:w": nd.array(rng.randn(4, 3).astype(np.float32)),
              "aux:m": nd.array(rng.rand(3).astype(np.float32))}
    path = str(tmp_path / "out.params")
    nd.save(path, params, format="mxnet")
    # the file IS the reference layout: re-read with the raw parser
    blob = open(path, "rb").read()
    assert struct.unpack("<Q", blob[:8])[0] == 0x112
    out = nd.load(path)
    for k in params:
        np.testing.assert_array_equal(out[k].asnumpy(),
                                      params[k].asnumpy())


def test_checkpoint_flow_reads_reference_file(tmp_path):
    """model.load_checkpoint consumes a reference-written .params via
    the same nd.load path (arg:/aux: prefixes)."""
    rng = np.random.RandomState(3)
    w = rng.randn(3, 2).astype(np.float32)
    prefix = str(tmp_path / "model")
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=3, no_bias=True,
                                name="fc")
    net.save(prefix + "-symbol.json")
    with open(prefix + "-0007.params", "wb") as f:
        f.write(_file([_dense(w, True)], ["arg:fc_weight"]))
    sym, args, aux = mx.model.load_checkpoint(prefix, 7)
    np.testing.assert_array_equal(args["fc_weight"].asnumpy(), w)
    assert aux == {}


def test_sparse_save_load_round_trip(tmp_path):
    """Sparse checkpoints write true sparse records and round-trip
    (review finding: loadable sparse entries must be re-savable)."""
    from mxnet_tpu.ndarray import sparse
    rng = np.random.RandomState(4)
    d = np.zeros((6, 4), np.float32)
    d[1] = rng.rand(4)
    d[4] = rng.rand(4)
    rsp = mx.nd.cast_storage(mx.nd.array(d), "row_sparse")
    csr = mx.nd.cast_storage(mx.nd.array(d), "csr")
    path = str(tmp_path / "sp.params")
    mx.nd.save(path, {"w_rsp": rsp, "w_csr": csr}, format="mxnet")
    out = mx.nd.load(path)
    assert out["w_rsp"].stype == "row_sparse"
    assert out["w_csr"].stype == "csr"
    np.testing.assert_allclose(out["w_rsp"].todense().asnumpy(), d,
                               rtol=1e-6)
    np.testing.assert_allclose(out["w_csr"].todense().asnumpy(), d,
                               rtol=1e-6)
    # the zip layout densifies but accepts sparse too
    path2 = str(tmp_path / "sp.zip")
    mx.nd.save(path2, {"w": rsp})
    np.testing.assert_allclose(mx.nd.load(path2)["w"].asnumpy(), d,
                               rtol=1e-6)


def test_export_1d_conv_round_trips(tmp_path):
    """Regression: 1-D convolutions export spec-valid attribute lengths
    (strides/dilations/pads were hardcoded 2-D)."""
    data = mx.sym.Variable("data")
    c = mx.sym.Convolution(data, kernel=(3,), num_filter=4, pad=(1,),
                           name="c1d")
    f = mx.sym.FullyConnected(mx.sym.Flatten(c), num_hidden=2, name="fc")
    exe = f.simple_bind(data=(2, 3, 8))
    rng = np.random.RandomState(5)
    for n, a in exe.arg_dict.items():
        if n != "data":
            a[:] = mx.nd.array(rng.randn(*a.shape).astype(np.float32) * .2)
    x = rng.randn(2, 3, 8).astype(np.float32)
    exe.arg_dict["data"][:] = mx.nd.array(x)
    ref = exe.forward(is_train=False)[0].asnumpy()
    from mxnet_tpu.contrib import onnx as mxonnx
    path = str(tmp_path / "c1d.onnx")
    mxonnx.export_model(
        f, {n: a for n, a in exe.arg_dict.items() if n != "data"},
        (2, 3, 8), onnx_file_path=path)
    blob = open(path, "rb").read()
    graph = mxonnx._parse(mxonnx._one(mxonnx._parse(blob), 7))
    node0 = mxonnx._parse(next(iter(mxonnx._all(graph, 1))))
    attrs = mxonnx._decode_attrs(node0)
    assert attrs["kernel_shape"] == [3]
    assert attrs["strides"] == [1] and attrs["pads"] == [1, 1]
    sym2, args2, aux2 = mxonnx.import_model(path)
    exe2 = sym2.simple_bind(data=(2, 3, 8))
    for n, a in args2.items():
        exe2.arg_dict[n][:] = a
    exe2.arg_dict["data"][:] = mx.nd.array(x)
    np.testing.assert_allclose(exe2.forward(is_train=False)[0].asnumpy(),
                               ref, rtol=1e-4, atol=1e-5)


def test_gluon_load_parameters_reads_reference_file(tmp_path):
    """gluon load_parameters flows through the format-aware nd.load, so
    weight files written by reference gluon (binary, plain names) load
    directly."""
    from mxnet_tpu.gluon import nn
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Dense(2))
    net.initialize()
    x = mx.nd.array(np.random.RandomState(6).randn(2, 3)
                    .astype(np.float32))
    net(x)                                    # materialize shapes
    names = list(net._collect_params_with_prefix())
    rng = np.random.RandomState(7)
    weights = {n: rng.randn(*net._collect_params_with_prefix()[n]
                            .shape).astype(np.float32) for n in names}
    path = str(tmp_path / "gluon.params")
    with open(path, "wb") as f:
        f.write(_file([_dense(weights[n], True) for n in names], names))
    net.load_parameters(path)
    for n in names:
        np.testing.assert_array_equal(
            net._collect_params_with_prefix()[n].data().asnumpy(),
            weights[n])


def test_empty_row_sparse_round_trip(tmp_path):
    """ADVICE r4: a zero-stored-rows row_sparse array (storage shape
    (0, D)) must load back — d == 0 dims are legal, not 'implausible'."""
    rsp = mx.nd.cast_storage(mx.nd.zeros((6, 4)), "row_sparse")
    path = str(tmp_path / "empty.params")
    mx.nd.save(path, {"w": rsp}, format="mxnet")
    out = mx.nd.load(path)
    assert out["w"].stype == "row_sparse"
    np.testing.assert_allclose(out["w"].todense().asnumpy(),
                               np.zeros((6, 4), np.float32))
