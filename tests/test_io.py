"""Data iterator tests (reference: tests/python/unittest/test_io.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io


def test_ndarrayiter_basic():
    data = np.arange(100).reshape(25, 4).astype(np.float32)
    label = np.arange(25).astype(np.float32)
    it = io.NDArrayIter(data, label, batch_size=5)
    batches = list(it)
    assert len(batches) == 5
    assert batches[0].data[0].shape == (5, 4)
    assert batches[0].label[0].shape == (5,)
    np.testing.assert_allclose(batches[1].data[0].asnumpy(), data[5:10])
    # second epoch after reset
    it.reset()
    assert len(list(it)) == 5


def test_ndarrayiter_pad():
    data = np.arange(23 * 2).reshape(23, 2).astype(np.float32)
    it = io.NDArrayIter(data, batch_size=5, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 5
    assert batches[-1].pad == 2
    assert batches[-1].data[0].shape == (5, 2)
    # padded tail wraps to the start
    np.testing.assert_allclose(batches[-1].data[0].asnumpy()[3:], data[:2])


def test_ndarrayiter_discard():
    data = np.zeros((23, 2), dtype=np.float32)
    it = io.NDArrayIter(data, batch_size=5, last_batch_handle="discard")
    assert len(list(it)) == 4


def test_ndarrayiter_shuffle_keeps_pairing():
    data = np.arange(40).astype(np.float32).reshape(40, 1)
    label = np.arange(40).astype(np.float32)
    it = io.NDArrayIter(data, label, batch_size=8, shuffle=True)
    for batch in it:
        np.testing.assert_allclose(batch.data[0].asnumpy()[:, 0],
                                   batch.label[0].asnumpy())


def test_ndarrayiter_dict_input():
    it = io.NDArrayIter({"a": np.zeros((10, 2)), "b": np.zeros((10, 3))},
                        batch_size=5)
    names = [d.name for d in it.provide_data]
    assert sorted(names) == ["a", "b"]
    b = next(it)
    assert len(b.data) == 2


def test_provide_data_desc():
    data = np.zeros((10, 3, 4, 4), dtype=np.float32)
    it = io.NDArrayIter(data, batch_size=2)
    desc = it.provide_data[0]
    assert desc.name == "data"
    assert desc.shape == (2, 3, 4, 4)
    assert io.DataDesc.get_batch_axis("NCHW") == 0


def test_resize_iter():
    data = np.zeros((20, 2), dtype=np.float32)
    base = io.NDArrayIter(data, batch_size=5)
    it = io.ResizeIter(base, 7)
    assert len(list(it)) == 7
    it.reset()
    assert len(list(it)) == 7


def test_prefetching_iter():
    data = np.arange(60).reshape(20, 3).astype(np.float32)
    base = io.NDArrayIter(data, batch_size=4)
    it = io.PrefetchingIter(base)
    batches = list(it)
    assert len(batches) == 5
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:4])
    it.reset()
    assert len(list(it)) == 5


def test_csv_iter(tmp_path):
    data = np.random.rand(12, 3).astype(np.float32)
    f = tmp_path / "d.csv"
    np.savetxt(f, data, delimiter=",")
    it = io.CSVIter(data_csv=str(f), data_shape=(3,), batch_size=4)
    batches = list(it)
    assert len(batches) == 3
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:4],
                               rtol=1e-5)


def test_ndarrayiter_roll_over_multi_epoch():
    """roll_over with labels must survive multiple epochs (the cache is
    consumed by both getdata and getlabel)."""
    data = np.arange(10).astype(np.float32).reshape(10, 1)
    label = np.arange(10).astype(np.float32)
    it = io.NDArrayIter(data, label, batch_size=4,
                        last_batch_handle="roll_over")
    for _epoch in range(3):
        total = 0
        for batch in it:
            assert batch.data[0].shape == (4, 1)
            np.testing.assert_allclose(batch.data[0].asnumpy()[:, 0],
                                       batch.label[0].asnumpy())
            total += 4
        it.reset()
        assert total >= 8
