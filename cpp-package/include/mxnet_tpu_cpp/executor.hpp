// C++ Symbol + Executor wrappers over the general C ABI.
// Capability analog of the reference's cpp-package/include/mxnet-cpp/
// symbol.h + executor.h: load a topology from JSON, simple_bind with
// example inputs, forward/backward, reach args/grads/outputs.
#ifndef MXNET_TPU_CPP_EXECUTOR_HPP_
#define MXNET_TPU_CPP_EXECUTOR_HPP_

#include <map>
#include <string>
#include <vector>

#include "mxnet_tpu_cpp/ndarray.hpp"

namespace mxnet_tpu_cpp {

class Symbol {
 public:
  static Symbol FromJSON(const std::string& json) {
    Symbol s;
    Check(MXSymbolCreateFromJSON(json.c_str(), &s.handle_));
    return s;
  }

  static Symbol Variable(const std::string& name) {
    Symbol s;
    Check(MXSymbolCreateVariable(name.c_str(), &s.handle_));
    return s;
  }

  // op node with free inputs; wire them with Compose (the reference's
  // two-phase mxnet-cpp Symbol building)
  static Symbol Atomic(const std::string& op,
                       const std::map<std::string, std::string>& attrs,
                       const std::string& name = "") {
    std::vector<const char*> ks, vs;
    for (const auto& kv : attrs) {
      ks.push_back(kv.first.c_str());
      vs.push_back(kv.second.c_str());
    }
    Symbol s;
    Check(MXSymbolCreateAtomicSymbol(
        op.c_str(), static_cast<uint32_t>(ks.size()), ks.data(),
        vs.data(), name.empty() ? nullptr : name.c_str(), &s.handle_));
    return s;
  }

  void Compose(const std::map<std::string, const Symbol*>& inputs,
               const std::string& name = "") {
    std::vector<const char*> ks;
    std::vector<SymbolHandle> hs;
    for (const auto& kv : inputs) {
      ks.push_back(kv.first.c_str());
      hs.push_back(kv.second->handle());
    }
    Check(MXSymbolCompose(handle_, name.empty() ? nullptr : name.c_str(),
                          static_cast<uint32_t>(ks.size()), ks.data(),
                          hs.data()));
  }

  // keys=NULL: wire args in order into the graph's free variables
  void ComposePositional(const std::vector<const Symbol*>& args,
                         const std::string& name = "") {
    std::vector<SymbolHandle> hs;
    for (const auto* a : args) hs.push_back(a->handle());
    Check(MXSymbolCompose(handle_, name.empty() ? nullptr : name.c_str(),
                          static_cast<uint32_t>(hs.size()), nullptr,
                          hs.data()));
  }

  Symbol(Symbol&& o) noexcept : handle_(o.handle_) { o.handle_ = nullptr; }
  Symbol(const Symbol&) = delete;
  Symbol& operator=(const Symbol&) = delete;

  ~Symbol() {
    if (handle_ != nullptr) MXSymbolFree(handle_);
  }

  std::string ToJSON() const {
    const char* j = nullptr;
    Check(MXSymbolSaveToJSON(handle_, &j));
    return j;
  }

  std::vector<std::string> ListArguments() const {
    uint32_t n = 0;
    const char** names = nullptr;
    Check(MXSymbolListArguments(handle_, &n, &names));
    return std::vector<std::string>(names, names + n);
  }

  SymbolHandle handle() const { return handle_; }

 private:
  Symbol() = default;
  SymbolHandle handle_ = nullptr;
};

class Executor {
 public:
  Executor(const Symbol& sym, const std::vector<std::string>& input_names,
           const std::vector<const NDArray*>& input_examples) {
    std::vector<const char*> ns;
    std::vector<NDArrayHandle> hs;
    for (const auto& n : input_names) ns.push_back(n.c_str());
    for (const auto* a : input_examples) hs.push_back(a->handle());
    Check(MXExecutorSimpleBind(sym.handle(),
                               static_cast<uint32_t>(ns.size()),
                               ns.data(), hs.data(), &handle_));
  }

  Executor(const Executor&) = delete;
  Executor& operator=(const Executor&) = delete;

  ~Executor() {
    if (handle_ != nullptr) MXExecutorFree(handle_);
  }

  void Forward(bool is_train) {
    Check(MXExecutorForward(handle_, is_train ? 1 : 0));
  }

  void Backward() { Check(MXExecutorBackward(handle_)); }

  NDArray Arg(const std::string& name) const {
    NDArrayHandle h = nullptr;
    Check(MXExecutorGetArg(handle_, name.c_str(), &h));
    return NDArray::FromHandle(h);
  }

  NDArray Grad(const std::string& name) const {
    NDArrayHandle h = nullptr;
    Check(MXExecutorGetGrad(handle_, name.c_str(), &h));
    return NDArray::FromHandle(h);
  }

  std::vector<NDArray> Outputs() const {
    uint32_t n = 0;
    NDArrayHandle* hs = nullptr;
    Check(MXExecutorOutputs(handle_, &n, &hs));
    std::vector<NDArray> out;
    out.reserve(n);
    for (uint32_t i = 0; i < n; ++i)
      out.push_back(NDArray::FromHandle(hs[i]));
    return out;
  }

  ExecutorHandle handle() const { return handle_; }

 private:
  ExecutorHandle handle_ = nullptr;
};

}  // namespace mxnet_tpu_cpp

#endif  // MXNET_TPU_CPP_EXECUTOR_HPP_
