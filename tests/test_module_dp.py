"""Data-parallel Module(context=[...]) tests.

The reference's primary multi-GPU pattern is
``Module(sym, context=[mx.gpu(i) for i in range(N)])`` with
DataParallelExecutorGroup slicing the batch (reference:
python/mxnet/module/executor_group.py:143,310-341). Here the same API
shards the batch over a 1-D 'dp' mesh inside one compiled program; these
tests verify the multi-device trajectory matches single-device training
and that an unmappable context list fails loudly instead of silently
using one device.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io
from mxnet_tpu.base import MXNetError
from mxnet_tpu.module import Module


def _mlp_sym():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=32)
    act1 = mx.sym.Activation(fc1, name="relu1", act_type="relu")
    fc2 = mx.sym.FullyConnected(act1, name="fc2", num_hidden=10)
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _toy_data(n=256, seed=3):
    rng = np.random.RandomState(seed)
    centers = rng.randn(10, 64).astype(np.float32) * 1.5
    labels = rng.randint(0, 10, size=n)
    data = (centers[labels] + rng.randn(n, 64)).astype(np.float32)
    return data, labels.astype(np.float32)


def _train_losses(contexts, steps=8, batch=32):
    """Train with fixed init/data; return the per-step CE losses."""
    data, labels = _toy_data()
    mod = Module(_mlp_sym(), context=contexts)
    mod.bind(data_shapes=[("data", (batch, 64))],
             label_shapes=[("softmax_label", (batch,))])
    mod.init_params(mx.init.Xavier(magnitude=2.0))
    # deterministic init: overwrite with a seeded dense init so both runs
    # start from identical weights
    rng = np.random.RandomState(11)
    args = {n: mx.nd.array(rng.randn(*a.shape).astype(np.float32) * 0.05)
            for n, a in mod._exec.arg_dict.items()
            if n not in ("data", "softmax_label")}
    mod.set_params(args, {}, allow_missing=True, force_init=True)
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    losses = []
    for i in range(steps):
        lo = (i * batch) % (len(data) - batch)
        db = io.DataBatch(data=[mx.nd.array(data[lo:lo + batch])],
                          label=[mx.nd.array(labels[lo:lo + batch])])
        mod.forward(db, is_train=True)
        probs = mod.get_outputs()[0].asnumpy()
        li = labels[lo:lo + batch].astype(int)
        losses.append(float(-np.mean(
            np.log(np.maximum(probs[np.arange(batch), li], 1e-10)))))
        mod.backward()
        mod.update()
    return losses


def test_module_multi_context_matches_single_device():
    """4-device DP trajectory == 1-device trajectory (the reference's
    multi_lenet.py-style consistency check)."""
    single = _train_losses(mx.cpu(0))
    multi = _train_losses([mx.cpu(i) for i in range(4)])
    np.testing.assert_allclose(multi, single, rtol=2e-4, atol=2e-5)
    assert single[-1] < single[0] * 0.7, "training did not reduce loss"


def test_module_multi_context_actually_shards():
    """The bound executor must hold a real 4-way mesh — not context[0]."""
    mod = Module(_mlp_sym(), context=[mx.cpu(i) for i in range(4)])
    mod.bind(data_shapes=[("data", (32, 64))],
             label_shapes=[("softmax_label", (32,))])
    mod.init_params()
    assert mod._exec._dp_mesh is not None
    assert mod._exec._dp_mesh.shape["dp"] == 4
    batch = io.DataBatch(data=[mx.nd.zeros((32, 64))],
                         label=[mx.nd.zeros((32,))])
    mod.forward(batch, is_train=True)
    data_arr = mod._exec.arg_dict["data"]._data
    assert len(data_arr.sharding.device_set) == 4


def test_module_duplicate_contexts_raise():
    """A context list that folds onto one device must fail loudly
    (round-2 verdict: silent single-device training is unacceptable)."""
    import jax
    n = len(jax.devices())
    with pytest.raises(MXNetError, match="distinct devices"):
        mod = Module(_mlp_sym(), context=[mx.cpu(0), mx.cpu(n)])
        mod.bind(data_shapes=[("data", (8, 64))],
                 label_shapes=[("softmax_label", (8,))])


def test_module_dp_indivisible_batch_raises():
    mod = Module(_mlp_sym(), context=[mx.cpu(i) for i in range(3)])
    with pytest.raises(MXNetError, match="divisible"):
        mod.bind(data_shapes=[("data", (32, 64))],
                 label_shapes=[("softmax_label", (32,))])


def test_module_dp_bf16_convergence():
    """Mixed-precision end to end through the Module DP path
    (VERDICT r4 weak #6; reference tests/python/train/test_dtype.py):
    bf16 batches, fp32 master weights via multi_precision, two-device
    data parallelism, full accuracy on the separable problem."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import io
    from mxnet_tpu.module import Module

    rng = np.random.RandomState(7)
    centers = rng.randn(10, 64).astype(np.float32) * 1.5
    labels = rng.randint(0, 10, size=500)
    d32 = (centers[labels] + rng.randn(500, 64)).astype(np.float32)
    arr = mx.nd.array(d32).astype("bfloat16")
    assert arr.dtype == "bfloat16" or str(arr.dtype) == "bfloat16"
    it = io.NDArrayIter(arr, labels.astype(np.float32), batch_size=50,
                        shuffle=True)
    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=10,
                              name="fc"), name="softmax")
    mod = Module(sym, context=[mx.cpu(0), mx.cpu(1)])
    mod.fit(it, num_epoch=4, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2,
                              "multi_precision": True})
    score = mod.score(io.NDArrayIter(arr, labels.astype(np.float32),
                                     batch_size=50), "acc")
    assert score[0][1] > 0.95, score


def test_executor_manager_group_matches_single_device():
    """DataParallelExecutorManager (reference executor_manager.py): two
    per-device executors over sliced batches; summed per-device grads
    equal the single-executor grads on the full batch."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import io
    from mxnet_tpu.executor_manager import DataParallelExecutorManager

    rng = np.random.RandomState(0)
    data = rng.randn(8, 5).astype(np.float32)
    labels = rng.randint(0, 3, size=8).astype(np.float32)
    it = io.NDArrayIter(data, labels, batch_size=8)

    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=3,
                              name="fc"), name="softmax")
    mgr = DataParallelExecutorManager(sym, [mx.cpu(0), mx.cpu(1)], it)
    w = rng.randn(3, 5).astype(np.float32)
    b = np.zeros(3, np.float32)
    mgr.set_params({"fc_weight": mx.nd.array(w),
                    "fc_bias": mx.nd.array(b)}, {})
    batch = next(it)
    mgr.load_data_batch(batch)
    mgr.forward(is_train=True)
    mgr.backward()
    metric = mx.metric.Accuracy()
    mgr.update_metric(metric, batch.label)
    assert 0.0 <= metric.get()[1] <= 1.0

    # reference single-device executor on the full batch
    exe = sym.simple_bind(mx.cpu(0),
                          grad_req={"fc_weight": "write",
                                    "fc_bias": "write", "data": "null",
                                    "softmax_label": "null"},
                          data=(8, 5), softmax_label=(8,))
    exe.arg_dict["fc_weight"][:] = mx.nd.array(w)
    exe.arg_dict["fc_bias"][:] = mx.nd.array(b)
    exe.arg_dict["data"][:] = batch.data[0]
    exe.arg_dict["softmax_label"][:] = batch.label[0]
    exe.forward(is_train=True)
    exe.backward()
    for pname, parts in zip(mgr.execgrp.param_names, mgr.grad_arrays):
        # SoftmaxOutput gradients SUM over the batch (reference
        # normalization='null' default), so per-device parts sum to the
        # full-batch gradient
        summed = sum(p.asnumpy() for p in parts)
        np.testing.assert_allclose(summed,
                                   exe.grad_dict[pname].asnumpy(),
                                   rtol=1e-4, atol=1e-5)


def test_executor_manager_bucketed_updates_propagate():
    """Regression (round-5 review): with sym_gen bucketing, grad_arrays
    must come from the group that ran backward, and parameter updates
    must carry across bucket switches."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import io
    from mxnet_tpu.executor_manager import DataParallelExecutorManager

    def sym_gen(seq_len):
        d = mx.sym.var("data")
        pooled = mx.sym.mean(d, axis=1, keepdims=True)
        return mx.sym.SoftmaxOutput(
            mx.sym.FullyConnected(pooled, num_hidden=2, name="fc"),
            name="softmax")

    def make_batch(key):
        return io.DataBatch(
            data=[mx.nd.ones((4, key))], label=[mx.nd.zeros((4,))],
            bucket_key=key,
            provide_data=[io.DataDesc("data", (4, key))],
            provide_label=[io.DataDesc("softmax_label", (4,))])

    mgr = DataParallelExecutorManager(
        sym_gen(8), [mx.cpu(0), mx.cpu(1)], make_batch(8),
        sym_gen=sym_gen)
    mgr.set_params({"fc_weight": mx.nd.zeros((2, 1)),
                    "fc_bias": mx.nd.zeros((2,))}, {})
    w_before = None
    for key in [8, 16, 8]:
        mgr.load_data_batch(make_batch(key))
        mgr.forward(is_train=True)
        mgr.backward()
        # grads from the group that RAN (non-zero for the wrong class)
        gsum = sum(float(np.abs(g.asnumpy()).sum())
                   for parts in mgr.grad_arrays for g in parts)
        assert gsum > 0, "zero grads from bucket group (key=%d)" % key
        # sgd step on the current group's params
        for parts, gparts in zip(mgr.param_arrays, mgr.grad_arrays):
            for p, g in zip(parts, gparts):
                p[:] = p - 0.1 * g
        w_now = mgr.param_arrays[0][0].asnumpy().copy()
        if w_before is not None:
            assert not np.allclose(w_now, w_before), \
                "updates lost across bucket switch"
        w_before = w_now
