"""Ulysses (all-to-all) sequence/context parallelism.

The second of the two first-class long-context strategies (the other is
``ring_attention``). The reference framework predates both — its
long-sequence story is bucketing + fused RNNs (SURVEY.md §5
"long-context"); this module is the TPU-native capability replacement,
following the DeepSpeed-Ulysses communication pattern:

- Activations arrive sequence-sharded over mesh axis ``sp``
  (each device holds (b, h, S/n, d)).
- One ``lax.all_to_all`` re-shards heads<->sequence: every device ends
  up with the FULL sequence for h/n of the heads.
- Attention for those heads runs entirely locally (the Pallas flash
  kernel or plain XLA einsum — exact global causal masking, no online
  merge needed).
- A second all_to_all restores sequence sharding.

Communication: 2 all-to-alls of the Q/K/V/O activations per attention
call — O(b·s·d·(n-1)/n²) bytes per device per all-to-all, riding ICI.
Versus the ring: fewer, larger collectives and a simpler local kernel,
but requires num_heads % n == 0 (the ring has no head constraint and
overlaps transfer with compute). Both shard the sequence axis, so
either drops into the same ``sp`` mesh axis of a 5-axis layout.

Differentiable end-to-end: ``lax.all_to_all`` is linear (its transpose
is the reverse all-to-all) and the local attention is the flash kernel
custom-vjp or pure jnp.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from ._compat import shard_map

__all__ = ["ulysses_attention", "ulysses_self_attention"]


def _local_full_attention(q, k, v, causal, sm_scale, impl, interpret):
    """Full-sequence attention on local heads (runs inside shard_map)."""
    if impl == "auto":
        impl = "flash"
    if impl == "flash":
        from ..ops.pallas.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                               interpret=interpret)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        mask = jnp.arange(sk)[None, :] <= jnp.arange(sq)[:, None]
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _ulysses_local(q, k, v, axis_name, causal, sm_scale, impl,
                   interpret=None):
    """Per-shard body: heads<->sequence all-to-all sandwich.

    In: (b, h, S/n, d) sequence-sharded. all_to_all with
    split_axis=heads, concat_axis=seq yields (b, h/n, S, d); after local
    attention the inverse all_to_all restores (b, h, S/n, d).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # split h across the axis, gather the full sequence
    qh = jax.lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2,
                            tiled=True)
    kh = jax.lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2,
                            tiled=True)
    vh = jax.lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2,
                            tiled=True)
    o = _local_full_attention(qh, kh, vh, causal, sm_scale, impl,
                              interpret)
    # split the sequence back, gather this shard's full head set
    return jax.lax.all_to_all(o, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)


def ulysses_attention(q, k, v, mesh=None, axis="sp", causal=False,
                      sm_scale=None, impl="auto", interpret=None):
    """All-to-all sequence-parallel attention over mesh axis ``axis``.

    q, k, v : (batch, heads, seq, head_dim); ``seq`` divisible by the
        axis size and ``heads`` divisible by the axis size (the Ulysses
        constraint — use :func:`ring_attention` when heads < devices).
    impl : "flash" (Pallas kernel), "einsum", or "auto".
    """
    from .mesh import current_mesh
    mesh = mesh or current_mesh()
    if mesh is None:
        raise ValueError("ulysses_attention needs a Mesh "
                         "(parallel.make_mesh)")
    n = mesh.shape[axis]
    if q.shape[1] % n:
        raise ValueError(
            "ulysses_attention: num_heads=%d not divisible by mesh axis "
            "%r size %d (use ring_attention for few-head models)"
            % (q.shape[1], axis, n))
    if q.shape[2] % n:
        raise ValueError("ulysses_attention: seq=%d not divisible by "
                         "mesh axis %r size %d" % (q.shape[2], axis, n))
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    spec = P(None, None, axis, None)
    fn = shard_map(
        functools.partial(_ulysses_local, axis_name=axis,
                          causal=bool(causal), sm_scale=float(sm_scale),
                          impl=impl, interpret=bool(interpret)),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def ulysses_self_attention(x, w_qkv, w_out, num_heads, mesh=None,
                           axis="sp", causal=False, impl="auto"):
    """Fused all-to-all sequence-parallel self-attention: x (b, seq, dm).

    Projections run on sequence-sharded activations (local matmuls);
    only the two all-to-alls move data between devices — the drop-in
    alternative to :func:`ring_self_attention`.
    """
    b, s, dm = x.shape
    qkv = jnp.einsum("bsd,de->bse", x, w_qkv)
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):
        return t.reshape(b, s, num_heads, dm // num_heads).transpose(
            0, 2, 1, 3)

    o = ulysses_attention(heads(q), heads(k), heads(v), mesh=mesh,
                          axis=axis, causal=causal, impl=impl)
    o = o.transpose(0, 2, 1, 3).reshape(b, s, dm)
    return jnp.einsum("bsd,de->bse", o, w_out)
