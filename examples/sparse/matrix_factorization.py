"""Matrix factorization with sparse embedding gradients.

Capability analog of the reference's sparse MF example (reference:
example/sparse/matrix_factorization/train.py — MovieLens ratings, two
SparseEmbedding tables, row_sparse grads, lazy Adam). Each step's
backward touches O(batch) embedding rows via ``sparse.embedding``; the
lazy Adam kernels update exactly those rows, so a 1M x 64 table costs
the same per step as a 1k x 64 one.

Run: python examples/sparse/matrix_factorization.py
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx                                     # noqa: E402
from mxnet_tpu import autograd, nd, optimizer as opt       # noqa: E402
from mxnet_tpu.ndarray import sparse                       # noqa: E402


def synthetic_ratings(num_users, num_items, n, rank=8, seed=0):
    rng = np.random.RandomState(seed)
    u_f = rng.randn(num_users, rank) / np.sqrt(rank)
    i_f = rng.randn(num_items, rank) / np.sqrt(rank)
    users = rng.randint(0, num_users, n)
    items = rng.randint(0, num_items, n)
    ratings = np.sum(u_f[users] * i_f[items], axis=1)
    return users.astype(np.int32), items.astype(np.int32), \
        ratings.astype(np.float32)


def train(num_users=1000, num_items=2000, factor_size=16, n=4096,
          batch_size=256, epochs=3, lr=0.02, log=print):
    rng = np.random.RandomState(1)
    users, items, ratings = synthetic_ratings(num_users, num_items, n)
    user_w = nd.array(rng.randn(num_users, factor_size).astype("float32")
                      * 0.05)
    item_w = nd.array(rng.randn(num_items, factor_size).astype("float32")
                      * 0.05)
    user_w.attach_grad()
    item_w.attach_grad()
    optim = opt.create("adam", learning_rate=lr)
    st_u = optim.create_state(0, user_w)
    st_i = optim.create_state(1, item_w)

    losses = []
    for epoch in range(epochs):
        perm = rng.permutation(n)
        total, count = 0.0, 0
        for lo in range(0, n - batch_size + 1, batch_size):
            sel = perm[lo:lo + batch_size]
            u = nd.array(users[sel])
            i = nd.array(items[sel])
            r = nd.array(ratings[sel])
            with autograd.record():
                ue = sparse.embedding(u, user_w)           # (B, F)
                ie = sparse.embedding(i, item_w)
                pred = nd.sum(ue * ie, axis=1)
                loss = nd.mean((pred - r) ** 2)
            loss.backward()
            optim.update(0, user_w, user_w.grad, st_u)     # lazy Adam
            optim.update(1, item_w, item_w.grad, st_i)
            total += float(loss.asscalar())
            count += 1
        losses.append(total / max(count, 1))
        log("epoch %d: mse %.4f" % (epoch, losses[-1]))
    return losses


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-users", type=int, default=1000)
    ap.add_argument("--num-items", type=int, default=2000)
    ap.add_argument("--factor-size", type=int, default=16)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--num-epoch", type=int, default=3)
    ap.add_argument("--lr", type=float, default=0.02)
    args = ap.parse_args()
    losses = train(args.num_users, args.num_items, args.factor_size,
                   batch_size=args.batch_size, epochs=args.num_epoch,
                   lr=args.lr)
    assert losses[-1] < losses[0], "loss did not improve"


if __name__ == "__main__":
    main()
