"""Shard-aware checkpointing + failure detection.

Reference capability: model.py save/load_checkpoint + ps-lite liveness
(kvstore.h:353), extended to sharded training state (SURVEY.md §5 says
"design checkpoint/restore to be shard-aware").
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.checkpoint import ShardedCheckpointManager
from mxnet_tpu.parallel.mesh import make_mesh


def _sharded_state(mesh):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    w = jax.device_put(jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
                       NamedSharding(mesh, P("dp", None)))
    b = jax.device_put(jnp.ones((8,), jnp.float32),
                       NamedSharding(mesh, P()))
    return {"w": w, "b": b, "step_scale": jnp.float32(0.5)}


def test_sharded_roundtrip_preserves_sharding(tmp_path):
    import jax
    mesh = make_mesh((4,), axis_names=("dp",))
    state = _sharded_state(mesh)
    mgr = ShardedCheckpointManager(str(tmp_path))
    mgr.save(3, state)
    assert mgr.latest_step() == 3
    like = _sharded_state(mesh)
    restored = mgr.restore(like=like)
    mgr.close()
    np.testing.assert_allclose(np.asarray(restored["w"]),
                               np.asarray(state["w"]))
    assert restored["w"].sharding == state["w"].sharding
    assert restored["b"].sharding == state["b"].sharding


def test_max_to_keep_and_resume(tmp_path):
    mesh = make_mesh((2,), axis_names=("dp",))
    state = _sharded_state(mesh)
    mgr = ShardedCheckpointManager(str(tmp_path), max_to_keep=2)
    for step in (1, 2, 3):
        import jax
        state = {**state, "b": state["b"] + 1.0}
        mgr.save(step, state)
    steps = mgr.all_steps()
    assert 3 in steps and len(steps) <= 2
    restored = mgr.restore(like=state)
    mgr.close()
    np.testing.assert_allclose(np.asarray(restored["b"]),
                               np.asarray(state["b"]))


def test_restore_with_no_checkpoints_raises(tmp_path):
    """restore()/restore_latest_valid() on an empty directory raise a
    clear MXNetError instead of an opaque orbax failure."""
    from mxnet_tpu.base import MXNetError
    mgr = ShardedCheckpointManager(str(tmp_path))
    with pytest.raises(MXNetError, match="no checkpoint found"):
        mgr.restore()
    with pytest.raises(MXNetError, match="no checkpoint found"):
        mgr.restore_latest_valid()
    mgr.close()


def test_max_to_keep_prunes_old_steps(tmp_path):
    mgr = ShardedCheckpointManager(str(tmp_path), max_to_keep=2)
    state = {"w": np.ones((4,), np.float32)}
    import jax.numpy as jnp
    for step in (1, 2, 3, 4):
        mgr.save(step, {"w": jnp.full((4,), float(step))})
    steps = mgr.all_steps()
    mgr.close()
    assert steps == [3, 4]


def test_restore_latest_valid_falls_back_over_corrupt_step(tmp_path):
    """Fallback across a corrupted latest step: every file of the
    newest step is truncated; restore_latest_valid returns the previous
    good step with its values intact."""
    import os
    import jax.numpy as jnp
    from mxnet_tpu import telemetry as tm
    mgr = ShardedCheckpointManager(str(tmp_path))
    like = {"w": jnp.zeros((4, 4)), "b": jnp.zeros((4,))}
    mgr.save(1, {"w": jnp.full((4, 4), 1.0), "b": jnp.full((4,), 1.0)})
    mgr.save(2, {"w": jnp.full((4, 4), 2.0), "b": jnp.full((4,), 2.0)})
    for root, _dirs, files in os.walk(str(tmp_path / "2")):
        for fn in files:
            with open(os.path.join(root, fn), "r+b") as f:
                f.truncate(1)
    snap0 = tm.snapshot()
    step, restored = mgr.restore_latest_valid(like=like)
    snap1 = tm.snapshot()
    mgr.close()
    assert step == 1
    np.testing.assert_allclose(np.asarray(restored["w"]),
                               np.full((4, 4), 1.0))
    assert snap1["ckpt_corrupt"] - snap0["ckpt_corrupt"] >= 1
    assert snap1["ckpt_fallbacks"] - snap0["ckpt_fallbacks"] == 1


def test_checkpoint_accepts_ndarrays(tmp_path):
    mgr = ShardedCheckpointManager(str(tmp_path))
    state = {"w": mx.nd.array(np.ones((3, 3), np.float32))}
    mgr.save(0, state)
    out = mgr.restore(0)
    mgr.close()
    np.testing.assert_allclose(np.asarray(out["w"]), np.ones((3, 3)))


def test_dead_node_detection():
    import socket
    import time
    from mxnet_tpu.kvstore_server import KVStoreServer, send_msg, recv_msg
    server = KVStoreServer(port=0, num_workers=2, sync_mode=True)
    server.start_background()
    s = socket.socket()
    s.connect(("127.0.0.1", server.port))
    send_msg(s, ("HELLO", None, 0))
    recv_msg(s)
    # within the grace window nothing reads as dead
    send_msg(s, ("DEAD_NODES", None, 30.0))
    st, dead = recv_msg(s)[:2]
    assert st == "OK" and dead == []
    # after the window: rank 0 heartbeats, rank 1 (never connected) dies
    time.sleep(0.3)
    send_msg(s, ("HELLO", None, 0))
    recv_msg(s)
    send_msg(s, ("DEAD_NODES", None, 0.2))
    st, dead = recv_msg(s)[:2]
    server.stop()
    assert st == "OK"
    assert dead == [1]


def test_transformer_5axis_checkpoint_resume(tmp_path):
    """Checkpoint/resume of the 5-axis transformer: sharded params save
    through the Orbax path and restore with identical values + step
    continuity (SURVEY §5 checkpoint/resume on the flagship model)."""
    import jax
    import numpy as np
    from mxnet_tpu import checkpoint as ckpt
    from mxnet_tpu.parallel.mesh import make_mesh
    from mxnet_tpu.parallel.transformer import (
        TransformerConfig, init_transformer_params,
        make_transformer_train_step)

    cfg = TransformerConfig(vocab_size=32, d_model=16, n_heads=2,
                            n_layers=2, d_ff=32, max_len=16,
                            pos_type="rope")
    mesh = make_mesh((2, 1, 2, 1, 1),
                     axis_names=("dp", "sp", "tp", "pp", "ep"))
    params, _ = init_transformer_params(cfg, mesh, seed=0)
    step = make_transformer_train_step(cfg, mesh, lr=0.1)
    rng = np.random.RandomState(0)
    tok = rng.randint(0, 32, (4, 8)).astype(np.int32)
    tgt = rng.randint(0, 32, (4, 8)).astype(np.int32)
    params, _ = step(params, tok, tgt)

    mgr = ckpt.ShardedCheckpointManager(str(tmp_path / "ck"))
    mgr.save(3, params)

    params2, _ = init_transformer_params(cfg, mesh, seed=99)
    restored = mgr.restore(like=params2)
    assert mgr.latest_step() == 3
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    # training continues from the restored state
    restored, loss = step(restored, tok, tgt)
    assert np.isfinite(float(loss))
