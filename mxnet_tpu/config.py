"""Runtime configuration: the MXNET_* environment-variable tier.

Reference: the reference reads ~46 documented env vars via dmlc::GetEnv
at point of use (docs/faq/env_var.md) on top of per-object dmlc
Parameter structs. Here the same tier is a typed registry: every knob
the framework consults is declared once with type/default/doc, read
through :func:`get`, and enumerable for docs (``python -m
mxnet_tpu.config`` prints the table).
"""
from __future__ import annotations

import os

__all__ = ["get", "describe", "VARS"]

# name -> (type, default, doc)
VARS = {
    "MXNET_TPU_PLATFORM": (str, "", "Force the JAX platform (cpu/tpu) "
                           "before backend init — the reliable override "
                           "when a site hook already imported jax."),
    "MXNET_ENGINE_TYPE": (str, "ThreadedEnginePerDevice",
                          "NaiveEngine = serialize after every op "
                          "(degrade-to-serial debug mode, reference: "
                          "docs/faq/env_var.md:77)."),
    "MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN": (int, 15,
                                            "Engine bulking knob (API "
                                            "parity; XLA fusion subsumes "
                                            "it)."),
    "MXNET_TPU_PS_URI": (str, "", "Parameter-server host for dist_* "
                         "KVStore types (DCN tier)."),
    "MXNET_TPU_PS_PORT": (int, 9090, "Parameter-server port."),
    "MXNET_TPU_PS_BIND": (str, "127.0.0.1", "Server bind address; "
                          "non-loopback requires MXNET_TPU_PS_TOKEN."),
    "MXNET_TPU_PS_TOKEN": (str, "", "Shared auth token for the PS wire "
                           "protocol."),
    "MXNET_TPU_PS_MODE": (str, "sync", "sync = aggregate-then-update "
                          "BSP; async = per-push updates."),
    "MXNET_TPU_NUM_WORKERS": (int, 1, "World size in PS mode."),
    "MXNET_TPU_RANK": (int, 0, "This worker's rank in PS mode."),
    "MXNET_TPU_ROLE": (str, "worker", "PS-mode process role (worker/"
                       "server/scheduler) for the launch.py tooling "
                       "path."),
    "MXNET_TPU_BENCH_DIR": (str, "", "Override for the benchmark "
                            "results directory (default .bench/ under "
                            "the repo root)."),
    "MXNET_DIST_COORDINATOR": (str, "", "host:port of process 0's "
                               "jax.distributed coordinator for "
                               "dist_tpu_sync multi-host training "
                               "(dist_runtime.py). Empty = standard "
                               "cluster autodetection (Cloud TPU / "
                               "SLURM / MPI), or single-process."),
    "MXNET_DIST_NUM_PROCESSES": (int, 1, "World size for the explicit "
                                 "MXNET_DIST_COORDINATOR route."),
    "MXNET_DIST_PROCESS_ID": (int, 0, "This process's rank for the "
                              "explicit MXNET_DIST_COORDINATOR "
                              "route."),
    "MXNET_DIST_DEAD_S": (float, 10.0,
                          "Elastic membership: a dist_tpu_sync rank "
                          "whose control-plane heartbeat is older than "
                          "this is declared lost and a rescale begins "
                          "(elastic.py)."),
    "MXNET_STEP_TIMEOUT_S": (float, 120.0,
                             "Elastic membership: a fused train step "
                             "that has not completed after this long "
                             "is treated as a wedged collective (a "
                             "rank parked in a dead all-reduce) and "
                             "routed to the same rescale path as a "
                             "detected death. 0 disables the "
                             "watchdog."),
    "MXNET_ELASTIC_DIR": (str, "",
                          "Shared directory for the elastic control "
                          "plane (heartbeats, rescale votes/plans, "
                          "join requests). Setting it on a "
                          "dist_tpu_sync fit enables checkpoint-free "
                          "elastic rescale on membership change; "
                          "empty = fail-as-a-unit (PR 4 supervisor "
                          "relaunch)."),
    "MXNET_ELASTIC_HOST": (str, "",
                           "Host this rank advertises in its elastic "
                           "heartbeats (peers dial it when this rank "
                           "becomes the rescale coordinator). Empty = "
                           "127.0.0.1, the single-machine/chaos-test "
                           "default."),
    "MXNET_ELASTIC_HB_S": (float, 1.0,
                           "Elastic membership heartbeat period "
                           "(control-plane file rewrite interval); "
                           "liveness window is MXNET_DIST_DEAD_S."),
    "MXNET_ELASTIC_JOIN": (int, 0,
                           "Set to 1 on a relaunched trainer to enter "
                           "fit in JOIN mode: request admission from "
                           "the running elastic world and adopt its "
                           "plan instead of initializing a new "
                           "cluster (the ProcessSupervisor relaunch "
                           "hook sets this)."),
    "MXNET_BENCH_TUNNEL_RETRIES": (int, 5,
                                   "Bench driver: accelerator-init "
                                   "probe attempts before the live "
                                   "round is abandoned to banked "
                                   "results (the BENCH_r02/r04 flaky "
                                   "device tunnel)."),
    "MXNET_BENCH_TUNNEL_BACKOFF_S": (float, 2.0,
                                     "Bench driver: base of the "
                                     "jittered exponential backoff "
                                     "between tunnel probe retries."),
    "MXNET_KVSTORE_BIGARRAY_BOUND": (int, 1000000,
                                     "Arrays above this size may be "
                                     "sharded across servers "
                                     "(reference: env_var.md:102)."),
    "MXNET_ENFORCE_DETERMINISM": (bool, False,
                                  "Prefer deterministic reductions "
                                  "(maps to XLA deterministic flags)."),
    "MXNET_PROFILER_AUTOSTART": (bool, False,
                                 "Start the profiler at import."),
    "MXNET_TEST_SEED": (int, 0, "RNG seed for the test harness "
                        "(tools/flakiness_checker.py rotates it per "
                        "trial; reference: docs/faq/env_var.md test "
                        "seeding)."),
    "MXNET_UPDATE_BUFFER_DONATION": (bool, True,
                                     "Donate weight/state buffers in "
                                     "optimizer update kernels (XLA "
                                     "input->output aliasing = true "
                                     "in-place updates, no double-"
                                     "buffering)."),
    "MXNET_FUSED_STEP": (bool, True,
                         "Compile forward+backward+optimizer update into "
                         "ONE donated XLA program per train step "
                         "(Executor.train_step; Module/Gluon Trainer "
                         "local-update paths). 0 restores the separate "
                         "forward/vjp programs plus per-parameter update "
                         "dispatches."),
    "MXNET_PALLAS_FUSED_UPDATE": (bool, True,
                                  "Route SGD-momentum/Adam fused update "
                                  "rules through the Pallas "
                                  "ops/pallas/fused_update.py kernels "
                                  "(Mosaic on TPU; off-TPU the kernels "
                                  "dispatch to their bitwise lax twins, "
                                  "so 0 vs 1 is a no-op on CPU). 0 pins "
                                  "the plain lax rules everywhere."),
    "MXNET_INT8_CONV_IM2COL": (bool, False,
                               "Force _contrib_quantized_conv_int8 "
                               "through the im2col + Pallas int8-matmul "
                               "route off-TPU too (on TPU it is the "
                               "default). The lax conv path stays the "
                               "bitwise acceptance twin; int32 "
                               "accumulation makes the two routes "
                               "bitwise-identical."),
    "MXNET_TELEMETRY": (bool, True,
                        "Always-on runtime metrics (telemetry.py): op "
                        "dispatch, jit-cache, HBM, kvstore, io "
                        "instruments. 0 removes the hot-path hooks "
                        "entirely; telemetry.enable() flips at runtime."),
    "MXNET_SERVE_MAX_BATCH": (int, 8,
                              "Largest serving batch bucket "
                              "(serve.InferenceEngine). Buckets default "
                              "to the power-of-two ladder 1..max; the "
                              "jit cache holds at most len(buckets) "
                              "forward programs."),
    "MXNET_SERVE_BUCKETS": (str, "", "Explicit serving batch buckets as "
                            "a comma list (e.g. '1,4,16'); empty = "
                            "power-of-two ladder up to "
                            "MXNET_SERVE_MAX_BATCH."),
    "MXNET_SERVE_QUEUE_DEPTH": (int, 64,
                                "Serve admission-control bound: requests "
                                "beyond this many queued are rejected "
                                "immediately (HTTP 503), never queued "
                                "into unbounded latency."),
    "MXNET_SERVE_BATCH_WAIT_MS": (int, 2,
                                  "How long the micro-batcher holds the "
                                  "first queued request open for "
                                  "coalescing (higher = bigger batches, "
                                  "more latency floor)."),
    "MXNET_SERVE_DEADLINE_MS": (int, 2000,
                                "Default per-request serving deadline; "
                                "expired requests fail with HTTP 504 "
                                "before wasting a chip dispatch. "
                                "0 disables."),
    "MXNET_SERVE_WORKERS": (int, 1,
                            "Serve worker threads pulling batches off "
                            "the queue. >1 overlaps host pad/unpad and "
                            "JSON work with device compute (per-bucket "
                            "executors are lock-guarded)."),
    "MXNET_SERVE_WORKER_RESTARTS": (int, 16,
                                    "Restart budget for crashed serve "
                                    "worker threads (shared across the "
                                    "crew, counted in serving/"
                                    "worker_restarts_total). Past it a "
                                    "crashed worker stays down; with no "
                                    "worker alive /healthz degrades to "
                                    "not-ready."),
    "MXNET_SERVE_SHADOW_FRACTION": (float, 0.0,
                                    "Default fraction of live requests "
                                    "ModelRegistry.enable_shadow mirrors "
                                    "to the shadow (quantized) engine "
                                    "for drift measurement "
                                    "(quantize/shadow_drift). Mirrors "
                                    "run on a side thread and never "
                                    "delay or fail primary requests."),
    "MXNET_FLEET_MIN_REPLICAS": (int, 1,
                                 "Fleet tier lower bound: the autoscaler "
                                 "never retires below this many live "
                                 "replicas (serve.fleet)."),
    "MXNET_FLEET_MAX_REPLICAS": (int, 4,
                                 "Fleet tier upper bound: the autoscaler "
                                 "never spawns past this many replicas."),
    "MXNET_FLEET_PREFIX_TOKENS": (int, 16,
                                  "Prompt-head length the router hashes "
                                  "for /generate prefix affinity: "
                                  "requests sharing their first N "
                                  "tokens pin to one replica's KV/"
                                  "prefix-cache locality domain."),
    "MXNET_FLEET_AFFINITY_SLACK": (int, 4,
                                   "Affinity yields to load: when the "
                                   "pinned replica carries this many "
                                   "more outstanding requests than the "
                                   "least-loaded one, the router "
                                   "breaks affinity for the request "
                                   "(router/affinity_yields_total)."),
    "MXNET_FLEET_FORWARD_RETRIES": (int, 2,
                                    "Router forward retries across "
                                    "OTHER replicas after a connection "
                                    "failure ejects the picked one "
                                    "(only before any response byte "
                                    "reached the client)."),
    "MXNET_FLEET_SCALE_UP_S": (float, 10.0,
                               "Autoscaler hold window: the hot signal "
                               "(replica SLO burn on /alerts, or queue "
                               "depth past MXNET_FLEET_QUEUE_UP) must "
                               "be sustained this long before a "
                               "scale-up."),
    "MXNET_FLEET_SCALE_DOWN_S": (float, 30.0,
                                 "Autoscaler hold window: fleet-wide "
                                 "slack (no burn, queues under "
                                 "MXNET_FLEET_QUEUE_DOWN) must be "
                                 "sustained this long before a "
                                 "scale-down (hysteresis against "
                                 "flapping; > MXNET_FLEET_SCALE_UP_S "
                                 "by design)."),
    "MXNET_FLEET_COOLDOWN_S": (float, 15.0,
                               "Minimum wall between autoscaler "
                               "actions — a fresh replica gets to "
                               "absorb load before the next verdict."),
    "MXNET_FLEET_INTERVAL_S": (float, 1.0,
                               "Autoscaler control-loop tick: how often "
                               "replica /alerts + queue signals are "
                               "polled."),
    "MXNET_FLEET_QUEUE_UP": (float, 4.0,
                             "Mean per-replica serving/queue_depth "
                             "above which a tick reads hot (queue "
                             "growth scales up before the burn-rate "
                             "windows mature)."),
    "MXNET_FLEET_QUEUE_DOWN": (float, 0.5,
                               "Max per-replica serving/queue_depth "
                               "below which (absent burn) a tick reads "
                               "cold."),
    "MXNET_FLEET_SPAWN_TIMEOUT_S": (float, 120.0,
                                    "Spawn-to-ready budget: a replica "
                                    "that has not passed /healthz by "
                                    "then is killed and triaged as a "
                                    "failure."),
    "MXNET_FLEET_DRAIN_TIMEOUT_S": (float, 30.0,
                                    "Retirement drain budget: how long "
                                    "a quiesced replica may take to "
                                    "finish its outstanding requests "
                                    "before SIGTERM regardless."),
    "MXNET_QUANT_PERCENTILE": (float, 99.99,
                               "Percentile of |x| the percentile/"
                               "entropy calibration observer clips "
                               "activation ranges at "
                               "(quantize.calibrate."
                               "PercentileObserver) — outliers stop "
                               "stretching every other value's int8 "
                               "resolution."),
    "MXNET_DECODE_SLOTS": (int, 8,
                           "Concurrent sequences the decode engine "
                           "(serve.DecodeEngine) schedules per step. "
                           "Decode compiles one program per power-of-"
                           "two slot bucket up to this."),
    "MXNET_DECODE_PAGE_SIZE": (int, 16,
                               "Tokens per KV-cache page. Smaller = "
                               "less reserved-memory waste per "
                               "sequence, more block-table gather "
                               "entries per step."),
    "MXNET_DECODE_NUM_PAGES": (int, 512,
                               "KV-cache page pool size (page 0 is a "
                               "reserved null page). HBM cost: 2 * "
                               "layers * pages * page_size * kv_heads "
                               "* head_dim * itemsize. Admission "
                               "refuses requests the free list cannot "
                               "cover (503, page-exhaustion detail)."),
    "MXNET_DECODE_MAX_CONTEXT": (int, 256,
                                 "Max prompt + generated tokens per "
                                 "sequence (must be a multiple of the "
                                 "page size; sets the block-table "
                                 "width and the prefill ladder top)."),
    "MXNET_DECODE_QUEUE_DEPTH": (int, 64,
                                 "Decode admission bound: requests "
                                 "waiting for a slot beyond this are "
                                 "rejected immediately (HTTP 503)."),
    "MXNET_DECODE_MAX_NEW_TOKENS": (int, 128,
                                    "Default and cap for a request's "
                                    "max_new_tokens (bounds its page "
                                    "reservation)."),
    "MXNET_DECODE_DEADLINE_MS": (int, 30000,
                                 "Default per-request decode deadline "
                                 "(queued or mid-stream; expired "
                                 "sessions are retired and their "
                                 "pages freed). 0 disables."),
    "MXNET_CKPT_GRACE_S": (int, 30,
                           "Preemption grace window: on SIGTERM, fit "
                           "finishes the in-flight batch and takes a "
                           "final checkpoint; a watchdog hard-exits the "
                           "process when the window ends (the platform "
                           "reclaims the VM then anyway). 0 disables "
                           "the watchdog."),
    "MXNET_KV_RETRIES": (int, 4,
                         "Max retries per kvstore op after a transient "
                         "transport failure (jittered exponential "
                         "backoff; kvstore/retries_total counts them). "
                         "Exhaustion raises a clear MXNetError naming "
                         "the op and attempt count."),
    "MXNET_KV_TIMEOUT_MS": (int, 60000,
                            "Per-op kvstore deadline: bounds each "
                            "socket wait AND the total retry budget, "
                            "so a dead parameter server degrades to an "
                            "error, never a hang. 0 = no deadline."),
    "MXNET_KV_BACKOFF_MS": (int, 50,
                            "Base kvstore retry backoff; attempt n "
                            "sleeps ~base*2^(n-1) with full jitter, "
                            "capped by the remaining op deadline."),
    "MXNET_KV_DEAD_S": (float, 60.0,
                        "Liveness timeout for PS-mode workers: a rank "
                        "with no traffic (RPCs or heartbeats) for this "
                        "many seconds is declared dead. dist_sync rounds "
                        "and barriers then FAIL FAST with an MXNetError "
                        "naming the dead rank(s) instead of hanging; "
                        "dist_async membership just shrinks until the "
                        "rank rejoins. Clients heartbeat at a third of "
                        "this interval."),
    "MXNET_KV_SNAPSHOT_PATH": (str, "",
                               "KVStore server state snapshot file "
                               "(store, barrier generation, RPC dedup "
                               "commit records, membership epochs, "
                               "server-side optimizer state). Empty "
                               "disables snapshots; set it to make the "
                               "server restartable with --restore after "
                               "a SIGKILL."),
    "MXNET_KV_SNAPSHOT_S": (float, 10.0,
                            "Async-mode snapshot throttle: at most one "
                            "server snapshot per this many seconds "
                            "(updates applied since the last snapshot "
                            "are the documented failover loss window). "
                            "Sync mode ignores it — every committed "
                            "round snapshots before acking, so a "
                            "restored sync run is bitwise-identical."),
    "MXNET_SUPERVISOR_MAX_FAILURES": (int, 3,
                                      "TrainingSupervisor.supervise "
                                      "stop-bound for GENUINE failures "
                                      "(nonzero exit from an uncaught "
                                      "exception). Preemption-grade "
                                      "deaths (signal kills, rc 137/"
                                      "143) relaunch without burning "
                                      "this budget."),
    "MXNET_TRACING": (bool, True,
                      "End-to-end span tracing (tracing.py): request/"
                      "step timelines propagated across serve, "
                      "executor, kvstore, and module layers. 0 removes "
                      "every call-site hook (one module-bool check, "
                      "like fault.py)."),
    "MXNET_TRACE_SAMPLE": (float, 1.0,
                           "Head-sampling probability for new traces "
                           "(decided once at the root: an HTTP request "
                           "or a train step). 0 disables recording but "
                           "keeps X-Request-Id echo; lower in "
                           "production to bound tracer work."),
    "MXNET_TRACE_OPS": (bool, False,
                        "Record a per-op op.dispatch span for every "
                        "eager dispatch under a sampled trace. Off by "
                        "default: on microsecond-scale ops the span "
                        "write dominates the dispatch itself (the "
                        "trace_overhead bench banks it), so structural "
                        "spans stay cheap and per-op detail is opt-in."),
    "MXNET_TRACE_SLOW_MS": (int, 1000,
                            "Slow-exemplar threshold: sampled traces "
                            "whose root span exceeds this many ms (and "
                            "every sampled trace ending in an error/"
                            "timeout/injected fault) are retained in a "
                            "separate always-kept ring."),
    "MXNET_TRACE_RING": (int, 64,
                         "How many finished traces the in-memory ring "
                         "keeps for /traces and the chrome-trace "
                         "merge."),
    "MXNET_LOG_JSON": (bool, False,
                       "log.get_logger emits one JSON object per "
                       "record (ts/level/name/msg + trace_id/span_id "
                       "from the active trace context). 0 keeps the "
                       "plain formatter, which appends [trace=…] when "
                       "a context is active."),
    "MXNET_NUMERICS": (str, "off",
                       "In-program numerics sentinels folded into the "
                       "fused train step (health.py): off | step "
                       "(loss proxy + global grad norm + nonfinite "
                       "count, one small D2H fetch per step) | full "
                       "(adds per-parameter attribution so a trip "
                       "names the layer). Zero extra host dispatches, "
                       "zero recompiles across LR steps."),
    "MXNET_NUMERICS_POLICY": (str, "warn",
                              "What a numerics-sentinel trip does: "
                              "warn (log + count + flight-record, "
                              "keep training) | raise "
                              "(health.NumericsError) | "
                              "checkpoint-and-raise (fit saves the "
                              "tripped state under <prefix>.numerics "
                              "for forensics, then raises)."),
    "MXNET_NUMERICS_SPIKE": (float, 0.0,
                             "Grad-norm spike threshold: trip when the "
                             "global grad norm exceeds this many times "
                             "its running EMA. 0 disables spike "
                             "detection (nonfinite detection stays "
                             "on)."),
    "MXNET_FLIGHT_RECORDER": (str, "",
                              "Crash-safe flight-recorder path "
                              "(blackbox.py): lifecycle events "
                              "(compiles, swaps, failovers, rejoins, "
                              "checkpoints, faults, alerts, numerics "
                              "trips) appended as CRC-framed fsync'd "
                              "records readable post-mortem via "
                              "python -m mxnet_tpu.blackbox. Empty "
                              "disables."),
    "MXNET_FLIGHT_RECORDER_MB": (float, 4.0,
                                 "Flight-recorder ring bound: the "
                                 "active segment rotates to <path>.1 "
                                 "at half this size, so on-disk "
                                 "footprint never exceeds ~this many "
                                 "MB and the newest events always "
                                 "survive."),
    "MXNET_SLO_INTERVAL_S": (float, 2.0,
                             "SLO evaluator wake period (health.py "
                             "background thread; it only READS "
                             "telemetry). Rules fire on multi-window "
                             "burn rate, so the interval bounds "
                             "detection latency, not sensitivity."),
    "MXNET_SLO_SERVE_P99_MS": (float, 1000.0,
                               "Default serve_p99 SLO rule threshold: "
                               "interval-local p99 of serving/"
                               "request_seconds above this fires "
                               "/alerts after the burn windows "
                               "agree."),
    "MXNET_SLO_DECODE_ITL_P99_MS": (float, 250.0,
                                    "Default decode_itl_p99 SLO rule "
                                    "threshold over decode/"
                                    "step_seconds p99 (inter-token "
                                    "latency)."),
    "MXNET_SLO_MFU_DIVERGENCE": (float, 0.20,
                                 "Default mfu_divergence SLO rule "
                                 "threshold: the health/mfu_divergence "
                                 "gauge (|measured/hand-counted - 1| "
                                 "from bench runs) above this fires "
                                 "/alerts in events mode."),
    "MXNET_SLO_BADPUT_FRACTION": (float, 0.5,
                                  "Default badput_fraction SLO rule "
                                  "threshold on the goodput/"
                                  "badput_fraction gauge: the fraction "
                                  "of run wall NOT spent in useful "
                                  "training-step compute sustained "
                                  "above this fires /alerts."),
    "MXNET_GOODPUT": (bool, True,
                      "Training goodput ledger (goodput.py): "
                      "attribute every wall-second of a fit to one "
                      "category (step_compute/data_wait/compile/"
                      "checkpoint/rescale/restart/straggler_wait/"
                      "idle). Pure host arithmetic, zero extra device "
                      "dispatches; 0 removes the fit-loop hooks."),
    "MXNET_GOODPUT_PREV_EXIT_TS": (str, "",
                                   "Unix timestamp of the supervised "
                                   "predecessor process's death, "
                                   "stamped into a relaunched child's "
                                   "env by checkpoint."
                                   "ProcessSupervisor.run so the "
                                   "child's goodput ledger books the "
                                   "relaunch gap as `restart`. Not "
                                   "set by hand."),
    "MXNET_OBSERVATORY_TIMEOUT_S": (float, 2.0,
                                    "Per-peer HTTP timeout of the "
                                    "cluster observatory's read-only "
                                    "scrapes (observatory.py); a peer "
                                    "that cannot answer within it "
                                    "counts one observatory/"
                                    "scrape_failures_total and is "
                                    "skipped, never raised."),
    "MXNET_FORENSICS": (int, 0,
                        "Compiler-forensics capture (forensics.py): "
                        "after health.capture_cost registers a "
                        "program, also capture its optimized HLO "
                        "(AOT lower+compile under "
                        "suppress_compile_tracking — a persistent-"
                        "cache disk load when MXNET_COMPILE_CACHE_DIR "
                        "is set) and write the per-fusion report "
                        "artifact. Once per program, nothing per "
                        "step; without a compile cache the capture "
                        "compile is real warmup wall."),
    "MXNET_FORENSICS_DIR": (str, "",
                            "Forensics report directory (CRC'd "
                            "<fingerprint>.json artifacts, atomic "
                            "writes). Empty: defaults to "
                            "<MXNET_COMPILE_CACHE_DIR>/forensics; "
                            "with neither set, reports stay in-memory "
                            "only (/programs + diagnostics)."),
    "MXNET_TPU_PEAK_FLOPS": (float, 197e12,
                             "Peak accelerator FLOP/s used as the MFU "
                             "denominator by BOTH benchmark.py "
                             "estimates and the live executor/mfu "
                             "gauge (health.py). Default: v5e bf16 "
                             "MXU peak."),
    "MXNET_TPU_PEAK_HBM_GBPS": (float, 819.0,
                                "Peak HBM bandwidth (GB/s) for the "
                                "hbm_bw_util roofline gauges. "
                                "Default: v5e."),
    "MXNET_COMPILE_CACHE_DIR": (str, "",
                                "Persistent compile cache directory "
                                "(programs.py wires jax's "
                                "jax_compilation_cache_dir underneath): "
                                "compiled XLA executables are "
                                "serialized here and a fresh process "
                                "loads them from disk instead of "
                                "recompiling — the sub-minute replica "
                                "cold-start path. The registry also "
                                "keeps <dir>/warmset.json, the warm-set "
                                "manifest prewarm replays at startup. "
                                "Empty disables. See "
                                "docs/compile_cache.md."),
    "MXNET_PROGRAMS_MAX": (int, 512,
                           "Compiled-program registry bound "
                           "(programs.get_or_build): past this many "
                           "entries the least-recently-used is evicted "
                           "(programs/evictions_total counts them). "
                           "0 = unbounded."),
    "MXNET_FAULT_INJECT": (str, "",
                           "Arm fault-injection points at import: "
                           "point:step:kind[:count] comma list "
                           "(kinds: raise/transient/delay/crash; see "
                           "mxnet_tpu/fault.py). Test-only — never set "
                           "in production."),
    "MXNET_IO_WORKERS": (int, 0,
                         "Decode worker processes for io.DataPipeline. "
                         "0 = inline decode on the staging thread "
                         "(bitwise-identical stream, no parallelism); "
                         "-1 = host cores minus one. Production TPU VMs "
                         "want this near the host core count."),
    "MXNET_IO_PREFETCH": (int, 2,
                          "Depth of the DataPipeline device staging "
                          "buffer: how many decoded batches are "
                          "device_put ahead of the consumer so H2D "
                          "overlaps the previous step's compute. Also "
                          "bounds in-flight decode (workers + prefetch) "
                          "— the pipeline's backpressure."),
    "MXNET_IO_WORKER_RESTARTS": (int, 4,
                                 "Restart budget for crashed "
                                 "DataPipeline decode workers "
                                 "(io/worker_restarts_total counts "
                                 "them). In-flight batches are "
                                 "re-decoded on restart; past the "
                                 "budget the pipeline raises instead "
                                 "of looping a crashing worker."),
    "MXNET_DATALOADER_START_METHOD": (str, "fork",
                                      "Process start method for "
                                      "DataLoader AND io.DataPipeline "
                                      "workers (fork/spawn/forkserver). "
                                      "fork shares the dataset/source "
                                      "copy-on-write but inherits JAX's "
                                      "threads; use spawn/forkserver if "
                                      "forked workers crash (script "
                                      "then needs the standard __main__ "
                                      "guard)."),
}


def get(name, default=None):
    """Read a declared config var with its registered type/default."""
    if name in VARS:
        typ, reg_default, _ = VARS[name]
        raw = os.environ.get(name)
        if raw is None:
            return reg_default if default is None else default
        if typ is bool:
            return raw.lower() in ("1", "true", "yes", "on")
        return typ(raw)
    return os.environ.get(name, default)


def describe():
    """Human-readable table of every config variable."""
    lines = []
    for name in sorted(VARS):
        typ, default, doc = VARS[name]
        cur = os.environ.get(name)
        lines.append("%-40s %-6s default=%-24r %s%s" %
                     (name, typ.__name__, default,
                      ("[set: %r] " % cur) if cur is not None else "", doc))
    return "\n".join(lines)


if __name__ == "__main__":
    print(describe())
