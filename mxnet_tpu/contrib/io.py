"""contrib IO: DataIter adapters (reference:
python/mxnet/contrib/io.py — DataLoaderIter wraps a gluon DataLoader
in the classic DataIter protocol so Module.fit consumes it)."""
from __future__ import annotations

import numpy as _np

from ..io import DataBatch, DataDesc, DataIter
from ..ndarray.ndarray import NDArray, array as _nd_array

__all__ = ["DataLoaderIter"]


class DataLoaderIter(DataIter):
    """Present a ``gluon.data.DataLoader`` as a DataIter (reference:
    contrib/io.py DataLoaderIter): each loader item must be a
    (data, label) pair; shapes come from the first batch."""

    def __init__(self, loader, data_name="data",
                 label_name="softmax_label", dtype="float32"):
        self._loader = loader
        self._iter = iter(loader)
        self._dtype = dtype
        first = next(self._iter)
        data, label = self._as_pair(first)
        super().__init__(batch_size=data.shape[0])
        self.provide_data = [DataDesc(data_name, data.shape, dtype)]
        self.provide_label = [DataDesc(label_name, label.shape, dtype)]
        self._pending = (data, label)

    @staticmethod
    def _as_pair(item):
        data, label = item

        def nd(x):
            if isinstance(x, NDArray):
                return x
            return _nd_array(_np.asarray(x))

        return nd(data), nd(label)

    def reset(self):
        self._iter = iter(self._loader)
        self._pending = None

    def next(self):
        if self._pending is not None:
            data, label = self._pending
            self._pending = None
        else:
            data, label = self._as_pair(next(self._iter))
        return DataBatch(data=[data], label=[label], pad=0)
