"""Marshalling helpers behind the general C ABI (src/native/c_api.cc).

Reference: src/c_api/c_api.cc + c_api_ndarray.cc + c_api_function.cc —
the 198-function flat C surface. Here the C side owns handle lifetime
(a handle IS a strong PyObject* to the object below) and calls these
small, positional helpers; everything shape/dtype/attr-shaped stays in
Python where the JAX runtime lives.

All functions deal in plain types: bytes, lists of ints/strings — no
numpy required on the C side beyond raw buffers.
"""
from __future__ import annotations

import ast

import numpy as _np

from .base import MXNetError
from .ndarray.ndarray import NDArray, zeros as _nd_zeros
from .ops import registry as _reg

__all__ = [
    "nd_create", "nd_shape", "nd_dtype", "nd_copy_from_bytes",
    "nd_to_bytes", "nd_wait", "nd_save", "nd_load",
    "op_list", "op_info", "imperative_invoke",
    "autograd_set_recording", "autograd_mark", "autograd_backward",
    "symbol_from_json", "symbol_to_json", "symbol_list_arguments",
    "executor_bind", "executor_forward", "executor_backward",
    "executor_arg", "executor_grad", "executor_outputs",
    "kv_create", "kv_init", "kv_push", "kv_pull", "kv_type", "kv_rank",
    "kv_group_size",
    "iter_list", "iter_create", "iter_next", "iter_reset", "iter_data",
    "iter_label", "iter_pad",
    "profiler_set_config", "profiler_set_state", "profiler_dump",
    "version", "device_count", "random_seed", "nd_slice", "nd_at",
    "nd_reshape", "nd_context", "nd_storage_type", "nd_wait_all",
    "symbol_list_outputs", "symbol_list_aux", "symbol_get_attr",
    "symbol_list_attr", "kv_set_optimizer", "kv_barrier",
    "engine_set_bulk_size", "profiler_pause", "profiler_stats_print",
]

_DTYPES = {0: "float32", 1: "float64", 2: "float16", 3: "uint8",
           4: "int32", 5: "int8", 6: "int64", 12: "bfloat16"}
_DTYPE_IDS = {v: k for k, v in _DTYPES.items()}


# -- NDArray CRUD (reference: c_api.cc MXNDArrayCreateEx etc.) -------------

def nd_create(shape, dtype_id=0, device="cpu", dev_id=0):
    from .context import Context
    ctx = Context(device, dev_id)
    return _nd_zeros(tuple(int(s) for s in shape), ctx=ctx,
                     dtype=_DTYPES[int(dtype_id)])


def nd_shape(arr):
    return list(arr.shape)


def nd_dtype(arr):
    return _DTYPE_IDS[str(_np.dtype(arr.dtype))]


def nd_copy_from_bytes(arr, buf):
    src = _np.frombuffer(buf, dtype=arr.dtype).reshape(arr.shape)
    arr[:] = NDArray(src.copy(), ctx=arr.context)
    return 0


def nd_to_bytes(arr):
    return arr.asnumpy().tobytes()


def nd_wait(arr):
    arr.wait_to_read()
    return 0


def nd_save(fname, arrs, names):
    from .ndarray import utils as _utils
    _utils.save(fname, dict(zip(names, arrs)) if names else list(arrs))
    return 0


def nd_load(fname):
    from .ndarray import utils as _utils
    loaded = _utils.load(fname)
    if isinstance(loaded, dict):
        names = sorted(loaded)
        return [loaded[n] for n in names], names
    return list(loaded), []


# -- op registry + imperative invoke ---------------------------------------

def op_list():
    return _reg.list_ops()


def op_info(name):
    """(doc, attr_names, attr_default_reprs, num_outputs_or_-1)."""
    op = _reg.get_op(name)
    keys = sorted(op.attr_defaults)
    n_out = op.num_outputs if isinstance(op.num_outputs, int) else -1
    return (op.doc or "", keys, [repr(op.attr_defaults[k]) for k in keys],
            n_out)


def _parse_attr(v):
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


def imperative_invoke(name, inputs, keys, vals):
    """Run one op on NDArray handles (reference: MXImperativeInvoke).
    Returns the output list (mutating ops return their mutated input)."""
    from .ndarray.ndarray import invoke_op
    attrs = {k: _parse_attr(v) for k, v in zip(keys, vals)}
    out = invoke_op(name, list(inputs), attrs)
    return list(out) if isinstance(out, (list, tuple)) else [out]


# -- autograd (reference: c_api.cc MXAutogradSetIsRecording etc.) ----------

def autograd_set_recording(flag):
    from . import autograd
    return 1 if autograd.set_recording(bool(flag)) else 0


def autograd_mark(arrs):
    from . import autograd
    autograd.mark_variables(list(arrs))
    return 0


def autograd_backward(heads):
    from . import autograd
    autograd.backward(list(heads))
    return 0


def autograd_get_grad(arr):
    if arr.grad is None:
        raise MXNetError("array has no gradient")
    g = arr.grad
    return g if isinstance(g, NDArray) else g.todense()


# -- symbol + executor (reference: MXSymbolCreateFromJSON,
#    MXExecutorSimpleBindEx families) ---------------------------------------

def symbol_from_json(json_str):
    from .symbol import symbol as _sym
    return _sym.load_json(json_str)


def symbol_to_json(sym):
    return sym.tojson()


def symbol_list_arguments(sym):
    return list(sym.list_arguments())


class _ExecWrap(object):
    __slots__ = ("exe",)

    def __init__(self, exe):
        self.exe = exe


def executor_bind(sym, names, shape_arrs):
    """simple_bind with named input shapes taken from NDArray handles."""
    shapes = {n: tuple(a.shape) for n, a in zip(names, shape_arrs)}
    return _ExecWrap(sym.simple_bind(**shapes))


def executor_forward(w, is_train):
    w.exe.forward(is_train=bool(is_train))
    return 0


def executor_backward(w):
    w.exe.backward()
    return 0


def executor_arg(w, name):
    return w.exe.arg_dict[name]


def executor_grad(w, name):
    return w.exe.grad_dict[name]


def executor_outputs(w):
    return list(w.exe.outputs)


# -- kvstore (reference: c_api.cc MXKVStoreCreate block,
#    include/mxnet/c_api.h:1942) --------------------------------------------

def kv_create(name):
    from . import kvstore
    return kvstore.create(name)


def kv_init(kv, keys, vals):
    kv.init(list(keys), list(vals))
    return 0


def kv_push(kv, keys, vals, priority):
    kv.push(list(keys), list(vals), priority=int(priority))
    return 0


def kv_pull(kv, keys, outs, priority):
    kv.pull(list(keys), out=list(outs), priority=int(priority))
    return 0


def kv_type(kv):
    return kv.type


def kv_rank(kv):
    return int(kv.rank)


def kv_group_size(kv):
    return int(kv.num_workers)


# -- data iterators (reference: c_api.cc MXListDataIters /
#    MXDataIterCreateIter — the string-kwarg C++ iterator registry) ---------

# iterators creatable through flat string kwargs, mirroring the
# reference's IO registry (NDArrayIter is Python-side there too)
_C_ITERS = ("ImageRecordIter", "MNISTIter", "CSVIter", "LibSVMIter")


class _IterWrap(object):
    __slots__ = ("it", "batch")

    def __init__(self, it):
        self.it = it
        self.batch = None


def iter_list():
    return list(_C_ITERS)


def iter_create(name, keys, vals):
    from . import io as _io
    if name not in _C_ITERS:
        raise MXNetError("unknown data iter %r (have %s)"
                         % (name, ", ".join(_C_ITERS)))
    kwargs = {k: _parse_attr(v) for k, v in zip(keys, vals)}
    if "data_shape" in kwargs and not isinstance(kwargs["data_shape"],
                                                 (tuple, list)):
        kwargs["data_shape"] = (kwargs["data_shape"],)
    return _IterWrap(getattr(_io, name)(**kwargs))


def iter_next(w):
    try:
        w.batch = next(w.it)
        return 1
    except StopIteration:
        w.batch = None
        return 0


def iter_reset(w):
    w.it.reset()
    w.batch = None
    return 0


def _cur_batch(w):
    if w.batch is None:
        raise MXNetError("no current batch: call MXDataIterNext first")
    return w.batch


def iter_data(w):
    return _cur_batch(w).data[0]


def iter_label(w):
    return _cur_batch(w).label[0]


def iter_pad(w):
    return int(_cur_batch(w).pad or 0)


# -- profiler (reference: src/c_api/c_api_profile.cc) -----------------------

def profiler_set_config(keys, vals):
    from . import profiler
    kwargs = {}
    for k, v in zip(keys, vals):
        kwargs[k] = _parse_attr(v)
    profiler.set_config(**kwargs)
    return 0


def profiler_set_state(state):
    from . import profiler
    profiler.set_state({0: "stop", 1: "run"}[int(state)])
    return 0


def profiler_dump(finished):
    from . import profiler
    profiler.dump(finished=bool(finished))
    return 0


# -- batch-2 surfaces: runtime misc, NDArray views, symbol attrs,
#    kvstore optimizer/barrier, profiler pause/stats (reference: c_api.cc) --


def version():
    from . import libinfo
    return int("".join("%02d" % int(x)
                       for x in libinfo.__version__.split(".")[:3]))


def device_count():
    import jax
    try:
        return len(jax.devices())
    except Exception:
        return 0


def random_seed(seed):
    from . import random as _random
    _random.seed(int(seed))
    return 0


def nd_slice(arr, begin, end):
    # MXNDArraySlice slices the leading axis (reference: MXNDArraySlice)
    return arr.slice(begin=(int(begin),), end=(int(end),))


def nd_at(arr, idx):
    return arr[int(idx)]


def nd_reshape(arr, shape):
    return arr.reshape(tuple(int(s) for s in shape))


def nd_context(arr):
    ctx = arr.context
    return (ctx.device_type, int(ctx.device_id))


def nd_storage_type(arr):
    # reference codes (_STORAGE_TYPE_STR_TO_ID): default 0, rsp 1, csr 2
    stype = getattr(arr, "stype", "default")
    return {"default": 0, "row_sparse": 1, "csr": 2}.get(stype, -1)


def nd_wait_all():
    from .ndarray import waitall
    waitall()
    return 0


def symbol_list_outputs(sym):
    return list(sym.list_outputs())


def symbol_list_aux(sym):
    return list(sym.list_auxiliary_states())


def symbol_get_attr(sym, key):
    v = sym.attr(key)
    return "" if v is None else str(v)


def symbol_list_attr(sym):
    attrs = sym.list_attr() or {}
    out = []
    for k in sorted(attrs):
        out.append(str(k))
        out.append(str(attrs[k]))
    return out


def kv_set_optimizer(kv, name, keys, vals):
    import ast as _ast
    from . import optimizer as _opt
    kwargs = {}
    for k, v in zip(keys, vals):
        try:
            kwargs[k] = _ast.literal_eval(v)
        except (ValueError, SyntaxError):
            kwargs[k] = v
    kv.set_optimizer(_opt.create(name, **kwargs))
    return 0


def kv_barrier(kv):
    kv.barrier()
    return 0


def engine_set_bulk_size(size):
    from . import engine as _engine
    return int(_engine.set_bulk_size(int(size)))


def profiler_pause(paused):
    from . import profiler as _prof
    if paused:
        _prof.pause()
    else:
        _prof.resume()
    return 0


def profiler_stats_print(reset):
    from . import profiler as _prof
    return _prof.dumps(reset=bool(reset))


# -- batch-3 surfaces: profiler objects, raw-bytes NDArray serialization,
#    kvstore pushpull, executor reshape (reference: c_api_profile.cc
#    MXProfileCreate* family; c_api.cc MXNDArraySaveRawBytes,
#    MXKVStorePushPull, MXExecutorReshape) --------------------------------

def profile_create(kind, domain, name):
    from . import profiler as _prof
    cls = {"domain": _prof.Domain, "task": _prof.Task,
           "frame": _prof.Frame, "counter": _prof.Counter,
           "event": _prof.Event}[kind]
    if kind in ("domain", "event"):
        return cls(name)
    return cls(domain, name)


def profile_duration(obj, start):
    if start:
        obj.start()
    else:
        obj.stop()
    return 0


def profile_counter_set(obj, value):
    obj.set_value(float(value))
    return 0


def profile_counter_adjust(obj, delta):
    obj.increment(float(delta))
    return 0


def profile_marker(domain, name, scope):
    from . import profiler as _prof
    _prof.Marker(domain, name).mark(scope)
    return 0


def nd_save_raw(arr):
    from .ndarray import mxnet_format as _fmt
    return _fmt.dumps([("", arr)], keyed=False)


def nd_load_raw(buf):
    from .ndarray import mxnet_format as _fmt
    _keys, arrs = _fmt.loads(bytes(buf))
    if not arrs:
        raise MXNetError("empty NDArray byte stream")
    return arrs[0]


def nd_copy_from_ndarray(dst, src):
    dst[:] = src.todense() if hasattr(src, "todense") and \
        getattr(src, "stype", "default") != "default" else src
    return 0


def kv_pushpull(kv, keys, vals, outs, priority):
    kv.pushpull(list(keys), list(vals), out=list(outs),
                priority=int(priority))
    return 0


def executor_reshape(w, names, shape_arrs):
    shapes = {n: tuple(a.shape) for n, a in zip(names, shape_arrs)}
    return _ExecWrap(w.exe.reshape(**shapes))


# -- batch-4: symbol construction (reference: c_api_symbolic.cc
#    MXSymbolCreateVariable / MXSymbolCreateAtomicSymbol /
#    MXSymbolCompose / MXSymbolCopy) ---------------------------------------

def symbol_create_variable(name):
    from .symbol.symbol import var
    return var(name)


def symbol_create_atomic(op_name, keys, vals, name):
    """An op symbol with its inputs left as free (auto) variables;
    Compose wires them (the reference's two-phase graph building)."""
    from . import symbol as _sym_ns
    # only REGISTERED operators resolve — module-level helpers on the
    # symbol namespace (load, Group, var, ...) must not be reachable
    # through the C ABI's op entry point
    if op_name not in _reg.list_ops():
        raise MXNetError("no symbolic operator %r" % op_name)
    fn = getattr(_sym_ns, op_name, None)
    if fn is None or not callable(fn):
        raise MXNetError("no symbolic operator %r" % op_name)
    attrs = {k: _parse_attr(v) for k, v in zip(keys, vals)}
    if name:
        attrs["name"] = name
    return fn(**attrs)


def symbol_compose(sym, name, keys, args):
    """Wire ``args`` into ``sym``'s free variables, in place."""
    if keys:
        sym._compose(name=name or None, **dict(zip(keys, args)))
    else:
        sym._compose(*args, name=name or None)
    return 0


def symbol_copy(sym):
    return sym.copy()


# -- batch 5: CachedOp / autograd state / symbol breadth / recordio /
#    kvstore roles / sparse accessors / quantization
#    (reference: c_api.cc MXCreateCachedOp:1233, c_api_symbolic.cc,
#     c_api_profile.cc, kvstore.h:353)


class _CachedOpC(object):
    """C-ABI CachedOp: the symbol's whole graph as ONE jitted program.

    Inputs follow the reference's CachedOp convention: every entry of
    ``list_arguments() + list_auxiliary_states()``, in order
    (reference: src/imperative/cached_op.cc:40)."""

    def __init__(self, sym):
        self._sym = sym
        self._names = sym.list_arguments() + sym.list_auxiliary_states()
        self._fn = None

    def __call__(self, arrs):
        import jax
        from .symbol.symbol import _graph_eval_fn
        if len(arrs) != len(self._names):
            raise MXNetError(
                "CachedOp expects %d inputs (args+aux), got %d"
                % (len(self._names), len(arrs)))
        if self._fn is None:
            fn = _graph_eval_fn(self._sym, is_train=False)
            names = self._names

            def pure(vals, key):
                outs, _ = fn(dict(zip(names, vals)), key)
                return outs

            self._fn = jax.jit(pure)
        key = jax.random.PRNGKey(0)
        return [NDArray(o)
                for o in self._fn([a._data for a in arrs], key)]


def cached_op_create(sym):
    return _CachedOpC(sym)


def cached_op_invoke(op, inputs):
    return op(inputs)


def autograd_is_recording():
    from . import autograd
    return int(autograd.is_recording())


def autograd_is_training():
    from . import autograd
    return int(autograd.is_training())


def autograd_set_training(flag):
    from . import autograd
    return int(autograd.set_training(bool(flag)))


def autograd_backward_ex(heads, ograds, variables, retain_graph,
                         train_mode):
    """BackwardEx: explicit head gradients + optional variable list whose
    grads are returned (reference: MXAutogradBackwardEx)."""
    from . import autograd
    ograds = ograds or None
    autograd.backward(heads, head_grads=ograds,
                      retain_graph=bool(retain_graph),
                      train_mode=bool(train_mode))
    return [v.grad for v in variables] if variables else []


def nd_create_none():
    return NDArray(_np.zeros((0,), _np.float32))


def nd_detach(arr):
    return arr.detach()


def nd_get_grad(arr):
    return arr.grad


def nd_reshape64(arr, dims, reverse):
    """Reshape with 0 (copy input dim) and -1 (infer) specials;
    ``reverse`` matches the specials from the right like the
    reference's MXNDArrayReshape64."""
    shape = list(arr.shape)
    dims = list(dims)
    if reverse:
        dims = dims[::-1]
        shape = shape[::-1]
    out = []
    for i, d in enumerate(dims):
        if d == 0:
            if i >= len(shape):
                raise MXNetError("0-dim at %d has no source dim" % i)
            out.append(shape[i])
        else:
            out.append(int(d))
    if reverse:
        out = out[::-1]
    return arr.reshape(tuple(out))


def nd_load_from_buffer(buf):
    """Load a .params/.ndarray byte buffer (reference:
    MXNDArrayLoadFromBuffer) — same container format as nd_load."""
    import os
    import tempfile
    fd, path = tempfile.mkstemp(suffix=".params")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(bytes(buf))
        return nd_load(path)
    finally:
        os.unlink(path)


def nd_get_data_nd(arr):
    """Values array of a sparse NDArray; dense arrays return themselves
    (reference: MXNDArrayGetDataNDArray)."""
    from .ndarray.sparse import BaseSparseNDArray
    if isinstance(arr, BaseSparseNDArray):
        return NDArray(_np.asarray(arr.data))
    return arr


def nd_get_aux_nd(arr, i):
    from .ndarray.sparse import CSRNDArray, RowSparseNDArray
    if isinstance(arr, RowSparseNDArray):
        aux = (arr.indices,)
    elif isinstance(arr, CSRNDArray):
        aux = (arr.indptr, arr.indices)
    else:
        raise MXNetError("dense NDArray has no aux array")
    if not 0 <= i < len(aux):
        raise MXNetError("aux index %d out of range" % i)
    return NDArray(_np.asarray(aux[i]))


def nd_get_aux_type(arr, i):
    from .ndarray.sparse import CSRNDArray, RowSparseNDArray
    if isinstance(arr, RowSparseNDArray):
        aux = (arr.indices,)
    elif isinstance(arr, CSRNDArray):
        aux = (arr.indptr, arr.indices)
    else:
        raise MXNetError("dense NDArray has no aux array")
    if not 0 <= i < len(aux):
        raise MXNetError("aux index %d out of range" % i)
    return _DTYPE_IDS[str(_np.dtype(aux[i].dtype))]


def nd_create_sparse(stype, shape, data, aux):
    from .ndarray.sparse import csr_matrix, row_sparse_array
    if stype == "row_sparse":
        return row_sparse_array((data, aux[0]), shape=tuple(shape))
    if stype == "csr":
        return csr_matrix((data, aux[1], aux[0]), shape=tuple(shape))
    raise MXNetError("unknown sparse storage type %r" % stype)


def nd_check_format(arr, full_check):
    """Validate sparse index structure (reference:
    MXNDArraySyncCheckFormat); dense arrays are trivially valid."""
    from .ndarray.sparse import BaseSparseNDArray
    if isinstance(arr, BaseSparseNDArray):
        arr.check_format(full_check=bool(full_check))
    return 0


def symbol_from_file(fname):
    with open(fname) as f:
        return symbol_from_json(f.read())


def symbol_save_file(sym, fname):
    sym.save(fname)
    return 0


def symbol_group(syms):
    from .symbol.symbol import Group
    return Group(list(syms))


def symbol_get_internals(sym):
    return sym.get_internals()


def symbol_get_children(sym):
    return sym.get_children()


def symbol_get_output(sym, i):
    return sym[int(i)]


def symbol_num_outputs(sym):
    return len(sym.list_outputs())


def symbol_get_name(sym):
    entries = sym._entries
    if len(entries) == 1 and entries[0][0].name:
        return entries[0][0].name
    return None


def symbol_set_attr(sym, key, val):
    """Annotation attrs (lr_mult, ctx_group, ...) store dunder-prefixed
    so they never collide with op params — the graph evaluator passes
    bare attrs as op kwargs; Symbol.attr resolves them bare."""
    wrapped = key
    if not (key.startswith("__") and key.endswith("__")):
        node = sym._entries[0][0]
        declared = ()
        if not node.is_var:
            try:
                declared = _reg.get_op(node.op).attr_defaults
            except Exception:
                declared = ()
        if key not in declared:
            wrapped = "__%s__" % key
    sym._set_attr(**{wrapped: val})
    return 0


def symbol_print(sym):
    return sym.debug_str()


def symbol_list_attr_shallow(sym):
    """Non-recursive attr dict of the head node (reference:
    MXSymbolListAttrShallow)."""
    out = []
    node = sym._entries[0][0]
    for k, v in sorted(getattr(node, "attrs", {}).items()):
        if k.startswith("__") and k.endswith("__"):
            k = k[2:-2]        # annotation attrs resolve bare
        out.append(str(k))
        out.append(str(v))
    return out


def symbol_get_inputs(sym):
    """Free variables of the graph, each as its own Symbol handle
    (reference: MXSymbolGetInputSymbols)."""
    from .symbol.symbol import _topo, Symbol
    return [Symbol([(n, 0)]) for n in _topo(sym._entries)
            if n.is_var and not n.is_aux]


def symbol_infer_shape(sym, keys, shapes, partial):
    fn = sym.infer_shape_partial if partial else sym.infer_shape
    arg_shapes, out_shapes, aux_shapes = fn(
        **{k: tuple(s) for k, s in zip(keys, shapes)})
    complete = all(
        ls is not None and all(s is not None for s in ls)
        for ls in (arg_shapes, out_shapes, aux_shapes))
    none_to_empty = lambda ls: [list(s) if s is not None else []  # noqa
                                for s in (ls or [])]
    return (none_to_empty(arg_shapes), none_to_empty(out_shapes),
            none_to_empty(aux_shapes), int(complete))


def symbol_infer_type(sym, keys, dtype_ids):
    arg_t, out_t, aux_t = sym.infer_type(
        **{k: _DTYPES[i] for k, i in zip(keys, dtype_ids)})
    to_ids = lambda ls: [_DTYPE_IDS[_np.dtype(t).name]  # noqa: E731
                         for t in (ls or [])]
    return (to_ids(arg_t), to_ids(out_t), to_ids(aux_t),
            int(arg_t is not None))


def op_creators():
    """Atomic-symbol creator handles = interned op-name strings
    (reference returns nnvm op pointers; the name IS our identity)."""
    return sorted(_reg.list_ops())


def creator_name(h):
    return str(h)


def recio_reader_create(path):
    from .recordio import MXRecordIO
    return MXRecordIO(path, "r")


def recio_writer_create(path):
    from .recordio import MXRecordIO
    return MXRecordIO(path, "w")


def recio_read(r):
    return r.read()            # None at EOF


def recio_write(w, buf):
    w.write(bytes(buf))
    return 0


def recio_seek(r, pos):
    r.seek(pos)
    return 0


def recio_tell(r):
    return r.tell()


def recio_close(r):
    r.close()
    return 0


def kv_role():
    import os
    return os.environ.get("MXNET_TPU_ROLE", "worker")


def kv_num_dead(kv, node_id, timeout):
    return int(kv.num_dead_node(node_id, timeout=timeout))


def kv_set_gc(kv, keys, vals):
    kv.set_gradient_compression(
        {k: _parse_attr(v) for k, v in zip(keys, vals)})
    return 0


def kv_send_command(kv, head, body):
    """Controller command to all servers (reference:
    MXKVStoreSendCommmandToServers); profiler heads route through the
    server-profiler path."""
    kv._server_profiler_command(head, body)
    return 0


def kv_set_barrier_before_exit(kv, flag):
    kv._barrier_before_exit = bool(flag)
    return 0


def kv_run_server(kv):
    """Run the server-role loop on this process, blocking until shutdown
    (reference: MXKVStoreRunServer)."""
    from .kvstore_server import serve_forever as _serve
    _serve()
    return 0


def kv_init_ps_env(keys, vals):
    import os
    os.environ.update({str(k): str(v) for k, v in zip(keys, vals)})
    return 0


def kv_set_updater(kv, fn_ptr, handle_ptr, str_keys):
    """Install a C updater callback: merged gradient + stored weight per
    key (reference: MXKVStoreSetUpdater/SetUpdaterEx). ``fn_ptr`` is the
    raw C function pointer; handles passed to it are borrowed PyObject*
    valid for the duration of the call. A NULL fn_ptr clears the
    updater."""
    if not fn_ptr:
        kv._set_updater(None)
        return 0
    import ctypes
    keyt = ctypes.c_char_p if str_keys else ctypes.c_int
    proto = ctypes.CFUNCTYPE(None, keyt, ctypes.c_void_p,
                             ctypes.c_void_p, ctypes.c_void_p)
    cb = proto(fn_ptr)

    def updater(key, recv, local):
        if str_keys:
            k = str(key).encode()
        else:
            ks = str(key)
            if not ks.lstrip("-").isdigit():
                raise MXNetError(
                    "int-key updater installed but store key %r is not "
                    "numeric; use MXKVStoreSetUpdaterEx (string keys)"
                    % (key,))
            k = int(ks)
        cb(k, id(recv), id(local), handle_ptr)

    kv._set_updater(updater)
    return 0


def iter_index(w):
    b = _cur_batch(w)
    if b.index is None:
        raise MXNetError("iterator does not provide batch indices")
    return [int(i) for i in b.index]


def iter_info(name):
    from . import io as _io
    cls = getattr(_io, name, None)
    if cls is None:
        raise MXNetError("no such iterator %r" % name)
    doc = (cls.__doc__ or "").strip()
    return [name, doc.splitlines()[0] if doc else ""]


def quantize_symbol(sym, excluded, quantized_dtype):
    """Graph-only quantization pass (reference: MXQuantizeSymbol) —
    runtime min/max, no calibration table."""
    from .contrib.quantization import quantize_model
    qsym, _, _ = quantize_model(sym, {}, {}, calib_mode="none",
                                excluded_sym_names=tuple(excluded),
                                quantized_dtype=quantized_dtype)
    return qsym


def calibrate_quantized_symbol(sym, names, mins, maxs):
    """Attach a calibration table to a quantized graph (reference:
    MXSetCalibTableToQuantizedSymbol): set min/max attrs on matching
    quantize/requantize nodes so runtime range ops fold away."""
    from .symbol.symbol import _topo
    table = {n: (float(lo), float(hi))
             for n, lo, hi in zip(names, mins, maxs)}
    s = sym.copy()
    hits = 0
    for node in _topo(s._entries):
        base = (node.name or "").replace("_quantize", "") \
                                .replace("_requantize", "")
        if base in table:
            lo, hi = table[base]
            node.attrs["min_calib_range"] = str(lo)
            node.attrs["max_calib_range"] = str(hi)
            hits += 1
    return s


def executor_bind_explicit(sym, args, grads, req_strs, aux):
    """bind with explicit arrays in list_arguments order (reference:
    MXExecutorBind/BindX/BindEX)."""
    from .executor import Executor
    from .context import current_context
    grad_map = None
    if grads:
        names = sym.list_arguments()
        grad_map = {n: g for n, g in zip(names, grads) if g is not None}
    req = list(req_strs) if req_strs else "write"
    if isinstance(req, list) and req and all(r == req[0] for r in req):
        req = req[0]
    return _ExecWrap(Executor(sym, current_context(), list(args), grad_map,
                              req, list(aux) if aux else None))


def executor_backward_ex(w, ograds):
    w.exe.backward(ograds if ograds else None)
    return 0


def executor_print(w):
    return w.exe.debug_str()


def executor_optimized_symbol(w):
    return w.exe._symbol.copy()


def set_omp_threads(n):
    """Host thread-pool hint (reference: MXSetNumOMPThreads -> OMP);
    here it sizes the native decode pool default via env."""
    import os
    os.environ["OMP_NUM_THREADS"] = str(int(n))
    return 0


# -- batch 5b: sparse pulls, dlpack, fresh-grad flag, monitor callback


def kv_pull_rsp(kv, keys, outs, row_ids, priority):
    """Pull only the rows in row_ids per key (reference:
    MXKVStorePullRowSparse)."""
    kv.row_sparse_pull(list(keys), out=list(outs), priority=int(priority),
                       row_ids=list(row_ids))
    return 0


def kv_pull_sparse(kv, keys, outs, priority, ignore_sparse):
    kv.pull(list(keys), out=list(outs), priority=int(priority),
            ignore_sparse=bool(ignore_sparse))
    return 0


def symbol_grad(sym, wrt):
    """Faithful to the reference: MXSymbolGrad is 'not implemented'
    there (c_api_symbolic.cc:640); bind with grad_req and use
    backward."""
    return sym.grad(list(wrt))


def nd_get_fresh_grad(arr):
    return int(getattr(arr, "_fresh_grad", False))


def nd_set_fresh_grad(arr, flag):
    arr._fresh_grad = bool(flag)
    return 0


def nd_to_dlpack(arr):
    """DLPack capsule over a HOST snapshot of the buffer (the reference
    shares CPU memory; PjRt device buffers are copied D2H first)."""
    # .copy(): jax-backed views are read-only, which DLPack can't signal
    return arr.asnumpy().copy().__dlpack__()


class _DLPackWrapper(object):
    """Adapter giving a raw capsule the array-interchange protocol."""

    def __init__(self, capsule):
        self._c = capsule

    def __dlpack__(self, **kwargs):
        return self._c

    def __dlpack_device__(self):
        return (1, 0)  # kDLCPU


def nd_from_dlpack(capsule):
    return NDArray(_np.from_dlpack(_DLPackWrapper(capsule)).copy())


def executor_set_monitor(w, fn_ptr, handle_ptr, monitor_all):
    """Install a C monitor callback invoked per output (reference:
    MXExecutorSetMonitorCallback); handles passed to it are borrowed.
    A NULL fn_ptr uninstalls (lets C++ wrappers detach before their
    state dies)."""
    if not fn_ptr:
        w.exe.set_monitor_callback(None)
        return 0
    import ctypes
    proto = ctypes.CFUNCTYPE(None, ctypes.c_char_p, ctypes.c_void_p,
                             ctypes.c_void_p)
    cb = proto(fn_ptr)

    def monitor(name, arr):
        cb(str(name).encode(), id(arr), handle_ptr)

    w.exe.set_monitor_callback(monitor, monitor_all=bool(monitor_all))
    return 0
