#!/usr/bin/perl
# Build the AI::MXNetTPU XS extension (no non-core modules needed):
# xsubpp the glue, compile with the toolchain g++, link against
# build/native/libmxtpu_predict.so. Run from any cwd:
#   perl perl-package/AI-MXNetTPU/build.pl
# The loadable lands in blib/arch/auto/AI/MXNetTPU/ (DynaLoader layout);
# use with  perl -I<pkg>/lib -I<pkg>/blib/arch ...
use strict;
use warnings;
use Config;
use File::Basename qw(dirname);
use File::Path qw(make_path);
use File::Spec;
use ExtUtils::ParseXS;

my $pkg  = File::Spec->rel2abs(dirname(__FILE__));
my $root = dirname(dirname($pkg));
my $native = File::Spec->catdir($root, "build", "native");

die "build libmxtpu_predict.so first (make -C src/native)\n"
    unless -e File::Spec->catfile($native, "libmxtpu_predict.so");

my $arch_auto = File::Spec->catdir($pkg, "blib", "arch", "auto",
                                   "AI", "MXNetTPU");
make_path($arch_auto);

my $typemap = File::Spec->catfile($Config{privlib}, "ExtUtils", "typemap");
my $xs = File::Spec->catfile($pkg, "MXNetTPU.xs");
my $c  = File::Spec->catfile($pkg, "MXNetTPU.c");
ExtUtils::ParseXS->new->process_file(
    filename => $xs, output => $c, typemap => $typemap);

my $core = File::Spec->catdir($Config{archlib}, "CORE");
my $so = File::Spec->catfile($arch_auto, "MXNetTPU.$Config{dlext}");
my @cmd = ("g++", "-shared", "-fPIC", "-O2", $c,
           "-I", $core, "-I", File::Spec->catdir($root, "include"),
           split(" ", $Config{ccflags} || ""),
           "-DVERSION=\"0.1.0\"", "-DXS_VERSION=\"0.1.0\"",
           "-o", $so, "-L", $native, "-lmxtpu_predict",
           "-Wl,-rpath,$native");
system(@cmd) == 0 or die "compile failed: @cmd\n";
print "built $so\n";
