"""Cluster observatory + goodput ledger (ISSUE 20).

Fast units cover the ledger's accounting invariants (categories sum to
measured wall, restart-gap crediting, overrun honesty, open-step
overlap), the supervisor's ``MXNET_GOODPUT_PREV_EXIT_TS`` stamp, the
snapshot/diagnostics/SLO surfaces, peer discovery (heartbeat-published
endpoints, fleet roster, dead-peer degradation), the read-only scrape
fence, and the flight-ring merge — including a real subprocess ring
SIGKILLed mid-frame.

The ``slow``-marked chaos acceptance replays the PR 19 SIGKILL run
with per-rank flight rings and the goodput ledger on: the merged
incident timeline must read fault → member_lost → rescale(shrink) →
rescale(grow) in causal order, and the survivor's goodput report must
sum to 100% of wall with the outage attributed to rescale (the
relaunched joiner books its dead time as restart).
"""
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from mxnet_tpu import blackbox
from mxnet_tpu import goodput as gp
from mxnet_tpu import health
from mxnet_tpu import observatory as ob
from mxnet_tpu import telemetry as tm
from mxnet_tpu import tracing as tr

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_ledger():
    gp.reset()
    gp.enable(True)
    ob.configure()                       # clear any installed observatory
    yield
    gp.reset()
    ob.configure()


def _cat_sum(rep):
    return sum(v["seconds"] for v in rep["categories"].values())


# ---------------------------------------------------------------------------
# goodput ledger: the accounting invariants
# ---------------------------------------------------------------------------

def test_ledger_sums_to_wall():
    gp.session_begin()
    tok = gp.step_begin()
    time.sleep(0.03)
    gp.step_end(tok, data_wait_s=0.01)
    time.sleep(0.01)                     # real wall backing the note
    gp.note("checkpoint", 0.005)
    gp.session_end()
    rep = gp.report()
    assert rep["active"] and rep["steps"] == 1
    assert set(rep["categories"]) == set(gp.CATEGORIES)
    # THE invariant: categories (idle residual included) sum to wall
    assert abs(_cat_sum(rep) - rep["wall_s"]) < 1e-4
    assert rep["categories"]["data_wait"]["seconds"] >= 0.01
    assert rep["categories"]["checkpoint"]["seconds"] >= 0.005
    assert rep["categories"]["step_compute"]["seconds"] > 0
    assert rep["overrun_s"] == 0
    assert abs(rep["goodput_fraction"] + rep["badput_fraction"] - 1.0) < 1e-5


def test_ledger_inactive_and_disabled():
    assert gp.report() == {"active": False}
    gp.enable(False)
    gp.session_begin()
    assert not gp.active()
    assert gp.step_begin() is None


def test_note_rejects_idle_and_unknown():
    gp.session_begin()
    with pytest.raises(ValueError):
        gp.note("idle", 1.0)
    with pytest.raises(ValueError):
        gp.note("lunch", 1.0)
    with pytest.raises(ValueError):
        gp.note_since_last("idle")


def test_note_inside_open_step_not_double_counted():
    """A barrier wait booked from INSIDE an open step window must be
    subtracted from that step's compute — the sum stays <= wall."""
    gp.session_begin()
    tok = gp.step_begin()
    time.sleep(0.02)
    gp.note("straggler_wait", 0.015)     # booked mid-step (kv.barrier)
    gp.step_end(tok)
    rep = gp.report()
    assert abs(_cat_sum(rep) - rep["wall_s"]) < 1e-4
    assert rep["overrun_s"] == 0
    assert rep["categories"]["straggler_wait"]["seconds"] >= 0.015
    # step window was ~0.02s of which 0.015 was the wait
    assert rep["categories"]["step_compute"]["seconds"] < 0.02


def test_note_since_last_books_the_gap():
    """The elastic-outage idiom: an interrupted step never reaches
    step_end; note_since_last sweeps everything since the last
    accounting point into the category."""
    gp.session_begin()
    gp.step_begin()                      # the step that will "fail"
    time.sleep(0.02)
    dt = gp.note_since_last("rescale")
    assert dt >= 0.02
    rep = gp.report()
    assert rep["categories"]["rescale"]["seconds"] >= 0.02
    assert abs(_cat_sum(rep) - rep["wall_s"]) < 1e-4


def test_overrun_reported_honestly():
    """Booked time exceeding measured wall (clock skew) scales every
    category down so the report still sums exactly — and reports the
    overage instead of hiding it."""
    gp.session_begin()
    gp.note("checkpoint", 100.0)         # grossly exceeds session wall
    rep = gp.report()
    assert rep["overrun_s"] > 90
    assert abs(_cat_sum(rep) - rep["wall_s"]) < 1e-4
    assert rep["categories"]["idle"]["seconds"] == 0


def test_restart_gap_credited_from_env(monkeypatch):
    monkeypatch.setenv("MXNET_GOODPUT_PREV_EXIT_TS",
                       repr(time.time() - 2.5))
    gp.reset()
    gp.session_begin()
    rep = gp.report()
    restart = rep["categories"]["restart"]["seconds"]
    assert 2.0 < restart < 10.0
    # the gap extends measured wall, so the invariant covers the outage
    assert rep["wall_s"] >= restart
    assert abs(_cat_sum(rep) - rep["wall_s"]) < 1e-4


def test_supervisor_stamps_prev_exit_ts(tmp_path):
    """A relaunched child finds its predecessor's death timestamp in
    the env ProcessSupervisor built for it."""
    from mxnet_tpu.checkpoint import ProcessSupervisor
    marker = str(tmp_path / "seen.json")
    script = str(tmp_path / "child.py")
    with open(script, "w") as f:
        f.write(
            "import json, os, sys\n"
            "ts = os.environ.get('MXNET_GOODPUT_PREV_EXIT_TS')\n"
            "if ts is None: sys.exit(17)\n"           # first launch dies
            "json.dump({'ts': float(ts)}, open(%r, 'w'))\n" % marker)
    sup = ProcessSupervisor(max_failures=3, relaunch_delay_s=0)
    t0 = time.time()
    rc = sup.run([sys.executable, script])
    assert rc == 0 and sup.launches == 2
    seen = json.load(open(marker))
    assert t0 <= seen["ts"] <= time.time()


def test_snapshot_and_diagnostics_bank_goodput():
    gp.session_begin()
    tok = gp.step_begin()
    gp.step_end(tok)
    snap = tm.snapshot()
    assert "goodput_fraction" in snap and "goodput_wall_s" in snap
    for c in gp.CATEGORIES:
        assert "goodput_%s_s" % c in snap
    info = tm.diagnostics(as_dict=True)
    assert info["goodput"]["active"] is True


def test_badput_slo_rule_registered():
    assert "badput_fraction" in health.rules()


def test_goodput_overhead_job_registered():
    from mxnet_tpu import benchmark as B
    assert "goodput_overhead" in B.JOBS
    assert "goodput_overhead" in B.JOB_PRIORITY


def test_goodput_gauges_exported():
    gp.session_begin()
    for i in range(8):                   # gauge refresh is every 8th step
        gp.step_end(gp.step_begin())
    text = tm.render_prometheus()
    assert "mxnet_goodput_wall_seconds" in text
    assert 'mxnet_goodput_category_seconds{category="step_compute"}' in text
    assert "mxnet_goodput_badput_fraction" in text


# ---------------------------------------------------------------------------
# observatory: discovery, degradation, fence, /cluster
# ---------------------------------------------------------------------------

def test_cluster_endpoint_unconfigured(monkeypatch):
    monkeypatch.delenv("MXNET_ELASTIC_DIR", raising=False)
    code, payload = ob.cluster_endpoint("")
    assert code == 200 and payload == {"configured": False}


def test_cluster_mounted_on_telemetry_serve(monkeypatch):
    monkeypatch.delenv("MXNET_ELASTIC_DIR", raising=False)
    with tm.serve(port=0) as srv:
        body = urllib.request.urlopen(
            "http://127.0.0.1:%d/cluster" % srv.port, timeout=5).read()
    assert json.loads(body) == {"configured": False}


def test_cluster_mounted_on_serve_http(monkeypatch):
    monkeypatch.delenv("MXNET_ELASTIC_DIR", raising=False)
    from mxnet_tpu.serve.http import serve_http
    srv = serve_http(object(), port=0)   # GET /cluster needs no engine
    try:
        body = urllib.request.urlopen(
            "http://127.0.0.1:%d/cluster" % srv.port, timeout=5).read()
        assert json.loads(body) == {"configured": False}
        # the serving mount publishes itself as the scrapable endpoint
        assert tm.server_endpoint() == "127.0.0.1:%d" % srv.port
    finally:
        srv.close()


def test_dead_peer_degrades_to_counter():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead = "127.0.0.1:%d" % s.getsockname()[1]
    s.close()                            # nobody listens there now
    o = ob.Observatory(peers=(dead,), timeout_s=0.3)
    view = o.cluster_view()              # must not raise
    assert view["peer_count"] == 1
    assert view["peers"][0]["ok"] is False
    assert view["scrape_failures_total"] >= 3   # alerts+metrics+traces
    fam = tm.REGISTRY._families.get("observatory/scrape_failures_total")
    assert fam is not None and sum(c.value for _lv, c in fam.series()) >= 3


def test_heartbeat_publishes_endpoint_and_discovery(tmp_path):
    """An elastic rank's heartbeat carries its telemetry endpoint; the
    observatory discovers the rank from the heartbeat file alone and
    scrapes it."""
    from mxnet_tpu.elastic import ElasticAgent
    with tm.serve(port=0) as srv:
        agent = ElasticAgent(root=str(tmp_path), rank=0, world=1,
                             base_world=1, hb_s=999, dead_s=999)
        agent._beat()
        rec = json.load(open(tmp_path / "hb-g1-r0.json"))
        assert rec["telemetry"] == "127.0.0.1:%d" % srv.port
        o = ob.Observatory(elastic_dir=str(tmp_path))
        peers = o.discover()
        assert [p["name"] for p in peers] == ["rank0"]
        view = o.cluster_view()
        assert view["peers"][0]["ok"] is True
        assert view["scrape_failures_total"] == 0


def test_fleet_roster_peers_discovered():
    status = {"replicas": [{"name": "r0", "pid": 1, "port": 18341,
                            "endpoint": "127.0.0.1:18341",
                            "retiring": False, "warm": True,
                            "spawn_s": 0.1},
                           {"name": "r1", "pid": 2, "port": None,
                            "endpoint": None, "retiring": False,
                            "warm": False, "spawn_s": 0.1}]}

    class _FakeFleet(object):
        def status(self):
            return status
    o = ob.Observatory(fleet=_FakeFleet())
    peers = o.discover()
    # portless (still-spawning) replicas are skipped, not scraped
    assert peers == [{"name": "r0", "kind": "replica",
                      "host": "127.0.0.1", "port": 18341}]


def test_scrape_is_fenced_and_read_only():
    """The bugfix contract: observatory HTTP activity runs under the
    compile-tracking fence, so a scrape — even of this very process —
    cannot perturb compile counts or dispatch totals."""
    fenced = []
    real_get = ob._http_get

    def spying_get(host, port, path, timeout=2.0):
        fenced.append(getattr(tm._suppress, "on", 0) > 0)
        # a compile event arriving mid-scrape (any jax activity on
        # this thread) must NOT be counted — same fence as cost
        # analysis
        tm._on_jax_event("/jax/backend_compile_duration", 123.0)
        return real_get(host, port, path, timeout)

    with tm.serve(port=0) as srv:
        o = ob.Observatory(peers=("127.0.0.1:%d" % srv.port,))
        compiles0 = tm.compile_count()
        ctime0 = tm.compile_time()
        snap0 = tm.snapshot()["op_dispatch_total"]
        ob._http_get, _saved = spying_get, ob._http_get
        try:
            view = o.cluster_view()
        finally:
            ob._http_get = _saved
    assert view["peers"][0]["ok"] is True
    assert fenced and all(fenced), "scrape ran outside the fence"
    assert tm.compile_count() == compiles0
    assert tm.compile_time() == ctime0
    assert tm.snapshot()["op_dispatch_total"] == snap0


def test_self_scrape_merges_own_goodput():
    gp.session_begin()
    for _ in range(8):
        gp.step_end(gp.step_begin())
    gp.session_end()
    with tm.serve(port=0) as srv:
        o = ob.Observatory(peers=("127.0.0.1:%d" % srv.port,))
        view = o.cluster_view()
        summary = o.summary()
    gp_row = view["goodput"]["peer0"]
    assert set(gp_row["categories"]) == set(gp.CATEGORIES)
    assert "goodput_fraction" in gp_row
    assert summary["peers"] == 1 and summary["peers_ok"] == 1
    assert "goodput" in summary


def test_diagnostics_embeds_cluster_summary(monkeypatch):
    with tm.serve(port=0) as srv:
        ob.configure(peers=("127.0.0.1:%d" % srv.port,))
        info = tm.diagnostics(as_dict=True)
    assert info["cluster"]["peers"] == 1
    assert info["cluster"]["peers_ok"] == 1
    assert isinstance(info["cluster"]["alerts_firing"], list)


# ---------------------------------------------------------------------------
# flight-ring merge
# ---------------------------------------------------------------------------

def test_merge_rings_in_process(tmp_path):
    a, b = str(tmp_path / "a.bin"), str(tmp_path / "b.bin")
    blackbox.configure(a)
    blackbox.record_event("checkpoint", file="ck0", seconds=0.1)
    blackbox.record_event("alert", rule="r", state="firing", value=1.0)
    blackbox.configure(b)
    blackbox.record_event("checkpoint", file="ck1", seconds=0.2)
    blackbox.configure(None)
    merged = blackbox.merge_rings([a, b])
    names = [(e["event"], e["ring"]) for e in merged["events"]
             if e["event"] != "start"]
    assert names == [("checkpoint", a), ("alert", a), ("checkpoint", b)]
    ts = [e["t"] for e in merged["events"]]
    assert ts == sorted(ts)
    assert merged["abandoned"] == {a: 0, b: 0}
    # per-ring reads and the merge agree exactly: no loss, no dup
    for ring in (a, b):
        own, _torn = blackbox.read_events(ring)
        assert [e["event"] for e in merged["events"]
                if e["ring"] == ring] == [e["event"] for e in own]


def test_merge_rings_missing_ring_degrades(tmp_path):
    a = str(tmp_path / "a.bin")
    blackbox.configure(a)
    blackbox.record_event("checkpoint", file="ck", seconds=0.1)
    blackbox.configure(None)
    gone = str(tmp_path / "nope.bin")
    merged = blackbox.merge_rings([a, gone])
    assert any(e["event"] == "checkpoint" for e in merged["events"])
    assert merged["abandoned"][gone] == 0


_RING_WORKER = r'''
import json, os, signal, struct, sys, time, zlib
path, torn = sys.argv[1], int(sys.argv[2])
from mxnet_tpu import blackbox as bb
bb.configure(path)
for i in range(3):
    bb.record_event("checkpoint", file="ck%d" % i, seconds=0.01)
if torn:
    # the killer names itself before dying (fsync'd fault record)...
    bb.record_event("fault", point="test.kill", kind="crash", hit=1)
    # ...then the process is SIGKILLed mid-frame: a valid header whose
    # payload never finished hitting the disk
    payload = json.dumps({"t": time.time(), "pid": os.getpid(),
                          "event": "checkpoint"}).encode()
    frame = struct.pack("<4sII", b"FR\x00\x00", len(payload),
                        zlib.crc32(payload) & 0xFFFFFFFF) + payload[:9]
    with open(path, "ab") as f:
        f.write(frame)
        f.flush()
        os.fsync(f.fileno())
    print("TORN %d" % (struct.calcsize("<4sII") + 9), flush=True)
    os.kill(os.getpid(), signal.SIGKILL)
print("DONE", flush=True)
'''


def test_merge_rings_subprocess_sigkill_torn_tail(tmp_path):
    """Two real subprocess rings — one SIGKILLed mid-frame — merge
    into one ordered timeline: the killer fault event is present, the
    torn ring reports its abandoned bytes, and nothing is lost or
    duplicated."""
    script = str(tmp_path / "worker.py")
    with open(script, "w") as f:
        f.write(_RING_WORKER)
    ra, rb = str(tmp_path / "flight-a.bin"), str(tmp_path / "flight-b.bin")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = ROOT + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")

    pa = subprocess.run([sys.executable, script, ra, "1"], env=env,
                        capture_output=True, text=True, timeout=120)
    assert pa.returncode == -signal.SIGKILL, pa.stdout + pa.stderr
    torn_bytes = int(pa.stdout.split("TORN ")[1].split()[0])
    pb = subprocess.run([sys.executable, script, rb, "0"], env=env,
                        capture_output=True, text=True, timeout=120)
    assert pb.returncode == 0, pb.stdout + pb.stderr

    merged = blackbox.merge_rings([ra, rb])
    # torn tail accounted per ring, clean ring untouched
    assert merged["abandoned"] == {ra: torn_bytes, rb: 0}
    # the killer is in the timeline, from the SIGKILLed ring
    faults = [e for e in merged["events"] if e["event"] == "fault"]
    assert len(faults) == 1 and faults[0]["ring"] == ra
    assert faults[0]["kind"] == "crash"
    # ordered by time; ring A ran (and died) strictly before ring B
    ts = [e["t"] for e in merged["events"]]
    assert ts == sorted(ts)
    last_a = max(i for i, e in enumerate(merged["events"])
                 if e["ring"] == ra)
    first_b = min(i for i, e in enumerate(merged["events"])
                  if e["ring"] == rb)
    assert last_a < first_b
    # no loss, no duplication vs each ring read on its own
    for ring in (ra, rb):
        own, _ = blackbox.read_events(ring)
        assert [e["event"] for e in merged["events"]
                if e["ring"] == ring] == [e["event"] for e in own]

    # the CLI produces the same merged timeline
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.observatory",
         "--merge", ra, rb, "--json"],
        env=env, capture_output=True, text=True, timeout=120, cwd=ROOT)
    assert out.returncode == 0, out.stdout + out.stderr
    cli = json.loads(out.stdout)
    assert [e["event"] for e in cli["events"]] == \
        [e["event"] for e in merged["events"]]
    assert cli["abandoned"] == {ra: torn_bytes, rb: 0}


# ---------------------------------------------------------------------------
# cross-process skew + stitching (two live peers)
# ---------------------------------------------------------------------------

_PEER_WORKER = r'''
import json, os, sys, time
rank, eldir, dur = int(sys.argv[1]), sys.argv[2], float(sys.argv[3])
import jax
jax.config.update("jax_platforms", "cpu")
from mxnet_tpu import telemetry as tm
from mxnet_tpu import tracing as tr
tr.set_sample(1.0)
srv = tm.serve(port=0)
for i in range(4):
    with tr.start_span("train.step", attrs={"epoch": 0, "nbatch": i}):
        time.sleep(dur)
rec = {"ts": time.time(), "rank": rank, "pid": os.getpid(),
       "host": "127.0.0.1", "telemetry": "127.0.0.1:%d" % srv.port}
tmp = os.path.join(eldir, ".tmp-%d" % rank)
with open(tmp, "w") as f:
    json.dump(rec, f)
os.rename(tmp, os.path.join(eldir, "hb-g1-r%d.json" % rank))
print("READY", flush=True)
time.sleep(300)
'''


def test_skew_and_stitching_across_two_peers(tmp_path):
    """Two live peers with a 5x injected straggler delay: the
    observatory names the straggler, sets the per-rank and skew
    gauges, and stitches per-global-step cluster.step entries from
    both ranks' train.step summaries."""
    script = str(tmp_path / "peer.py")
    with open(script, "w") as f:
        f.write(_PEER_WORKER)
    eldir = str(tmp_path / "el")
    os.makedirs(eldir)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = ROOT + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    procs = []
    try:
        for rank, dur in ((0, 0.01), (1, 0.05)):
            procs.append(subprocess.Popen(
                [sys.executable, script, str(rank), eldir, str(dur)],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
        for p in procs:
            line = p.stdout.readline()
            assert "READY" in line, line

        prev_sample = tr.set_sample(1.0)
        try:
            o = ob.Observatory(elastic_dir=eldir)
            view = o.cluster_view()
        finally:
            tr.set_sample(prev_sample)

        assert view["peer_count"] == 2
        assert view["scrape_failures_total"] == 0
        # straggler named, skew ≈ 40ms
        assert view["skew"]["straggler"] == "rank1"
        assert view["skew"]["skew_s"] > 0.02
        # per-rank gauges + skew gauge materialized
        fam = tm.REGISTRY._families.get("observatory/rank_step_seconds")
        ranks = {lv[0] for lv, _c in fam.series()}
        assert {"rank0", "rank1"} <= ranks
        fam = tm.REGISTRY._families.get("observatory/step_skew_seconds")
        assert sum(c.value for _lv, c in fam.series()) > 0.02
        # stitched global steps: both ranks joined by (epoch, nbatch)
        steps = [s for s in view["steps"] if s["world"] == 2]
        assert len(steps) == 4
        for s in steps:
            assert s["straggler"] == "rank1"
            assert s["skew_ms"] > 20
            assert set(s["ranks"]) == {"rank0", "rank1"}
        # each newly stitched step became a cluster.step marker span
        roots = [t["root"] for t in tr.finished_traces(50)]
        assert roots.count("cluster.step") >= 4
    finally:
        for p in procs:
            p.kill()


# ---------------------------------------------------------------------------
# chaos acceptance: merged incident timeline + goodput over a real kill
# ---------------------------------------------------------------------------

_CHAOS_WORKER = r'''
"""One rank of a 2-process elastic fit with the goodput ledger and a
per-rank flight ring: prints its goodput report when training ends."""
import json, os, sys, time
import numpy as np
rank = int(sys.argv[1])
epochs, nb, L, dim = (int(a) for a in sys.argv[2:6])
pace_s = float(os.environ.get("ELASTIC_TEST_PACE_S", "0"))
joiner = bool(int(os.environ.get("MXNET_ELASTIC_JOIN", "0")))
import jax
jax.config.update("jax_cpu_collectives_implementation", "gloo")
if not joiner:
    os.environ["MXNET_DIST_COORDINATOR"] = os.environ["COORD"]
    os.environ["MXNET_DIST_NUM_PROCESSES"] = "2"
    os.environ["MXNET_DIST_PROCESS_ID"] = str(rank)
import mxnet_tpu as mx
from mxnet_tpu import dist_runtime
from mxnet_tpu import goodput as gp
from mxnet_tpu.module import Module
if not joiner:
    dist_runtime.acquire()

net = mx.sym.Variable("data")
net = mx.sym.FullyConnected(net, name="fc1", num_hidden=32)
net = mx.sym.Activation(net, name="relu1", act_type="relu")
net = mx.sym.FullyConnected(net, name="fcout", num_hidden=10)
net = mx.sym.SoftmaxOutput(net, name="softmax")

arg_params = None
if not joiner:
    shapes, _, _ = net.infer_shape(data=(L, dim))
    prng = np.random.RandomState(7)
    arg_params = {}
    for name, shape in zip(net.list_arguments(), shapes):
        if name not in ("data", "softmax_label"):
            arg_params[name] = mx.nd.array(
            prng.uniform(-0.1, 0.1, shape).astype(np.float32))

N = 2 * nb * L
rng = np.random.RandomState(3)
X = rng.randn(N, dim).astype(np.float32)
Y = rng.randint(0, 10, N).astype(np.float32)
it = mx.io.NDArrayIter(X, Y, batch_size=L, shuffle=True, seed=11,
                       last_batch_handle="discard", num_parts=2,
                       part_index=rank)

def _cb(param):
    if pace_s:
        time.sleep(pace_s)

mod = Module(net, context=mx.cpu())
mod.fit(it, num_epoch=epochs, optimizer="sgd",
        optimizer_params={"learning_rate": 0.05},
        arg_params=arg_params, kvstore="dist_tpu_sync",
        batch_end_callback=_cb)

print("GOODPUT_REPORT " + json.dumps(gp.report()), flush=True)
mod._kvstore.close()
dist_runtime.release()
'''

_EPOCHS, _NB, _L, _DIM = 4, 15, 4, 16


def _chaos_env(eldir):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1",
               MXNET_FUSED_STEP="1", MXNET_ELASTIC_DIR=eldir,
               MXNET_ELASTIC_HB_S="0.2", MXNET_DIST_DEAD_S="2.0",
               MXNET_STEP_TIMEOUT_S="60", ELASTIC_TEST_PACE_S="0.25")
    for v in ("MXNET_TPU_PS_URI", "MXNET_COMPILE_CACHE_DIR",
              "MXNET_FAULT_INJECT", "MXNET_ELASTIC_JOIN",
              "MXNET_FLIGHT_RECORDER", "MXNET_GOODPUT_PREV_EXIT_TS"):
        env.pop(v, None)
    env["PYTHONPATH"] = ROOT + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    env["COORD"] = "127.0.0.1:%d" % s.getsockname()[1]
    s.close()
    return env


def _spawn(script, rank, env, extra):
    argv = [sys.executable, script, str(rank), str(_EPOCHS), str(_NB),
            str(_L), str(_DIM)]
    return subprocess.Popen(argv, env=dict(env, **extra), cwd=ROOT,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)


def _goodput_report(out, who):
    for line in reversed(out.splitlines()):
        if line.startswith("GOODPUT_REPORT "):
            return json.loads(line[len("GOODPUT_REPORT "):])
    raise AssertionError("%s produced no GOODPUT_REPORT: %s"
                         % (who, out[-1500:]))


@pytest.mark.slow
def test_chaos_incident_timeline_and_goodput(tmp_path):
    """The ISSUE 20 acceptance: the PR 19 SIGKILL chaos run, observed.
    Rank 1 dies at the top of its 4th step; afterward the two rings
    merge into ONE incident timeline reading fault → member_lost →
    rescale(shrink) → rescale(grow: the rejoin) in causal order, the
    survivor's goodput ledger sums to 100% of wall with the outage
    attributed to rescale, and the relaunched joiner books its dead
    time as restart via MXNET_GOODPUT_PREV_EXIT_TS."""
    script = str(tmp_path / "worker.py")
    with open(script, "w") as f:
        f.write(_CHAOS_WORKER)
    eldir = str(tmp_path / "el")
    os.makedirs(eldir)
    ring0 = str(tmp_path / "flight-r0.bin")
    ring1 = str(tmp_path / "flight-r1.bin")
    env = _chaos_env(eldir)

    survivor = _spawn(script, 0, env, {"MXNET_FLIGHT_RECORDER": ring0})
    victim = _spawn(script, 1, env,
                    {"MXNET_FLIGHT_RECORDER": ring1,
                     "MXNET_FAULT_INJECT": "dist.member:4:crash"})
    procs = [survivor, victim]
    try:
        outv = victim.communicate(timeout=600)[0]
        death_ts = time.time()
        assert victim.returncode in (137, -9), (
            "victim should die SIGKILL-grade, got rc=%r: %s"
            % (victim.returncode, outv[-1500:]))
        deadline = time.time() + 120
        while (not [n for n in os.listdir(eldir)
                    if n.startswith("plan-g")]
               and time.time() < deadline):
            time.sleep(0.1)
        # relaunch as a joiner, carrying the supervisor's death stamp
        rejoin = _spawn(script, 1, env,
                        {"MXNET_ELASTIC_JOIN": "1",
                         "MXNET_FLIGHT_RECORDER": ring1,
                         "MXNET_GOODPUT_PREV_EXIT_TS": repr(death_ts)})
        procs.append(rejoin)
        outj = rejoin.communicate(timeout=600)[0]
        assert rejoin.returncode == 0, (
            "joiner failed rc=%r: %s" % (rejoin.returncode, outj[-1500:]))
        outs = survivor.communicate(timeout=600)[0]
        assert survivor.returncode == 0, (
            "survivor failed rc=%r: %s"
            % (survivor.returncode, outs[-1500:]))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    # -- (a) ONE merged incident timeline, causally ordered -----------
    merged = blackbox.merge_rings([ring0, ring1])
    assert sorted(merged["abandoned"]) == sorted([ring0, ring1])
    seq = [(e["event"], e.get("grow"), e["ring"]) for e in merged["events"]]
    i_fault = next(i for i, e in enumerate(merged["events"])
                   if e["event"] == "fault")
    i_lost = next(i for i, e in enumerate(merged["events"])
                  if e["event"] == "member_lost")
    rescales = [i for i, e in enumerate(merged["events"])
                if e["event"] == "rescale"]
    assert len(rescales) == 2, seq
    i_shrink, i_grow = rescales
    # the killer (victim's ring) precedes the survivor's detection,
    # which precedes the shrink plan, which precedes the rejoin grow
    assert merged["events"][i_fault]["ring"] == ring1
    assert merged["events"][i_fault]["kind"] == "crash"
    assert i_fault < i_lost < i_shrink < i_grow, seq
    shrink, grow = merged["events"][i_shrink], merged["events"][i_grow]
    assert (shrink["old_world"], shrink["world"]) == (2, 1)
    assert shrink["grow"] is False
    assert (grow["old_world"], grow["world"]) == (1, 2)
    assert grow["grow"] is True
    ts = [e["t"] for e in merged["events"]]
    assert ts == sorted(ts)

    # -- (b) goodput: sums to wall, outage attributed -----------------
    surv = _goodput_report(outs, "survivor")
    assert surv["active"] is True
    cats = {c: v["seconds"] for c, v in surv["categories"].items()}
    assert abs(sum(cats.values()) - surv["wall_s"]) \
        < max(1e-3, 1e-5 * surv["wall_s"])
    fr = {c: v["fraction"] for c, v in surv["categories"].items()}
    assert abs(sum(fr.values()) - 1.0) < 1e-3      # 100% of wall
    # the outage (detection + barrier + reinit + both rescales) landed
    # in rescale, and it is substantial vs this short run
    assert cats["rescale"] > 0.5, cats
    assert cats["step_compute"] > 0, cats
    assert surv["overrun_s"] == 0

    join = _goodput_report(outj, "joiner")
    jcats = {c: v["seconds"] for c, v in join["categories"].items()}
    # the relaunch gap (death → joiner session) was booked as restart
    assert jcats["restart"] > 0.5, jcats
    assert abs(sum(jcats.values()) - join["wall_s"]) \
        < max(1e-3, 1e-5 * join["wall_s"])
