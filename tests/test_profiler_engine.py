"""Profiler / engine / monitor / visualization tests
(reference: tests/python/unittest/test_profiler.py, test_engine.py)."""
import json
import os

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import profiler, engine, nd


def test_profiler_collects_op_events(tmp_path):
    fname = str(tmp_path / "trace.json")
    profiler.set_config(filename=fname, profile_imperative=True)
    profiler.start()
    x = mx.nd.array(np.random.rand(8, 8))
    y = nd.dot(x, x)
    y.wait_to_read()
    profiler.stop()
    path = profiler.dump()
    with open(path) as f:
        trace = json.load(f)
    names = {e["name"] for e in trace["traceEvents"]}
    assert "dot" in names
    table = profiler.dumps()
    assert "dot" in table


def test_profiler_task_counter_marker(tmp_path):
    profiler.set_config(filename=str(tmp_path / "t.json"))
    profiler.start()
    domain = profiler.Domain("custom")
    task = profiler.Task(domain, "mytask")
    task.start()
    task.stop()
    c = profiler.Counter(domain, "cnt", 0)
    c.increment(5)
    m = profiler.Marker(domain, "mark")
    m.mark()
    profiler.stop()
    path = profiler.dump(filename=str(tmp_path / "t.json"))
    trace = json.load(open(path))
    names = {e["name"] for e in trace["traceEvents"]}
    assert {"mytask", "cnt", "mark"} <= names


def test_engine_bulk_api():
    prev = engine.set_bulk_size(30)
    assert engine.set_bulk_size(prev) == 30
    with engine.bulk(8):
        x = mx.nd.ones((2, 2)) + 1
    assert float(x.sum().asscalar()) == 8


def test_naive_engine_mode():
    engine.set_engine_type("NaiveEngine")
    try:
        x = mx.nd.ones((4,)) * 3
        assert float(x.sum().asscalar()) == 12
    finally:
        engine.set_engine_type("ThreadedEnginePerDevice")


def test_monitor_on_block():
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.monitor import Monitor
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4, in_units=3))
    net.initialize()
    mon = Monitor(1, pattern=".*")
    mon.install_block(net)
    mon.tic()
    net(mx.nd.array(np.random.rand(2, 3)))
    rows = mon.toc()
    assert len(rows) >= 1


def test_print_summary(capsys):
    data = mx.sym.var("data")
    w = mx.sym.var("fc_weight")
    b = mx.sym.var("fc_bias")
    from mxnet_tpu.symbol import _internal  # noqa: F401
    out = mx.sym.FullyConnected(data, w, b, num_hidden=4, name="fc")
    from mxnet_tpu.visualization import print_summary
    print_summary(out, shape={"data": (2, 8)})
    captured = capsys.readouterr().out
    assert "fc" in captured
    assert "Total params: 36" in captured
